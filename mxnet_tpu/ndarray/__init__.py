"""``mx.nd`` namespace: NDArray + the generated operator frontends.

Reference role: python/mxnet/ndarray/ — op wrappers generated at import time
from the C-side registry (SURVEY.md §2.5).  Here the wrappers are generated
from the in-process registry populated by the ops_* modules; the same
registry also drives mx.sym, so the namespaces stay in lockstep.
"""
from __future__ import annotations

import sys as _sys

from .ndarray import (NDArray, array, from_jax, zeros, ones, empty, full,
                      arange, linspace, eye, moveaxis)
from . import register as _register_mod
from .register import (get_op, list_ops, invoke_by_name, make_frontend,
                       register_op)

# populate the registry
from . import ops_elemwise as _ops_elemwise      # noqa: F401
from . import ops_reduce as _ops_reduce          # noqa: F401
from . import ops_matrix as _ops_matrix          # noqa: F401
from . import ops_nn as _ops_nn                  # noqa: F401
from . import ops_optimizer as _ops_optimizer    # noqa: F401
from . import ops_contrib as _ops_contrib        # noqa: F401
from . import ops_linalg as _ops_linalg          # noqa: F401
from . import ops_spatial as _ops_spatial        # noqa: F401
from . import ops_quantization as _ops_quant     # noqa: F401
from . import ops_random as _ops_random          # noqa: F401
from . import ops_ctc as _ops_ctc                # noqa: F401
from . import ops_misc as _ops_misc              # noqa: F401
from . import ops_control_flow as _ops_cf        # noqa: F401
from . import ops_custom as _ops_custom          # noqa: F401
from . import ops_image as _ops_image            # noqa: F401
from . import ops_tail as _ops_tail              # noqa: F401
from . import ops_sldwin as _ops_sldwin          # noqa: F401
from . import random                              # noqa: F401
from . import contrib                             # noqa: F401
from . import image                               # noqa: F401

_this_module = _sys.modules[__name__]

for _name in list_ops():
    if not hasattr(_this_module, _name):
        setattr(_this_module, _name, make_frontend(get_op(_name)))
# aliases registered under alternative names
for _name, _op in list(_register_mod._registry.items()):
    if not hasattr(_this_module, _name):
        setattr(_this_module, _name, make_frontend(_op))


# ---------------------------------------------------------------------------
# fluent NDArray methods (reference: _set_ndarray_class + the generated
# method surface — x.sum(axis), x.take(idx), ... delegate to the op
# frontends with self as the first input)
# ---------------------------------------------------------------------------

_FLUENT_METHODS = (
    "prod", "abs", "swapaxes", "repeat", "flip", "sort", "argsort",
    "topk", "round", "floor", "ceil", "trunc", "rint", "fix", "sign",
    "tanh", "sinh", "cosh", "arcsinh", "arccosh", "arctanh", "sin",
    "cos", "tan", "arcsin", "arccos", "arctan", "degrees", "radians",
    "exp", "expm1", "log", "log1p", "log2", "log10", "sqrt", "rsqrt",
    "cbrt", "rcbrt", "square", "reciprocal", "erf", "erfinv", "gamma",
    "gammaln", "relu", "sigmoid", "softmax", "log_softmax", "softmin",
    "norm", "split", "slice_axis", "slice_like", "take", "pick", "diag",
    "nansum", "nanprod", "tile", "pad", "shape_array", "size_array",
    "broadcast_like", "reshape_like", "one_hot", "clip", "zeros_like",
    "ones_like")


def _attach_fluent(name):
    fn = getattr(_this_module, name)

    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    method.__name__ = name
    method.__doc__ = f"Fluent form of ``mx.nd.{name}`` (self as data)."
    return method


for _m in _FLUENT_METHODS:
    if not hasattr(NDArray, _m) and hasattr(_this_module, _m):
        setattr(NDArray, _m, _attach_fluent(_m))


# core methods NDArray implements by hand (views/host sync) but Symbol
# gets from the op registry — part of the same lockstep surface
_CORE_SYM_METHODS = (
    "sum", "mean", "max", "min", "argmax", "argmin", "reshape",
    "transpose", "dot", "broadcast_to", "flatten", "expand_dims",
    "squeeze", "slice")


# the same generated surface attaches to Symbol (reference keeps the two
# frontends in lockstep; hybridize would otherwise AttributeError on any
# fluent call inside hybrid_forward)
def _attach_symbol_fluent():
    from ..symbol.symbol import Symbol
    from ..symbol.register import _make_sym_frontend
    for m in _FLUENT_METHODS + _CORE_SYM_METHODS:
        if not hasattr(Symbol, m) and hasattr(_this_module, m):
            fe = _make_sym_frontend(
                getattr(_this_module, m).__name__)

            def method(self, *args, _fe=fe, **kwargs):
                return _fe(self, *args, **kwargs)
            method.__name__ = m
            setattr(Symbol, m, method)


# ---------------------------------------------------------------------------
# frontends that need special handling
# ---------------------------------------------------------------------------

def split_v2(ary, indices_or_sections=None, axis=0, squeeze_axis=False,
             **kwargs):
    """Reference-parity frontend (python/mxnet/ndarray/ndarray.py split_v2):
    positional ``indices_or_sections`` — an int selects equal sections, a
    tuple gives split indices (a leading 0 per the raw-op segment-start
    convention is accepted).  ``sections=``/``indices=`` kwargs also work."""
    if indices_or_sections is not None:
        import numpy as _np
        if isinstance(indices_or_sections, (int, _np.integer)):
            kwargs["sections"] = int(indices_or_sections)
        else:
            kwargs["indices"] = tuple(indices_or_sections)
    out = kwargs.pop("out", None)
    kwargs.pop("name", None)
    kwargs.setdefault("axis", axis)
    kwargs.setdefault("squeeze_axis", squeeze_axis)
    return invoke_by_name("split_v2", [ary], kwargs, out=out)


def _set_value(src=0.0, out=None, **kwargs):
    """Reference calling convention (c_api 1.x): a pure out= fill —
    ``_set_value(2.5, out=arr)`` with NO tensor inputs; the target
    supplies the shape."""
    from ..base import MXNetError
    if out is None:
        raise MXNetError("_set_value requires out=")
    return invoke_by_name("_set_value", [out], {"src": float(src)},
                          out=out)


def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=None, **kwargs):
    """Dropout; active only under autograd.train_mode (or mode='always'),
    matching the reference op's behavior."""
    from .. import autograd as _ag
    if mode != "always" and not _ag.is_training():
        return identity(data)                                 # noqa: F821
    # this frontend has already decided the op is ACTIVE, so it invokes
    # with mode='always' — which also tells node_takes_key to append the
    # PRNG key (the training-gated form exists only as a graph node)
    return invoke_by_name("Dropout", [data],
                          {"p": p, "axes": tuple(axes), "mode": "always"})


dropout = Dropout


# random_* flat aliases of the random submodule (reference API parity)
random_uniform = random.uniform
random_normal = random.normal
random_randint = random.randint
random_gamma = random.gamma
random_exponential = random.exponential
random_poisson = random.poisson
random_negative_binomial = random.negative_binomial
sample_multinomial = random.multinomial
shuffle = random.shuffle
# sample_* per-parameter-element draws (multisample_op.cc frontends)
sample_uniform = random.sample_uniform
sample_normal = random.sample_normal
sample_gamma = random.sample_gamma
sample_exponential = random.sample_exponential
sample_poisson = random.sample_poisson
sample_negative_binomial = random.sample_negative_binomial
sample_generalized_negative_binomial = \
    random.sample_generalized_negative_binomial
# *_like draws follow the input's shape/dtype/ctx
uniform_like = random.uniform_like
normal_like = random.normal_like
gamma_like = random.gamma_like
exponential_like = random.exponential_like
poisson_like = random.poisson_like
randint_like = random.randint_like


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None,
           **kwargs):
    return random.normal(loc=loc, scale=scale, shape=shape, dtype=dtype,
                         ctx=ctx, out=out)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None,
            **kwargs):
    return random.uniform(low=low, high=high, shape=shape, dtype=dtype,
                          ctx=ctx, out=out)


def waitall():
    from ..engine import wait_all
    wait_all()


def save(fname, data):
    from .utils import save as _save
    _save(fname, data)


def load(fname):
    from .utils import load as _load
    return _load(fname)
