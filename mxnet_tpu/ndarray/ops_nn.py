"""Neural-network operators: FC, conv, pooling, normalization, softmax, etc.

Reference parity: src/operator/nn/ (SURVEY.md §2.2) — each reference op had a
cuDNN fast path; here the fast path IS the op: XLA lowers dot/conv straight
onto the MXU, elementwise tails fuse into the matmul, and layouts are chosen
by the compiler.  MXNet conventions preserved: NCHW data layout, OIHW weight
layout, BatchNorm defaults (eps=1e-3, momentum=0.9, fix_gamma=True, channel
axis 1), pooling conventions 'valid'/'full', FullyConnected's flatten rule,
SoftmaxOutput's fused-gradient semantics.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    # ---- FullyConnected --------------------------------------------------
    def fc_maker(num_hidden=None, no_bias=False, flatten=True):
        def fn(x, w, *maybe_b):
            if flatten and x.ndim > 2:
                x = x.reshape(x.shape[0], -1)
            y = jnp.matmul(x, w.T)   # MXU path; weight is (num_hidden, in)
            if not no_bias:
                y = y + maybe_b[0]
            return y
        return fn
    register_op("FullyConnected", fc_maker, aliases=("fully_connected",))

    # ---- Convolution -----------------------------------------------------
    def _spatial_dims(kernel):
        return len(kernel)

    from ..base import is_channels_last

    def _conv_dn(nd, layout=None):
        # channels-last data layouts (reference conv layout param:
        # NWC/NHWC/NDHWC) — the TPU-native tiling.  Weights stay OIHW in
        # EVERY layout (lax dimension_numbers maps them; XLA's layout
        # assignment makes it free), so initializer fan math and
        # checkpoints are layout-portable — a deliberate deviation from
        # the reference's weight-follows-layout convention.
        if is_channels_last(layout, nd):
            if nd == 1:
                return ("NWC", "OIW", "NWC")
            if nd == 2:
                return ("NHWC", "OIHW", "NHWC")
            return ("NDHWC", "OIDHW", "NDHWC")
        if nd == 1:
            return ("NCH", "OIH", "NCH")
        if nd == 2:
            return ("NCHW", "OIHW", "NCHW")
        return ("NCDHW", "OIDHW", "NCDHW")

    def conv_maker(kernel=(), stride=None, dilate=None, pad=None,
                   num_filter=None, num_group=1, no_bias=False,
                   layout=None, workspace=None, cudnn_tune=None,
                   cudnn_off=None):
        nd = _spatial_dims(kernel)
        stride_ = tuple(stride) if stride else (1,) * nd
        dilate_ = tuple(dilate) if dilate else (1,) * nd
        pad_ = tuple(pad) if pad else (0,) * nd
        channels_last = is_channels_last(layout, nd)
        bshape = ((1,) + (1,) * nd + (-1,)) if channels_last \
            else ((1, -1) + (1,) * nd)

        def fn(x, w, *maybe_b):
            y = lax.conv_general_dilated(
                x, w, window_strides=stride_,
                padding=[(p, p) for p in pad_],
                rhs_dilation=dilate_,
                feature_group_count=num_group,
                dimension_numbers=_conv_dn(nd, layout))
            if not no_bias:
                b = maybe_b[0]
                y = y + b.reshape(bshape)
            return y
        return fn
    register_op("Convolution", conv_maker, aliases=("convolution",))
    # legacy 0.x surface (src/operator/convolution_v1.cc): same math, kept
    # as a distinct op name for checkpoint/JSON compatibility
    register_op("Convolution_v1", conv_maker)

    def deconv_maker(kernel=(), stride=None, dilate=None, pad=None,
                     adj=None, target_shape=None, num_filter=None,
                     num_group=1, no_bias=True, layout=None, workspace=None,
                     cudnn_tune=None, cudnn_off=None):
        nd = _spatial_dims(kernel)
        stride_ = tuple(stride) if stride else (1,) * nd
        pad_ = tuple(pad) if pad else (0,) * nd
        adj_ = tuple(adj) if adj else (0,) * nd

        def fn(x, w, *maybe_b):
            # transposed conv = dilated input conv with flipped kernel;
            # out = (in-1)*s - 2p + k + adj  (MXNet deconv arithmetic)
            k = kernel
            w_t = jnp.swapaxes(w, 0, 1)            # IO... -> OI...
            w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
            padding = [(k[i] - 1 - pad_[i], k[i] - 1 - pad_[i] + adj_[i])
                       for i in range(nd)]
            y = lax.conv_general_dilated(
                x, w_t, window_strides=(1,) * nd,
                padding=padding, lhs_dilation=stride_,
                feature_group_count=num_group,
                dimension_numbers=_conv_dn(nd))
            if not no_bias and maybe_b:
                y = y + maybe_b[0].reshape((1, -1) + (1,) * nd)
            return y
        return fn
    register_op("Deconvolution", deconv_maker, aliases=("deconvolution",))

    # ---- Pooling ---------------------------------------------------------
    def pool_maker(kernel=(), pool_type="max", stride=None, pad=None,
                   global_pool=False, pooling_convention="valid",
                   count_include_pad=True, cudnn_off=None, p_value=2,
                   layout=None):
        nd = len(kernel) if kernel else 2
        channels_last = is_channels_last(layout, nd if kernel else None)

        def fn(x):
            sdims = x.ndim - 2
            sp0 = 1 if channels_last else 2   # first spatial dim index
            if global_pool:
                axes = tuple(range(sp0, sp0 + sdims))
                if pool_type == "max":
                    r = jnp.max(x, axis=axes, keepdims=True)
                elif pool_type == "sum":
                    r = jnp.sum(x, axis=axes, keepdims=True)
                else:
                    r = jnp.mean(x, axis=axes, keepdims=True)
                return r
            k = tuple(kernel)
            s = tuple(stride) if stride else (1,) * sdims
            p = tuple(pad) if pad else (0,) * sdims
            pads = []
            for i in range(sdims):
                lo = hi = p[i]
                if pooling_convention == "full":
                    # ceil convention: pad extra on the high side so the last
                    # partial window is included (reference 'full' pooling)
                    in_sz = x.shape[sp0 + i] + 2 * p[i]
                    out_full = -(-(in_sz - k[i]) // s[i]) + 1
                    hi += max(0, (out_full - 1) * s[i] + k[i] - in_sz)
                pads.append((lo, hi))
            if channels_last:
                window = (1,) + k + (1,)
                strides = (1,) + s + (1,)
                padcfg = [(0, 0)] + pads + [(0, 0)]
            else:
                window = (1, 1) + k
                strides = (1, 1) + s
                padcfg = [(0, 0), (0, 0)] + pads
            if pool_type == "max":
                # init must be a STATIC scalar: a traced init value defeats
                # jax's reduce_window_max autodiff pattern-match
                init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                    else int(jnp.iinfo(x.dtype).min)
                return lax.reduce_window(x, init, lax.max, window, strides,
                                         padcfg)
            zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
            ssum = lax.reduce_window(x, zero, lax.add, window, strides,
                                     padcfg)
            if pool_type == "sum":
                return ssum
            if pool_type == "avg":
                if count_include_pad:
                    denom = 1.0
                    for ki in k:
                        denom *= ki
                    return ssum / jnp.asarray(denom, x.dtype)
                ones = jnp.ones(x.shape, x.dtype)
                cnt = lax.reduce_window(ones, zero, lax.add, window,
                                        strides, padcfg)
                return ssum / cnt
            if pool_type == "lp":
                pw = lax.reduce_window(jnp.abs(x) ** p_value,
                                       jnp.asarray(0, x.dtype), lax.add,
                                       window, strides, padcfg)
                return pw ** (1.0 / p_value)
            raise ValueError(pool_type)
        return fn
    register_op("Pooling", pool_maker, aliases=("pooling",))
    register_op("Pooling_v1", pool_maker)       # legacy pooling_v1.cc name

    # ---- activations -----------------------------------------------------
    def act_maker(act_type="relu"):
        table = {
            "relu": lambda x: jnp.maximum(x, 0),
            "sigmoid": jax.nn.sigmoid,
            "tanh": jnp.tanh,
            "softrelu": jax.nn.softplus,
            "softsign": lambda x: x / (1 + jnp.abs(x)),
        }
        return table[act_type]
    register_op("Activation", act_maker, aliases=("activation",))

    def leaky_maker(act_type="leaky", slope=0.25, lower_bound=0.125,
                    upper_bound=0.334):
        def fn(x, *maybe_gamma):
            if act_type == "leaky":
                return jnp.where(x >= 0, x, slope * x)
            if act_type == "prelu":
                g = maybe_gamma[0]
                g = g.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else g
                return jnp.where(x >= 0, x, g * x)
            if act_type == "elu":
                return jnp.where(x >= 0, x, slope * (jnp.exp(x) - 1))
            if act_type == "selu":
                alpha, scale = 1.6732632423543772, 1.0507009873554805
                return scale * jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1))
            if act_type == "gelu":
                return jax.nn.gelu(x, approximate=False)
            if act_type == "rrelu":
                mid = (lower_bound + upper_bound) / 2.0
                return jnp.where(x >= 0, x, mid * x)
            raise ValueError(act_type)
        return fn
    register_op("LeakyReLU", leaky_maker, aliases=("leaky_relu",))

    # ---- softmax family --------------------------------------------------
    def softmax_maker(axis=-1, temperature=None, length=None, dtype=None,
                      use_length=False):
        def fn(x, *maybe_len):
            xs = x / temperature if temperature else x
            if use_length and maybe_len:
                L = maybe_len[0].astype(jnp.int32)
                pos = jnp.arange(x.shape[axis])
                shape = [1] * x.ndim
                shape[axis] = x.shape[axis]
                mask = pos.reshape(shape) < L.reshape(
                    L.shape + (1,) * (x.ndim - L.ndim))
                xs = jnp.where(mask, xs, -jnp.inf)
                out = jax.nn.softmax(xs, axis=axis)
                return jnp.where(mask, out, 0.0)
            return jax.nn.softmax(xs, axis=axis)
        return fn
    register_op("softmax", softmax_maker)

    def log_softmax_maker(axis=-1, temperature=None, dtype=None,
                          use_length=False):
        def fn(x):
            xs = x / temperature if temperature else x
            return jax.nn.log_softmax(xs, axis=axis)
        return fn
    register_op("log_softmax", log_softmax_maker)

    def softmin_maker(axis=-1, temperature=None, dtype=None):
        def fn(x):
            xs = x / temperature if temperature else x
            return jax.nn.softmax(-xs, axis=axis)
        return fn
    register_op("softmin", softmin_maker)

    # SoftmaxOutput: forward=softmax over axis 1; the *gradient of data* is
    # (p - onehot(label))·grad_scale regardless of head gradient — the
    # reference's fused loss-layer contract (src/operator/softmax_output.cc).
    def softmax_output_maker(grad_scale=1.0, ignore_label=-1,
                             multi_output=False, use_ignore=False,
                             preserve_shape=False, normalization="null",
                             out_grad=False, smooth_alpha=0.0):
        @jax.custom_vjp
        def fwd(x, label):
            return jax.nn.softmax(x, axis=1)

        def fwd_fwd(x, label):
            p = fwd(x, label)
            return p, (p, label)

        def fwd_bwd(res, g):
            p, label = res
            lab = label.astype(jnp.int32)
            oh = jax.nn.one_hot(lab, p.shape[1], dtype=p.dtype)
            if p.ndim > 2:
                # (N, C, d...) with label (N, d...): move class axis last
                perm = (0,) + tuple(range(2, p.ndim)) + (1,)
                pm = jnp.transpose(p, perm)
                grad = pm - oh
                if use_ignore:
                    mask = (lab != ignore_label)[..., None]
                    grad = jnp.where(mask, grad, 0.0)
                inv = tuple(_np.argsort(perm))
                grad = jnp.transpose(grad, inv)
            else:
                grad = p - oh
                if use_ignore:
                    grad = jnp.where((lab != ignore_label)[:, None], grad, 0.0)
            scale = grad_scale
            if normalization == "batch":
                scale = scale / p.shape[0]
            elif normalization == "valid" and use_ignore:
                nvalid = jnp.maximum(jnp.sum(lab != ignore_label), 1)
                grad = grad / nvalid.astype(grad.dtype)
            return (grad * scale, jnp.zeros_like(label))

        fwd.defvjp(fwd_fwd, fwd_bwd)
        return fwd
    register_op("SoftmaxOutput", softmax_output_maker,
                aliases=("softmax_output", "SoftmaxActivation_out"))

    # ---- normalization ---------------------------------------------------
    def batchnorm_maker(eps=1e-3, momentum=0.9, fix_gamma=True,
                        use_global_stats=False, output_mean_var=False,
                        axis=1, cudnn_off=None, _training=True):
        def fn(x, gamma, beta, moving_mean, moving_var):
            ax = axis % x.ndim
            reduce_axes = tuple(i for i in range(x.ndim) if i != ax)
            bshape = [1] * x.ndim
            bshape[ax] = x.shape[ax]
            g = jnp.ones_like(gamma) if fix_gamma else gamma
            if _training and not use_global_stats:
                xf = x.astype(jnp.float32)
                mean = jnp.mean(xf, axis=reduce_axes)
                var = jnp.mean(jnp.square(xf), axis=reduce_axes) - \
                    jnp.square(mean)
                new_mean = momentum * moving_mean + (1 - momentum) * mean
                new_var = momentum * moving_var + (1 - momentum) * var
            else:
                mean, var = moving_mean, moving_var
                new_mean, new_var = moving_mean, moving_var
            inv = lax.rsqrt(var.astype(x.dtype) + jnp.asarray(eps, x.dtype))
            out = (x - mean.astype(x.dtype).reshape(bshape)) * \
                (inv * g.astype(x.dtype)).reshape(bshape) + \
                beta.astype(x.dtype).reshape(bshape)
            return (out, new_mean, new_var)
        return fn
    register_op("BatchNorm", batchnorm_maker, aliases=("batch_norm",))

    def batchnorm_v1_maker(eps=1e-3, momentum=0.9, fix_gamma=True,
                           use_global_stats=False, output_mean_var=False,
                           _training=True):
        # reference src/operator/batch_norm_v1.cc: the pre-0.12 op — NCHW
        # only (channel axis 1), no cudnn/axis options; kept because
        # legacy symbol JSON files reference it by name
        return batchnorm_maker(eps=eps, momentum=momentum,
                               fix_gamma=fix_gamma,
                               use_global_stats=use_global_stats,
                               axis=1, _training=_training)
    register_op("BatchNorm_v1", batchnorm_v1_maker,
                ref="src/operator/batch_norm_v1.cc")


    def layernorm_maker(axis=-1, eps=1e-5, output_mean_var=False):
        def fn(x, gamma, beta):
            mean = jnp.mean(x, axis=axis, keepdims=True)
            var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
            inv = lax.rsqrt(var + jnp.asarray(eps, x.dtype))
            shape = [1] * x.ndim
            shape[axis % x.ndim] = x.shape[axis % x.ndim]
            out = (x - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
            if output_mean_var:
                return (out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis))
            return out
        return fn
    register_op("LayerNorm", layernorm_maker, aliases=("layer_norm",))

    def groupnorm_maker(num_groups=1, eps=1e-5, output_mean_var=False):
        def fn(x, gamma, beta):
            # (N, C, ...) -> stats per (N, group); gamma/beta are
            # PER-GROUP, shape (num_groups,) — the reference convention
            # (src/operator/nn/group_norm.cc), unlike torch's
            # per-channel affine
            n, c = x.shape[0], x.shape[1]
            g = int(num_groups)
            rest = x.shape[2:]
            xg = x.reshape((n, g, c // g) + rest)
            axes = tuple(range(2, xg.ndim))
            mean = jnp.mean(xg, axis=axes, keepdims=True)
            var = jnp.mean(jnp.square(xg - mean), axis=axes,
                           keepdims=True)
            out = (xg - mean) * lax.rsqrt(var + jnp.asarray(eps, x.dtype))
            bshape = (1, g, 1) + (1,) * len(rest)
            out = out * gamma.reshape(bshape) + beta.reshape(bshape)
            out = out.reshape(x.shape)
            if output_mean_var:
                return (out, mean.reshape(n, g), var.reshape(n, g))
            return out
        return fn
    register_op("GroupNorm", groupnorm_maker, aliases=("group_norm",))

    def lrn_maker(alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
        half = int(nsize) // 2

        def fn(x):
            # cross-channel local response normalization (reference:
            # src/operator/nn/lrn.cc): square, box-sum over the channel
            # window, scale.  Asymmetric pad keeps the channel dim for
            # even nsize too.
            sq = jnp.square(x)
            pad = [(0, 0)] * x.ndim
            pad[1] = (half, int(nsize) - 1 - half)
            acc = lax.reduce_window(
                sq, jnp.asarray(0, x.dtype), lax.add,
                (1, int(nsize)) + (1,) * (x.ndim - 2),
                (1,) * x.ndim,
                pad)
            # reference normalizes alpha by the window size (cuDNN
            # convention, same as torch LocalResponseNorm)
            return x / jnp.power(knorm + (alpha / nsize) * acc, beta)
        return fn
    register_op("LRN", lrn_maker, aliases=("lrn",))

    def instancenorm_maker(eps=1e-3):
        def fn(x, gamma, beta):
            axes = tuple(range(2, x.ndim))
            mean = jnp.mean(x, axis=axes, keepdims=True)
            var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
            inv = lax.rsqrt(var + jnp.asarray(eps, x.dtype))
            shape = (1, -1) + (1,) * (x.ndim - 2)
            return (x - mean) * inv * gamma.reshape(shape) + \
                beta.reshape(shape)
        return fn
    register_op("InstanceNorm", instancenorm_maker, aliases=("instance_norm",))

    def l2norm_maker(eps=1e-10, mode="instance"):
        def fn(x):
            if mode == "instance":
                axes = tuple(range(1, x.ndim))
                keep = True
            elif mode == "channel":
                axes = (1,)
                keep = True
            else:  # spatial
                axes = tuple(range(2, x.ndim))
                keep = True
            norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=keep)
                            + eps)
            return x / norm
        return fn
    register_op("L2Normalization", l2norm_maker, aliases=("l2_normalization",))

    # ---- dropout (key passed as input; applied only when layer says so) --
    def dropout_maker(p=0.5, mode="training", axes=(), cudnn_off=None):
        def fn(x, key):
            if p <= 0.0:
                return x
            kp = 1.0 - p
            shape = list(x.shape)
            for a in axes:
                shape[a] = 1
            mask = jax.random.bernoulli(key, kp, tuple(shape))
            return jnp.where(mask, x / kp, 0.0).astype(x.dtype)
        return fn
    register_op("Dropout", dropout_maker, aliases=("dropout",),
                needs_rng=True)

    # ---- resize / upsample ----------------------------------------------
    def upsampling_maker(scale=1, num_filter=0, sample_type="nearest",
                         multi_input_mode="concat", num_args=1,
                         workspace=None):
        def fn(*xs):
            x = xs[0]
            if sample_type == "nearest":
                y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
                return y
            b, c, h, w = x.shape
            return jax.image.resize(x, (b, c, h * scale, w * scale),
                                    method="linear")
        return fn
    register_op("UpSampling", upsampling_maker, aliases=("upsampling",))

    def bilinear_resize_maker(height=None, width=None, scale_height=None,
                              scale_width=None, mode="size",
                              align_corners=True):
        # align_corners=True is the reference kernel's coordinate mapping
        # (bilinear_resize.cc: src = dst*(in-1)/(out-1)); jax.image.resize
        # only offers half-pixel centers, so that path is hand-gathered
        def fn(x):
            b, c, h, w = x.shape
            nh = height if height else int(h * scale_height)
            nw = width if width else int(w * scale_width)
            if not align_corners:
                return jax.image.resize(x, (b, c, nh, nw), method="linear")
            ys = (jnp.linspace(0.0, h - 1.0, nh) if nh > 1
                  else jnp.zeros((1,)))
            xs = (jnp.linspace(0.0, w - 1.0, nw) if nw > 1
                  else jnp.zeros((1,)))
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, h - 1)
            x1 = jnp.minimum(x0 + 1, w - 1)
            wy = (ys - y0).astype(x.dtype)[None, None, :, None]
            wx = (xs - x0).astype(x.dtype)[None, None, None, :]
            rows0, rows1 = jnp.take(x, y0, axis=2), jnp.take(x, y1, axis=2)
            r0 = jnp.take(rows0, x0, axis=3) * (1 - wx) \
                + jnp.take(rows0, x1, axis=3) * wx
            r1 = jnp.take(rows1, x0, axis=3) * (1 - wx) \
                + jnp.take(rows1, x1, axis=3) * wx
            return r0 * (1 - wy) + r1 * wy
        return fn
    register_op("BilinearResize2D", bilinear_resize_maker,
                aliases=("_contrib_BilinearResize2D",))

    # ---- RNN (fused multi-layer LSTM/GRU/tanh/relu over lax.scan) -------
    # Reference: src/operator/rnn.cc (cuDNN-fused); the TPU-native form is a
    # scan whose per-step cell is one fused matmul pair on the MXU.
    def rnn_maker(state_size=0, num_layers=1, mode="lstm",
                  bidirectional=False, p=0.0, state_outputs=False,
                  projection_size=None, use_sequence_length=False,
                  lstm_state_clip_min=None, lstm_state_clip_max=None,
                  lstm_state_clip_nan=False):
        ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
        ndir = 2 if bidirectional else 1

        def cell_step(mode_, W_x, W_h, b_x, b_h, x_t, h, c):
            gx = x_t @ W_x.T + b_x
            gh = h @ W_h.T + b_h
            if mode_ == "lstm":
                gates = gx + gh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return h_new, c_new
            if mode_ == "gru":
                # cuDNN GRU formulation: r,z from summed gates; n uses r*(Whn h)
                rx, zx, nx = jnp.split(gx, 3, axis=-1)
                rh, zh, nh = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(rx + rh)
                z = jax.nn.sigmoid(zx + zh)
                n = jnp.tanh(nx + r * nh)
                h_new = (1 - z) * n + z * h
                return h_new, c
            act = jnp.tanh if mode_ == "rnn_tanh" else (
                lambda v: jnp.maximum(v, 0))
            h_new = act(gx + gh)
            return h_new, c

        def fn(data, params, state, *maybe_cell):
            # data: (T, N, I); params: flat packed like cuDNN; state: (L*D,N,H)
            T, N, I = data.shape
            H = state_size
            state_c = maybe_cell[0] if mode == "lstm" else None
            offset = 0

            def take(n):
                nonlocal offset
                v = lax.dynamic_slice(params, (offset,), (n,))
                offset += n
                return v

            outs = data
            h_states, c_states = [], []
            layer_in_size = I
            for layer in range(num_layers):
                dir_outs = []
                for d in range(ndir):
                    li = layer * ndir + d
                    Wx = take(ngates * H * layer_in_size).reshape(
                        ngates * H, layer_in_size)
                    Wh = take(ngates * H * H).reshape(ngates * H, H)
                    bx = take(ngates * H)
                    bh = take(ngates * H)
                    h0 = state[li]
                    c0 = state_c[li] if state_c is not None else \
                        jnp.zeros_like(h0)
                    seq = outs if d == 0 else jnp.flip(outs, axis=0)

                    def step(carry, x_t, Wx=Wx, Wh=Wh, bx=bx, bh=bh):
                        h, c = carry
                        h2, c2 = cell_step(mode, Wx, Wh, bx, bh, x_t, h, c)
                        return (h2, c2), h2

                    (hT, cT), ys = lax.scan(step, (h0, c0), seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    dir_outs.append(ys)
                    h_states.append(hT)
                    c_states.append(cT)
                outs = dir_outs[0] if ndir == 1 else jnp.concatenate(
                    dir_outs, axis=-1)
                layer_in_size = H * ndir
            hN = jnp.stack(h_states)
            if mode == "lstm":
                return (outs, hN, jnp.stack(c_states))
            return (outs, hN)
        return fn
    register_op("RNN", rnn_maker, aliases=("rnn",))

    # cuDNN-compatible packed param size helper used by gluon.rnn
    def rnn_param_size(mode, num_layers, input_size, hidden_size,
                      bidirectional=False):
        ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
        ndir = 2 if bidirectional else 1
        total = 0
        lin = input_size
        for _ in range(num_layers):
            for _ in range(ndir):
                total += ngates * hidden_size * lin
                total += ngates * hidden_size * hidden_size
                total += 2 * ngates * hidden_size
            lin = hidden_size * ndir
        return total
    globals()["rnn_param_size"] = rnn_param_size


_register()
