"""Shape manipulation, indexing, linear algebra, sequence ops.

Reference parity: src/operator/tensor/{matrix_op.cc, dot.cc, indexing_op.cc,
init_op.cc, control_flow_op.cc}, src/operator/sequence_*.cc, swapaxis.cc,
pad.cc (SURVEY.md §2.2).  MXNet conventions preserved: ``dot`` contracts the
last axis of lhs with the first of rhs (not matmul broadcasting); ``slice``
accepts None entries for "from the edge"; Embedding/take indices may arrive
as float arrays and are truncated to int.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op, simple_op
from .ndarray import _thaw_key


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    # ---- contraction -----------------------------------------------------
    def dot_maker(transpose_a=False, transpose_b=False):
        def fn(a, b):
            if transpose_a:
                a = jnp.transpose(a)
            if transpose_b:
                b = jnp.transpose(b)
            return jnp.tensordot(a, b, axes=1)
        return fn
    register_op("dot", dot_maker)

    def batch_dot_maker(transpose_a=False, transpose_b=False):
        def fn(a, b):
            if transpose_a:
                a = jnp.swapaxes(a, -1, -2)
            if transpose_b:
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b)
        return fn
    register_op("batch_dot", batch_dot_maker, aliases=("linalg_gemm2_batched",))

    def linalg_gemm2_maker(transpose_a=False, transpose_b=False, alpha=1.0):
        def fn(a, b):
            if transpose_a:
                a = jnp.swapaxes(a, -1, -2)
            if transpose_b:
                b = jnp.swapaxes(b, -1, -2)
            return alpha * jnp.matmul(a, b)
        return fn
    register_op("linalg_gemm2", linalg_gemm2_maker)

    # ---- shape ops -------------------------------------------------------
    def reshape_maker(shape=None, reverse=False):
        from ..base import resolve_reshape_spec

        def fn(x):
            return jnp.reshape(x, resolve_reshape_spec(x.shape, shape,
                                                       reverse))
        return fn
    register_op("reshape", reshape_maker, aliases=("Reshape",))

    def transpose_maker(axes=None):
        def fn(x):
            return jnp.transpose(x, axes if axes else None)
        return fn
    register_op("transpose", transpose_maker)

    def expand_dims_maker(axis=0):
        def fn(x):
            return jnp.expand_dims(x, axis)
        return fn
    register_op("expand_dims", expand_dims_maker)

    def squeeze_maker(axis=None):
        def fn(x):
            return jnp.squeeze(x, axis)
        return fn
    register_op("squeeze", squeeze_maker)

    def flatten_maker():
        def fn(x):
            return jnp.reshape(x, (x.shape[0], -1))
        return fn
    register_op("flatten", flatten_maker, aliases=("Flatten",))

    def swapaxes_maker(dim1=0, dim2=0):
        def fn(x):
            return jnp.swapaxes(x, dim1, dim2)
        return fn
    register_op("swapaxes", swapaxes_maker, aliases=("SwapAxis",))

    def cast_maker(dtype="float32"):
        from ..base import dtype_np

        def fn(x):
            return x.astype(dtype_np(dtype))
        return fn
    register_op("cast", cast_maker, aliases=("Cast",))

    def amp_cast_maker(dtype="float32"):
        from ..base import dtype_np

        def fn(x):
            return x.astype(dtype_np(dtype))
        return fn
    register_op("amp_cast", amp_cast_maker)

    def amp_multicast_maker(num_outputs=1):
        def fn(*xs):
            widest = jnp.result_type(*xs)
            return tuple(x.astype(widest) for x in xs)
        return fn
    register_op("amp_multicast", amp_multicast_maker,
                doc="cast all inputs to their widest dtype (reference: "
                    "src/operator/tensor/amp_cast.cc amp_multicast)")

    simple_op("zeros_like", jnp.zeros_like, differentiable=False)
    simple_op("ones_like", jnp.ones_like, differentiable=False)
    simple_op("shape_array",
              lambda x: jnp.asarray(_np.asarray(x.shape), jnp.int32),
              differentiable=False, use_jit=False)
    simple_op("size_array",
              lambda x: jnp.asarray([x.size], jnp.int32),
              differentiable=False, use_jit=False)

    # ---- concat / split / stack -----------------------------------------
    def concat_maker(dim=1, num_args=None):
        def fn(*xs):
            return jnp.concatenate(xs, axis=dim)
        return fn
    register_op("concat", concat_maker, aliases=("Concat",))

    def stack_maker(axis=0, num_args=None):
        def fn(*xs):
            return jnp.stack(xs, axis=axis)
        return fn
    register_op("stack", stack_maker)

    def split_maker(num_outputs=1, axis=1, squeeze_axis=False):
        def fn(x):
            parts = jnp.split(x, num_outputs, axis=axis)
            if squeeze_axis:
                parts = [jnp.squeeze(p, axis=axis) for p in parts]
            return tuple(parts) if num_outputs > 1 else parts[0]
        return fn
    register_op("split", split_maker, aliases=("SliceChannel",))

    # ---- slicing ---------------------------------------------------------
    def slice_maker(begin=(), end=(), step=None):
        def fn(x):
            idx = []
            stp = step if step is not None else (None,) * len(begin)
            for b, e, s in zip(begin, end, stp):
                idx.append(slice(b, e, s))
            return x[tuple(idx)]
        return fn
    register_op("slice", slice_maker)

    def slice_axis_maker(axis=0, begin=0, end=None):
        def fn(x):
            idx = [slice(None)] * x.ndim
            idx[axis % x.ndim] = slice(begin, end)
            return x[tuple(idx)]
        return fn
    register_op("slice_axis", slice_axis_maker)

    def slice_like_maker(axes=()):
        def fn(x, like):
            idx = [slice(None)] * x.ndim
            axes_ = axes if axes else range(x.ndim)
            for a in axes_:
                idx[a % x.ndim] = slice(0, like.shape[a % x.ndim])
            return x[tuple(idx)]
        return fn
    register_op("slice_like", slice_like_maker)

    def basic_index_maker(key=None):
        def fn(x):
            return x[_thaw_key(key)]
        return fn
    register_op("_basic_index", basic_index_maker)

    def adv_index_maker():
        def fn(x, idx):
            return x[idx.astype(jnp.int32)] if jnp.issubdtype(
                idx.dtype, jnp.floating) else x[idx]
        return fn
    register_op("_advanced_index", adv_index_maker)

    # ---- indexing --------------------------------------------------------
    def take_maker(axis=0, mode="clip"):
        def fn(a, indices):
            idx = indices.astype(jnp.int32)
            return jnp.take(a, idx, axis=axis, mode=mode)
        return fn
    register_op("take", take_maker)

    def embedding_maker(input_dim=None, output_dim=None, dtype="float32",
                        sparse_grad=False):
        def fn(data, weight):
            return jnp.take(weight, data.astype(jnp.int32), axis=0,
                            mode="clip")
        return fn
    register_op("Embedding", embedding_maker, aliases=("embedding",))

    def gather_nd_maker():
        def fn(data, indices):
            idx = indices.astype(jnp.int32)
            m = idx.shape[0]
            return data[tuple(idx[i] for i in range(m))]
        return fn
    register_op("gather_nd", gather_nd_maker)

    def scatter_nd_maker(shape=None):
        def fn(data, indices):
            idx = indices.astype(jnp.int32)
            m = idx.shape[0]
            out = jnp.zeros(shape, data.dtype)
            return out.at[tuple(idx[i] for i in range(m))].set(data)
        return fn
    register_op("scatter_nd", scatter_nd_maker)

    def one_hot_maker(depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
        def fn(indices):
            oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
            return (oh * (on_value - off_value) + off_value).astype(
                jnp.dtype(dtype))
        return fn
    register_op("one_hot", one_hot_maker, differentiable=False)

    simple_op("where", lambda c, x, y: jnp.where(c != 0, x, y))

    def pick_maker(axis=-1, keepdims=False, mode="clip"):
        def fn(data, index):
            idx = index.astype(jnp.int32)
            ax = axis % data.ndim
            idxe = jnp.expand_dims(idx, ax)
            r = jnp.take_along_axis(data, idxe, axis=ax)
            return r if keepdims else jnp.squeeze(r, axis=ax)
        return fn
    register_op("pick", pick_maker)

    # ---- tile / repeat / flip / pad -------------------------------------
    def tile_maker(reps=()):
        def fn(x):
            return jnp.tile(x, reps)
        return fn
    register_op("tile", tile_maker)

    def repeat_maker(repeats=1, axis=None):
        def fn(x):
            return jnp.repeat(x, repeats, axis=axis)
        return fn
    register_op("repeat", repeat_maker)

    def reverse_maker(axis=()):
        def fn(x):
            return jnp.flip(x, axis)
        return fn
    register_op("reverse", reverse_maker, aliases=("flip",))

    def pad_maker(mode="constant", pad_width=(), constant_value=0.0):
        def fn(x):
            pw = [(pad_width[2 * i], pad_width[2 * i + 1])
                  for i in range(len(pad_width) // 2)]
            if mode == "constant":
                return jnp.pad(x, pw, constant_values=constant_value)
            if mode == "edge":
                return jnp.pad(x, pw, mode="edge")
            if mode == "reflect":
                return jnp.pad(x, pw, mode="reflect")
            raise ValueError(mode)
        return fn
    register_op("pad", pad_maker, aliases=("Pad",))

    # ---- broadcasting ----------------------------------------------------
    def broadcast_to_maker(shape=()):
        def fn(x):
            tgt = tuple(s if s != 0 else x.shape[i]
                        for i, s in enumerate(shape))
            return jnp.broadcast_to(x, tgt)
        return fn
    register_op("broadcast_to", broadcast_to_maker)

    def broadcast_like_maker(lhs_axes=None, rhs_axes=None):
        def fn(x, like):
            return jnp.broadcast_to(x, like.shape)
        return fn
    register_op("broadcast_like", broadcast_like_maker)

    def broadcast_axis_maker(axis=(), size=()):
        def fn(x):
            ax = axis if isinstance(axis, (tuple, list)) else (axis,)
            sz = size if isinstance(size, (tuple, list)) else (size,)
            tgt = list(x.shape)
            for a, s in zip(ax, sz):
                tgt[a % x.ndim] = s
            return jnp.broadcast_to(x, tuple(tgt))
        return fn
    register_op("broadcast_axis", broadcast_axis_maker,
                aliases=("broadcast_axes",))

    # ---- sequence ops (axis 0 = time by default, MXNet convention) ------
    def sequence_mask_maker(use_sequence_length=False, value=0.0, axis=0):
        def fn(data, *maybe_len):
            if not use_sequence_length:
                return data
            seq_len = maybe_len[0]
            T = data.shape[axis]
            pos = jnp.arange(T)
            # mask shape: broadcast pos along batch
            if axis == 0:
                mask = pos[:, None] < seq_len[None, :].astype(pos.dtype)
                ext = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
            else:  # axis == 1
                mask = pos[None, :] < seq_len[:, None].astype(pos.dtype)
                ext = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
            return jnp.where(ext, data, jnp.asarray(value, data.dtype))
        return fn
    register_op("SequenceMask", sequence_mask_maker,
                aliases=("sequence_mask",))

    def sequence_last_maker(use_sequence_length=False, axis=0):
        def fn(data, *maybe_len):
            if not use_sequence_length:
                return jnp.take(data, -1, axis=axis)
            seq_len = maybe_len[0].astype(jnp.int32) - 1
            if axis == 0:
                return data[seq_len, jnp.arange(data.shape[1])]
            return data[jnp.arange(data.shape[0]), seq_len]
        return fn
    register_op("SequenceLast", sequence_last_maker,
                aliases=("sequence_last",))

    def sequence_reverse_maker(use_sequence_length=False, axis=0):
        def fn(data, *maybe_len):
            if not use_sequence_length:
                return jnp.flip(data, axis=axis)
            seq_len = maybe_len[0].astype(jnp.int32)
            T = data.shape[0]
            pos = jnp.arange(T)[:, None]
            rev = seq_len[None, :] - 1 - pos
            idx = jnp.where(pos < seq_len[None, :], rev, pos)
            ext = idx.reshape(idx.shape + (1,) * (data.ndim - 2))
            return jnp.take_along_axis(
                data, jnp.broadcast_to(ext, data.shape), axis=0)
        return fn
    register_op("SequenceReverse", sequence_reverse_maker,
                aliases=("sequence_reverse",))

    # ---- misc ------------------------------------------------------------
    def diag_maker(k=0, axis1=0, axis2=1):
        def fn(x):
            if x.ndim == 1:
                return jnp.diag(x, k)
            return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)
        return fn
    register_op("diag", diag_maker)

    def reshape_like_maker(lhs_begin=None, lhs_end=None, rhs_begin=None,
                           rhs_end=None):
        def fn(lhs, rhs):
            # partial-range semantics (reference matrix_op reshape_like):
            # lhs dims [lhs_begin, lhs_end) are replaced by rhs dims
            # [rhs_begin, rhs_end); full-shape copy when no range given
            lb = 0 if lhs_begin is None else lhs_begin % (lhs.ndim + 1)
            le = lhs.ndim if lhs_end is None else lhs_end % (lhs.ndim + 1)
            rb = 0 if rhs_begin is None else rhs_begin % (rhs.ndim + 1)
            re = rhs.ndim if rhs_end is None else rhs_end % (rhs.ndim + 1)
            shape = lhs.shape[:lb] + rhs.shape[rb:re] + lhs.shape[le:]
            return jnp.reshape(lhs, shape)
        return fn
    register_op("reshape_like", reshape_like_maker)

    def moments_maker(axes=None, keepdims=False):
        ax = tuple(axes) if axes is not None else None

        def fn(x):
            mean = jnp.mean(x, axis=ax, keepdims=keepdims)
            var = jnp.mean(
                jnp.square(x - jnp.mean(x, axis=ax, keepdims=True)),
                axis=ax, keepdims=keepdims)
            return (mean, var)
        return fn
    register_op("moments", moments_maker)

    def cumsum_maker(axis=None, dtype=None):
        def fn(x):
            out = jnp.cumsum(x, axis=axis)
            return out.astype(dtype) if dtype else out
        return fn
    register_op("cumsum", cumsum_maker, aliases=("_np_cumsum",))

    def trace_maker(offset=0, axis1=0, axis2=1):
        def fn(x):
            return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)
        return fn
    register_op("trace", trace_maker)

    def tril_maker(k=0):
        def fn(x):
            return jnp.tril(x, k)
        return fn
    register_op("tril", tril_maker)

    def triu_maker(k=0):
        def fn(x):
            return jnp.triu(x, k)
        return fn
    register_op("triu", triu_maker)

    def depth_to_space_maker(block_size=1):
        def fn(x):
            b, c, h, w = x.shape
            bs = block_size
            y = x.reshape(b, bs, bs, c // (bs * bs), h, w)
            y = y.transpose(0, 3, 4, 1, 5, 2)
            return y.reshape(b, c // (bs * bs), h * bs, w * bs)
        return fn
    register_op("depth_to_space", depth_to_space_maker)

    def space_to_depth_maker(block_size=1):
        def fn(x):
            b, c, h, w = x.shape
            bs = block_size
            y = x.reshape(b, c, h // bs, bs, w // bs, bs)
            y = y.transpose(0, 3, 5, 1, 2, 4)
            return y.reshape(b, c * bs * bs, h // bs, w // bs)
        return fn
    register_op("space_to_depth", space_to_depth_maker)

    simple_op("stop_gradient", lax.stop_gradient,
              aliases=("BlockGrad", "block_grad"))
    # MakeLoss lives in ops_misc with the full reference backward contract
    # (constant grad_scale gradient, batch/valid normalization)
    simple_op("identity", lambda x: x, aliases=("_copy",))

    def smooth_l1_maker(scalar=1.0):
        def fn(x):
            s2 = scalar * scalar
            return jnp.where(jnp.abs(x) < 1.0 / s2,
                             0.5 * s2 * jnp.square(x),
                             jnp.abs(x) - 0.5 / s2)
        return fn
    register_op("smooth_l1", smooth_l1_maker)


_register()
