"""Reductions and ordering ops.

Reference parity: src/operator/tensor/broadcast_reduce_op*.cc (sum/mean/prod/
max/min/norm with axis/keepdims/exclude) and ordering_op.cc (topk/sort/
argsort) — SURVEY.md §2.2.  MXNet conventions preserved: ``exclude=True``
reduces over every axis *except* those given; argmax/argmin return float
arrays (index values in the input's float dtype); topk defaults to returning
indices along the last axis in descending order.
"""
from __future__ import annotations

from .register import register_op


def _norm_axis(axis, exclude=False):
    """Canonicalize the axis spec; resolution against ndim happens in-fn."""
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    return tuple(axis)


def _axes_for(x, axis, exclude):
    if axis is None:
        return None
    axes = tuple(a % x.ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(x.ndim) if a not in axes)
    return axes


def _make_reduce(jfn, acc32=False):
    def maker(axis=None, keepdims=False, exclude=False):
        axis = _norm_axis(axis)

        def fn(x):
            import jax.numpy as jnp
            axes = _axes_for(x, axis, exclude)
            if acc32 and x.dtype in (jnp.float16, jnp.bfloat16):
                # MXNET_SAFE_ACCUMULATION: low-precision sums accumulate fp32
                return jfn(x.astype(jnp.float32), axis=axes,
                           keepdims=keepdims).astype(x.dtype)
            return jfn(x, axis=axes, keepdims=keepdims)
        return fn
    return maker


def _register():
    import jax.numpy as jnp

    register_op("sum", _make_reduce(jnp.sum, acc32=True),
                aliases=("sum_axis",))
    register_op("mean", _make_reduce(jnp.mean, acc32=True))
    register_op("prod", _make_reduce(jnp.prod))
    register_op("nansum", _make_reduce(jnp.nansum, acc32=True))
    register_op("nanprod", _make_reduce(jnp.nanprod))
    register_op("max", _make_reduce(jnp.max), aliases=("max_axis",))
    register_op("min", _make_reduce(jnp.min), aliases=("min_axis",))

    def norm_maker(ord=2, axis=None, out_dtype=None, keepdims=False):
        axis_t = _norm_axis(axis)

        def fn(x):
            axes = _axes_for(x, axis_t, False)
            acc = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
            if ord == 1:
                r = jnp.sum(jnp.abs(acc), axis=axes, keepdims=keepdims)
            else:
                r = jnp.sqrt(jnp.sum(jnp.square(acc), axis=axes,
                                     keepdims=keepdims))
            return r.astype(out_dtype or x.dtype)
        return fn
    register_op("norm", norm_maker)

    def argmax_maker(axis=None, keepdims=False):
        def fn(x):
            r = jnp.argmax(x, axis=axis, keepdims=keepdims)
            # MXNet returns indices in float32
            return r.astype(jnp.float32)
        return fn
    register_op("argmax", argmax_maker, differentiable=False)

    def argmin_maker(axis=None, keepdims=False):
        def fn(x):
            return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)
        return fn
    register_op("argmin", argmin_maker, differentiable=False)

    def argmax_channel_maker():
        def fn(x):
            return jnp.argmax(x, axis=1).astype(jnp.float32)
        return fn
    register_op("argmax_channel", argmax_channel_maker, differentiable=False)

    # ---- ordering --------------------------------------------------------
    def topk_maker(axis=-1, k=1, ret_typ="indices", is_ascend=False,
                   dtype="float32"):
        def fn(x):
            ax = axis % x.ndim
            xs = jnp.moveaxis(x, ax, -1)
            key = xs if is_ascend else -xs
            idx = jnp.argsort(key, axis=-1)[..., :k]
            vals = jnp.take_along_axis(xs, idx, axis=-1)
            idx_f = jnp.moveaxis(idx, -1, ax).astype(jnp.dtype(dtype))
            vals_m = jnp.moveaxis(vals, -1, ax)
            if ret_typ == "indices":
                return idx_f
            if ret_typ == "value":
                return vals_m
            if ret_typ == "both":
                return (vals_m, idx_f)
            if ret_typ == "mask":
                m = jnp.zeros(xs.shape, x.dtype)
                m = jnp.put_along_axis(m, idx, jnp.ones((), x.dtype),
                                       axis=-1, inplace=False)
                return jnp.moveaxis(m, -1, ax)
            raise ValueError(ret_typ)
        return fn
    register_op("topk", topk_maker, differentiable=False)

    def sort_maker(axis=-1, is_ascend=True):
        def fn(x):
            r = jnp.sort(x, axis=axis)
            return r if is_ascend else jnp.flip(r, axis=axis)
        return fn
    register_op("sort", sort_maker)

    def argsort_maker(axis=-1, is_ascend=True, dtype="float32"):
        def fn(x):
            r = jnp.argsort(x, axis=axis)
            if not is_ascend:
                r = jnp.flip(r, axis=axis)
            return r.astype(jnp.dtype(dtype))
        return fn
    register_op("argsort", argsort_maker, differentiable=False)


_register()
