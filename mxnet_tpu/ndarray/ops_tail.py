"""Long-tail operators: special functions, numpy-namespace tail, shape
utilities, masked softmax, and the LARS single-tensor update.

Reference parity: the remaining small families of src/operator/ (SURVEY.md
§2.2) — special_functions-inl.h (polygamma, Bessel), mshadow_op.h activation
tail (log_sigmoid, mish, silu/swish, gelu, hard_swish), the numpy-interface
ops under src/operator/numpy/ (_npi_* — isnan/isinf family, bincount,
interp, ediff1d, kron, tensordot, vander, rot90, roll, cumprod, digitize,
searchsorted, nan_to_num, logaddexp, heaviside, copysign, lcm/gcd/ldexp),
src/operator/nn/softmax.cc masked_softmax/masked_log_softmax, and
src/operator/optimizer_op.cc lars_update.  Each body is the direct
jnp/jax.scipy dual — XLA fuses these into neighbouring MXU work, which is
the whole TPU-first design for elementwise tails.

MXNet conventions preserved: predicate ops (isnan etc.) return 0/1 in the
input float dtype, not bool (the registry-wide comparison rule,
ops_elemwise.py); integer-domain ops (lcm/gcd, bincount, digitize,
searchsorted) are non-differentiable.
"""
from __future__ import annotations

from .register import add_alias, register_op, simple_op


def _register_special():
    import jax
    import jax.numpy as jnp
    import jax.scipy.special as jsp

    unary = {
        "erfc": jsp.erfc,
        "bessel_i0": jsp.i0,
        "bessel_i1": jsp.i1,
        "bessel_i0e": jsp.i0e,
        "bessel_i1e": jsp.i1e,
        "log_sigmoid": jax.nn.log_sigmoid,
        "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
        "silu": jax.nn.silu,
        "hard_swish": lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0,
    }
    for name, fn in unary.items():
        simple_op(name, fn)
    add_alias("silu", "swish")

    # erfcinv via the reflection erfcinv(x) = erfinv(1 - x): jax ships no
    # direct dual, and the reflection is exact in fp32's domain of use
    simple_op("erfcinv", lambda x: jax.lax.erf_inv(1.0 - x))

    # order parameter n is an op attribute (static), matching the
    # reference's scalar-parameter calling convention
    register_op("polygamma", lambda n=0: (lambda x: jsp.polygamma(n, x)))

    # regularized incomplete gamma pair: two-tensor-input special fns
    simple_op("gammainc", jsp.gammainc)
    simple_op("gammaincc", jsp.gammaincc)
    simple_op("zeta", jsp.zeta)

    def gelu_maker(approximation="erf"):
        approx = approximation == "tanh"
        return lambda x: jax.nn.gelu(x, approximate=approx)
    register_op("gelu", gelu_maker)


def _register_np_tail():
    import jax.numpy as jnp

    def _pred(fn):
        # MXNet predicate convention: 0/1 in the input dtype, not bool
        def f(x):
            return fn(x).astype(x.dtype)
        return f

    for name, fn in {"isnan": jnp.isnan, "isinf": jnp.isinf,
                     "isfinite": jnp.isfinite, "isposinf": jnp.isposinf,
                     "isneginf": jnp.isneginf}.items():
        simple_op(name, _pred(fn), differentiable=False)

    def nan_to_num_maker(nan=0.0, posinf=None, neginf=None, copy=True):
        del copy                      # functional arrays: always a copy
        return lambda x: jnp.nan_to_num(x, nan=nan, posinf=posinf,
                                        neginf=neginf)
    register_op("nan_to_num", nan_to_num_maker)

    simple_op("logaddexp", jnp.logaddexp)
    simple_op("heaviside", jnp.heaviside)
    simple_op("copysign", jnp.copysign)
    # reference mshadow_op ldexp is x * 2^e with a FLOAT exponent (and a
    # well-defined gradient through e); jnp.ldexp would truncate to int
    simple_op("ldexp", lambda x, e: x * 2.0 ** e)
    for name, fn in {"lcm": jnp.lcm, "gcd": jnp.gcd}.items():
        simple_op(name, fn, differentiable=False)

    def cumprod_maker(axis=None, dtype=None):
        return lambda x: jnp.cumprod(x, axis=axis, dtype=dtype)
    register_op("cumprod", cumprod_maker)

    def logsumexp_maker(axis=None, keepdims=False):
        from jax.scipy.special import logsumexp
        ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
        return lambda x: logsumexp(x, axis=ax, keepdims=keepdims)
    register_op("logsumexp", logsumexp_maker)

    # bincount's output length is data-dependent unless minlength pins it:
    # run eagerly (use_jit=False) so concrete values size the output — the
    # same escape hatch as the other value-dependent-shape ops
    def bincount_maker(minlength=0):
        def fn(x, *weights):
            w = weights[0] if weights else None
            n = max(int(minlength), int(x.max()) + 1 if x.size else 0)
            return jnp.bincount(x.astype(jnp.int32), weights=w, length=n)
        return fn
    register_op("bincount", bincount_maker, use_jit=False,
                differentiable=False)

    def digitize_maker(right=False):
        return lambda x, bins: jnp.digitize(x, bins, right=right)
    register_op("digitize", digitize_maker, differentiable=False)

    def searchsorted_maker(side="left"):
        return lambda a, v: jnp.searchsorted(a, v, side=side)
    register_op("searchsorted", searchsorted_maker, differentiable=False)

    simple_op("interp", jnp.interp)

    def ediff1d_maker():
        return lambda x: jnp.ediff1d(x)
    register_op("ediff1d", ediff1d_maker)

    def trapz_maker(dx=1.0, axis=-1):
        trap = getattr(jnp, "trapezoid", None) or jnp.trapz
        def fn(y, *xp):
            if xp:
                return trap(y, x=xp[0], axis=axis)
            return trap(y, dx=dx, axis=axis)
        return fn
    register_op("trapz", trapz_maker)


def _register_shape_tail():
    import jax.numpy as jnp

    def einsum_maker(subscripts=""):
        if not subscripts:
            raise ValueError("einsum requires a subscripts string")
        return lambda *ops: jnp.einsum(subscripts, *ops)
    register_op("einsum", einsum_maker)

    def roll_maker(shift=None, axis=None):
        sh = shift if shift is None or isinstance(shift, int) \
            else tuple(shift)
        ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
        return lambda x: jnp.roll(x, sh, axis=ax)
    register_op("roll", roll_maker)

    def rot90_maker(k=1, axes=(0, 1)):
        return lambda x: jnp.rot90(x, k=k, axes=tuple(axes))
    register_op("rot90", rot90_maker)

    simple_op("kron", jnp.kron)

    def tensordot_maker(axes=2):
        ax = axes if isinstance(axes, int) else \
            tuple(tuple(a) for a in axes)
        return lambda a, b: jnp.tensordot(a, b, axes=ax)
    register_op("tensordot", tensordot_maker)

    def vander_maker(N=None, increasing=False):
        return lambda x: jnp.vander(x, N=N, increasing=increasing)
    register_op("vander", vander_maker)

    def meshgrid_maker(indexing="xy", sparse=False):
        return lambda *xs: tuple(jnp.meshgrid(*xs, indexing=indexing,
                                              sparse=sparse))
    register_op("meshgrid", meshgrid_maker)


def _register_masked_softmax():
    import jax.numpy as jnp

    def _masked(log):
        def maker(axis=-1, temperature=1.0, normalize=True):
            del normalize
            def fn(x, mask):
                m = mask != 0
                neg = jnp.finfo(x.dtype).min
                z = jnp.where(m, x / temperature, neg)
                z = z - jnp.max(z, axis=axis, keepdims=True)
                if log:
                    lse = jnp.log(jnp.sum(jnp.where(m, jnp.exp(z), 0.0),
                                          axis=axis, keepdims=True))
                    return jnp.where(m, z - lse, neg)
                e = jnp.where(m, jnp.exp(z), 0.0)
                return e / jnp.maximum(
                    jnp.sum(e, axis=axis, keepdims=True),
                    jnp.finfo(x.dtype).tiny)
            return fn
        return maker
    register_op("masked_softmax", _masked(log=False))
    register_op("masked_log_softmax", _masked(log=True))


def _register_lars():
    import jax.numpy as jnp

    # single-tensor LARS step (reference optimizer_op.cc lars_update):
    # trust ratio ||w||/(||g*rescale|| + wd*||w|| + eps) scales the lr,
    # then a plain (momentum-free) sgd step — the multi-tensor trust-ratio
    # path lives in multi_lars (ops_optimizer.py)
    def lars_update_maker(lr, eta=0.001, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0, epsilon=1e-9):
        def fn(weight, grad):
            g = grad.astype(jnp.float32) * rescale_grad
            if clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            wn = jnp.sqrt(jnp.sum(weight.astype(jnp.float32) ** 2))
            gn = jnp.sqrt(jnp.sum(g ** 2))
            trust = jnp.where(
                (wn > 0) & (gn > 0),
                eta * wn / (gn + wd * wn + epsilon), 1.0)
            step = trust * lr * (g + wd * weight.astype(jnp.float32))
            return (weight.astype(jnp.float32) - step).astype(weight.dtype)
        return fn
    register_op("lars_update", lars_update_maker, differentiable=False)


_register_special()
_register_np_tail()
_register_shape_tail()
_register_masked_softmax()
_register_lars()
add_alias("_sample_multinomial", "multinomial")
