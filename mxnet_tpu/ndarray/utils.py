"""NDArray serialization: ``mx.nd.save`` / ``mx.nd.load``.

Reference parity: NDArray::Save/Load over dmlc::Stream with a magic header
(src/ndarray/ndarray.cc, SURVEY.md §5.4) — the `.params` dict-of-arrays
format that checkpoints, Gluon save_parameters, and Module checkpoints all
share.  TPU-native container: same magic-plus-payload idea, with the payload
as an npz archive (portable, no C++ stream dependency); the *semantics*
(name→array dict or positional list) match the reference exactly.
"""
from __future__ import annotations

import io
import os
import struct
from typing import Dict, List, Union

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array

_MAGIC = b"MXTPU001"
_LIST_PREFIX = "__arr_"


def save(fname: str, data: Union[NDArray, List[NDArray], Dict[str, NDArray]]):
    """Save arrays to file (list or name→array dict, like mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    payload = {}
    if isinstance(data, dict):
        for k, v in data.items():
            payload[k] = v.asnumpy()
        is_dict = True
    else:
        for i, v in enumerate(data):
            payload[f"{_LIST_PREFIX}{i}"] = v.asnumpy()
        is_dict = False
    buf = io.BytesIO()
    _np.savez(buf, **payload)
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<B", 1 if is_dict else 0))
        f.write(buf.getvalue())


def load(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    """Load arrays saved by :func:`save`."""
    with open(fname, "rb") as f:
        return load_buffer(f.read(), what=fname)


def load_buffer(buf: bytes, what: str = "<buffer>") \
        -> Union[List[NDArray], Dict[str, NDArray]]:
    """Load arrays from in-memory bytes (reference:
    MXNDArrayLoadFromBuffer — the predict C API hands params this way)."""
    if buf[:len(_MAGIC)] != _MAGIC:
        raise MXNetError(f"{what}: not an NDArray file (bad magic)")
    off = len(_MAGIC)
    is_dict = struct.unpack("<B", buf[off:off + 1])[0] == 1
    npz = _np.load(io.BytesIO(buf[off + 1:]))
    if is_dict:
        return {k: array(npz[k]) for k in npz.files}
    items = sorted(npz.files, key=lambda k: int(k[len(_LIST_PREFIX):]))
    return [array(npz[k]) for k in items]
