"""Op registry + imperative dispatch.

Reference role: the NNVM op registry (`NNVM_REGISTER_OP` with FCompute/
FGradient/FInferShape attrs — SURVEY.md §2.1) plus the generated Python
wrappers (`python/mxnet/ndarray/register.py`) and the imperative invoke path
(`MXImperativeInvokeEx → Imperative::Invoke → Engine::PushAsync`, §3.1).

TPU-native design: one declarative registry drives everything.  Each op is a
*maker*: ``maker(**params) -> fn(*jax_arrays) -> jax_array(s)``.  Dispatch
jit-compiles the maker result per parameter signature (XLA compile cache keyed
by shape/dtype replaces FInferShape/FInferType), executes asynchronously
(PJRT replaces the threaded engine), and — when autograd is recording —
captures ``jax.vjp`` residuals on the tape (replaces FGradient).  The same
registry backs the Symbol graph composition (mxnet_tpu/symbol) so `mx.nd.*`
and `mx.sym.*` stay in lockstep, mirroring how both reference frontends were
generated from the single C-side registry.
"""
from __future__ import annotations

import functools
from time import perf_counter as _perf_counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..engine import engine
from .. import autograd as _autograd

__all__ = ["Operator", "register_op", "get_op", "list_ops", "invoke",
           "invoke_by_name", "invoke_binary", "make_frontend"]

_registry: Dict[str, "Operator"] = {}


def _canon(v: Any) -> Any:
    """Make a param value hashable/canonical for the compile cache."""
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x) for x in v)
    if isinstance(v, _np.dtype):
        return str(v)
    if isinstance(v, _np.generic):
        return v.item()
    return v


class Operator:
    """A registered operator (analog of ``nnvm::Op``)."""

    __slots__ = ("name", "maker", "aliases", "differentiable", "use_jit",
                 "doc", "ref", "vjp_maker", "needs_rng")

    def __init__(self, name: str, maker: Callable, aliases: Sequence[str] = (),
                 differentiable: bool = True, use_jit: bool = True,
                 doc: str = "", ref: str = "", vjp_maker: Callable = None,
                 needs_rng: bool = False):
        self.name = name
        self.maker = maker
        self.aliases = tuple(aliases)
        self.vjp_maker = vjp_maker
        self.differentiable = differentiable
        self.use_jit = use_jit
        self.doc = doc
        self.ref = ref              # reference file pointer for parity audits
        # sampling ops take a PRNG key as their LAST tensor input (the
        # jax key-threading discipline replacing the reference's per-device
        # resource RNG states, src/resource.cc): eager frontends pass it
        # explicitly; the symbol runner splits one per-forward base key
        self.needs_rng = needs_rng

    @functools.lru_cache(maxsize=None)
    def _fn_cached(self, kwkey: Tuple) -> Callable:
        import jax
        fn = self.maker(**dict(kwkey))
        return jax.jit(fn) if self.use_jit else fn

    def get_fn(self, kwargs: Dict[str, Any]) -> Callable:
        kwkey = tuple(sorted((k, _canon(v)) for k, v in kwargs.items()))
        try:
            return self._fn_cached(kwkey)
        except TypeError:
            # unhashable param slipped through; build uncached
            fn = self.maker(**kwargs)
            import jax
            return jax.jit(fn) if self.use_jit else fn

    @functools.lru_cache(maxsize=None)
    def _vjp_cached(self, kwkey: Tuple) -> Callable:
        # the imperative-training hot path (reference stack §3.1): a bare
        # jax.vjp RE-TRACES the op on every invoke; jitting the
        # (primals -> (outs, vjp_fn)) wrapper caches the trace per shape
        # signature (vjp_fn is a jax Partial — a pytree, so jit can
        # return it).  ~3.5x per-op dispatch win measured.
        import jax
        fn = self.maker(**dict(kwkey))
        wrapper = lambda *p: jax.vjp(fn, *p)   # noqa: E731
        return jax.jit(wrapper) if self.use_jit else wrapper

    def get_vjp_fn(self, kwargs: Dict[str, Any]) -> Tuple[Callable, bool]:
        """Returns (wrapper, runner_safe).  runner_safe is True ONLY for
        the jitted cached wrapper: its returned vjp closures have a
        STABLE pytree treedef across calls, so backward()'s jitted
        runner caches one compiled backward per signature.  The other
        paths produce fresh-treedef Partials or plain closures — running
        those through the runner would recompile every backward."""
        if self.vjp_maker is not None:
            # hand-built (primals -> (outs, vjp_fn)) wrapper — the escape
            # hatch for ops whose output shape depends on input VALUES
            # (jax.vjp cannot trace those); they run eagerly by
            # construction, so no jit cache applies
            return self.vjp_maker(**kwargs), False
        kwkey = tuple(sorted((k, _canon(v)) for k, v in kwargs.items()))
        try:
            return self._vjp_cached(kwkey), self.use_jit
        except TypeError:
            # unhashable kwargs: uncached — a fresh jax.jit here would be
            # a guaranteed cache miss (keyed on callable identity), i.e.
            # a full XLA compile EVERY invoke; eager vjp through the
            # per-primitive caches is the cheaper fallback
            import jax
            fn = self.maker(**kwargs)
            return (lambda *p: jax.vjp(fn, *p)), False


def register_op(name: str, maker: Optional[Callable] = None, *,
                aliases: Sequence[str] = (), differentiable: bool = True,
                use_jit: bool = True, doc: str = "", ref: str = "",
                vjp_maker: Optional[Callable] = None,
                needs_rng: bool = False):
    """Register an operator.  Usable directly or as a decorator on the maker."""
    def do(mk):
        op = Operator(name, mk, aliases=aliases, differentiable=differentiable,
                      use_jit=use_jit, doc=doc or (mk.__doc__ or ""), ref=ref,
                      vjp_maker=vjp_maker, needs_rng=needs_rng)
        for n in (name,) + tuple(aliases):
            # silent shadowing caused a real regression (round-4 review):
            # a later registration replaced an op under the same name with
            # different semantics.  Double registration is always a bug.
            if n in _registry:
                raise MXNetError(
                    f"operator name {n!r} is already registered "
                    f"(by {_registry[n].name!r})")
            _registry[n] = op
        return mk
    if maker is not None:
        do(maker)
        return maker
    return do


def simple_op(name: str, fn: Callable, **kw):
    """Register an op whose fn has no parameters (pure elementwise etc.)."""
    register_op(name, lambda: fn, **kw)


def add_alias(existing: str, *aliases: str) -> None:
    """Point additional names at an already-registered op (reference: the
    underscore canonical vs public-name dualities, e.g. _linalg_gemm /
    linalg_gemm).  Subject to the same duplicate check as register_op."""
    op = get_op(existing)
    for a in aliases:
        if a in _registry:
            raise MXNetError(
                f"operator name {a!r} is already registered "
                f"(by {_registry[a].name!r})")
        _registry[a] = op


def get_op(name: str) -> Operator:
    op = _registry.get(name)
    if op is None:
        raise MXNetError(f"operator {name!r} is not registered")
    return op


def list_ops() -> List[str]:
    return sorted(set(op.name for op in _registry.values()))


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _as_nd(x, ctx):
    from .ndarray import NDArray, array
    if isinstance(x, NDArray):
        return x
    return array(x, ctx=ctx)


# pre-dispatch rewrite hook (installed by contrib.amp to insert casts):
# fn(op_name, inputs) -> inputs
_invoke_hook = None


def set_invoke_hook(fn) -> None:
    """Install (or clear, with None) the global pre-dispatch input-rewrite
    hook — the seam contrib.amp uses for automatic mixed precision, the
    analog of the reference's amp.init() op-namespace monkey-patch."""
    global _invoke_hook
    _invoke_hook = fn


_SUBGRAPH_OPS = ("_foreach", "_while_loop", "_cond")


def node_takes_key(op_name: str, attrs: Dict[str, Any],
                   training: bool) -> bool:
    """THE single active-sampling predicate: whether one op application
    (given its attrs and the executor's train/eval mode) consumes a PRNG
    key.  Every key decision — eager invoke, the symbol runner's per-node
    split, graph-level needs_rng — routes through here, so key-feeding
    and key-consumption cannot drift apart.
      - Dropout gated to identity at inference consumes nothing.
      - Control-flow ops consume only if a SUBGRAPH samples (recursively)
        — an rng-free foreach must not advance the stream."""
    op = _registry.get(op_name)
    if op is None or not op.needs_rng:
        return False
    if op_name == "Dropout" and not training and \
            attrs.get("mode", "training") != "always":
        return False
    if op_name in _SUBGRAPH_OPS:
        return any(graph_needs_rng(v.sym, training)
                   for v in attrs.values() if hasattr(v, "sym"))
    return True


def graph_needs_rng(sym, training: bool) -> bool:
    """Any active sampling node in the graph (duck-typed Symbol: needs
    only ``_topo()``)?  The cheap form of ``sym.compile(training)
    .needs_rng`` — no runner closures are built just to read the bool."""
    return any(not n.is_var and node_takes_key(n.op, n.attrs, training)
               for n in sym._topo())


def op_takes_key(op: Operator, kwargs: Dict[str, Any]) -> bool:
    """``node_takes_key`` for an imperative invocation: kwargs play the
    role of node attrs (``_training`` rides in them on the symbol path;
    eager control flow runs in eval mode unless told otherwise)."""
    return node_takes_key(op.name, kwargs,
                          bool(kwargs.get("_training", False)))


def invoke(op: Operator, inputs: Sequence, kwargs: Dict[str, Any],
           out=None):
    """Dispatch an op imperatively (reference stack §3.1).

    Returns one NDArray, or a list for multi-output ops.  ``out=`` writes the
    (first) result into an existing NDArray in place.
    """
    from .ndarray import NDArray
    if _invoke_hook is not None:
        inputs = _invoke_hook(op.name, inputs)

    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            ctx = x.context
            break
    if ctx is None:
        # zero-input creation ops carry ctx as an op attribute
        # (reference init_op.cc convention) — honor it for the tag too
        ckw = kwargs.get("ctx")
        if ckw is not None:
            from ..context import Context
            ctx = ckw if isinstance(ckw, Context) else Context.from_str(ckw)
        else:
            ctx = current_context()
    nd_inputs = [_as_nd(x, ctx) for x in inputs]
    in_vals = [x._read() for x in nd_inputs]
    if op_takes_key(op, kwargs):
        # sampling ops take a PRNG key as their last input; eager dispatch
        # draws it here (under a hybrid trace, next_key() yields a TRACED
        # subkey of the CachedOp's key argument — push_key in random.py —
        # so compiled graphs stay fresh per call)
        from .. import random as _grandom
        in_vals.append(_grandom.next_key())

    differentiable = op.differentiable(kwargs) \
        if callable(op.differentiable) else op.differentiable
    recording = (_autograd.is_recording() and differentiable
                 and any(getattr(x, "_ag", None) is not None
                         for x in nd_inputs))
    eng = engine()
    # timing only when someone is listening (profiler) — invoke is the
    # hottest path in the library
    _timed = bool(eng._listeners)
    _t0 = _perf_counter() if _timed else 0.0
    if recording:
        vjp_wrapper, runner_safe = op.get_vjp_fn(kwargs)
        out_vals, vjp_fn = vjp_wrapper(*in_vals)
    else:
        out_vals = op.get_fn(kwargs)(*in_vals)
    _dispatch_us = (_perf_counter() - _t0) * 1e6 if _timed else 0.0

    multi = isinstance(out_vals, (tuple, list))
    raw_outs = list(out_vals) if multi else [out_vals]
    outs = [NDArray(v, ctx=ctx) for v in raw_outs]

    if recording:
        parents = [getattr(x, "_ag", None) for x in nd_inputs]
        node = _autograd.TapeNode(op.name, vjp_fn, parents,
                                  [(o.shape, o.dtype) for o in outs], multi,
                                  runner_safe=runner_safe)
        for i, o in enumerate(outs):
            o._ag = _autograd.AGInfo(node=node, index=i)

    eng.on_push(op.name, raw_outs, _dispatch_us)

    if out is not None:
        outs_for_write = outs if multi else [outs[0]]
        targets = out if isinstance(out, (list, tuple)) else [out]
        for tgt, src in zip(targets, outs_for_write):
            val = src._read()
            # out= keeps the target's dtype (an AMP cast hook may have
            # changed the compute dtype; the write-back contract wins)
            if val.dtype != tgt.dtype:
                val = val.astype(tgt.dtype)
            tgt._set_data(val)
        return out
    return outs if multi else outs[0]


def invoke_by_name(name: str, inputs: Sequence, kwargs: Dict[str, Any],
                   out=None):
    return invoke(get_op(name), inputs, kwargs, out=out)


# scalar fallbacks for the arithmetic dunders: (forward op, reflected op)
_SCALAR_MAP = {
    "broadcast_add": ("_plus_scalar", "_plus_scalar"),
    "broadcast_sub": ("_minus_scalar", "_rminus_scalar"),
    "broadcast_mul": ("_mul_scalar", "_mul_scalar"),
    "broadcast_div": ("_div_scalar", "_rdiv_scalar"),
    "broadcast_mod": ("_mod_scalar", "_rmod_scalar"),
    "broadcast_power": ("_power_scalar", "_rpower_scalar"),
    "broadcast_equal": ("_equal_scalar", "_equal_scalar"),
    "broadcast_not_equal": ("_not_equal_scalar", "_not_equal_scalar"),
    "broadcast_greater": ("_greater_scalar", "_lesser_scalar"),
    "broadcast_greater_equal": ("_greater_equal_scalar", "_lesser_equal_scalar"),
    "broadcast_lesser": ("_lesser_scalar", "_greater_scalar"),
    "broadcast_lesser_equal": ("_lesser_equal_scalar", "_greater_equal_scalar"),
}


def invoke_binary(name: str, lhs, rhs, reverse: bool = False):
    """Binary dunder dispatch: NDArray⊕NDArray uses the broadcast op;
    NDArray⊕scalar uses the ``_*_scalar`` variant with the scalar passed as a
    0-d array input (keeps one XLA compilation per shape, not per constant)."""
    from .ndarray import NDArray
    if isinstance(rhs, NDArray):
        args = [rhs, lhs] if reverse else [lhs, rhs]
        return invoke_by_name(name, args, {})
    if isinstance(rhs, (_np.ndarray, list)):
        args = [rhs, lhs] if reverse else [lhs, rhs]
        return invoke_by_name(name, args, {})
    fwd, rev = _SCALAR_MAP[name]
    sop = rev if reverse else fwd
    scal = _np.asarray(rhs)
    return invoke_by_name(sop, [lhs, scal], {})


@functools.lru_cache(maxsize=None)
def _maker_param_names(op: Operator) -> Tuple[str, ...]:
    import inspect
    try:
        return tuple(
            p.name for p in inspect.signature(op.maker).parameters.values()
            if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                          inspect.Parameter.KEYWORD_ONLY))
    except (TypeError, ValueError):
        return ()


def _is_param_value(v) -> bool:
    """Positional values that are op PARAMETERS, not tensor inputs.
    Tuples are parameters (shape/axes); plain lists stay tensor-ish
    (mx.nd converts lists to arrays)."""
    import jax
    if isinstance(v, (bool, int, float, str, tuple, _np.generic)):
        return True
    if isinstance(v, (_np.ndarray, jax.Array, list)):
        return False
    if hasattr(v, "_heads"):                # Symbol (duck-typed: symbol
        return False                        # imports this module)
    from .ndarray import NDArray
    return not isinstance(v, NDArray)


def split_positional_params(op: Operator, args: Sequence,
                            kwargs: Dict[str, Any]):
    """Reference-parity calling convention for generated wrappers: the
    C-side registry gave each wrapper an explicit signature
    ``op(data..., param1, param2, ...)``, so trailing non-tensor
    positionals map onto the op's parameters in maker-declaration order
    (``nd.sum(x, 1)`` ≡ ``nd.sum(x, axis=1)``)."""
    inputs = list(args)
    split = len(inputs)
    while split > 0 and _is_param_value(inputs[split - 1]):
        split -= 1
    extra = inputs[split:]
    if not extra:
        return inputs, kwargs
    names = _maker_param_names(op)
    if len(extra) > len(names):
        return inputs, kwargs               # unmappable: legacy behavior
    for n, v in zip(names, extra):
        if n in kwargs:
            raise TypeError(
                f"{op.name}() got multiple values for argument {n!r}")
        kwargs[n] = v
    return inputs[:split], kwargs


def make_frontend(op: Operator) -> Callable:
    """Build the user-facing ``mx.nd.<op>`` function."""
    def frontend(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)        # accepted for symbol-API symmetry
        inputs, kwargs = split_positional_params(op, args, kwargs)
        return invoke(op, inputs, kwargs, out=out)
    frontend.__name__ = op.name
    frontend.__qualname__ = op.name
    frontend.__doc__ = op.doc
    return frontend
