"""Op registry + imperative dispatch.

Reference role: the NNVM op registry (`NNVM_REGISTER_OP` with FCompute/
FGradient/FInferShape attrs — SURVEY.md §2.1) plus the generated Python
wrappers (`python/mxnet/ndarray/register.py`) and the imperative invoke path
(`MXImperativeInvokeEx → Imperative::Invoke → Engine::PushAsync`, §3.1).

TPU-native design: one declarative registry drives everything.  Each op is a
*maker*: ``maker(**params) -> fn(*jax_arrays) -> jax_array(s)``.  Dispatch
jit-compiles the maker result per parameter signature (XLA compile cache keyed
by shape/dtype replaces FInferShape/FInferType), executes asynchronously
(PJRT replaces the threaded engine), and — when autograd is recording —
captures ``jax.vjp`` residuals on the tape (replaces FGradient).  The same
registry backs the Symbol graph composition (mxnet_tpu/symbol) so `mx.nd.*`
and `mx.sym.*` stay in lockstep, mirroring how both reference frontends were
generated from the single C-side registry.
"""
from __future__ import annotations

import collections as _collections
import functools
import threading
import weakref as _weakref
from time import perf_counter as _perf_counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, hot_path
from ..context import current_context
from ..engine import PendingValue, engine, _install_flush_hook
from .. import autograd as _autograd

__all__ = ["Operator", "register_op", "get_op", "list_ops", "invoke",
           "invoke_by_name", "invoke_binary", "make_frontend",
           "flush_segment", "segment_cache_info", "segment_cache_clear"]

_registry: Dict[str, "Operator"] = {}


class _BoundedCache:
    """Tiny LRU with the ``functools.lru_cache`` info surface.

    Replaces the former ``lru_cache(maxsize=None)`` *methods* on Operator:
    those keyed on ``self``, pinning every Operator — and every compiled
    executable it ever produced — for the life of the process.  Eviction
    here drops the last reference to the jitted callable, which releases
    its jit/XLA cache entries with it."""

    __slots__ = ("maxsize", "_d", "hits", "misses")

    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._d = _collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, default=None):
        d = self._d
        try:
            val = d[key]
            d.move_to_end(key)
        except KeyError:
            # miss — or a concurrent eviction raced the move_to_end
            # (DataLoader worker threads dispatch ops too; individual
            # OrderedDict ops are GIL-atomic, sequences are not)
            self.misses += 1
            return default
        self.hits += 1
        return val

    def put(self, key, val) -> None:
        d = self._d
        d[key] = val
        try:
            d.move_to_end(key)
            if len(d) > self.maxsize:
                d.popitem(last=False)
        except KeyError:
            pass                      # concurrent eviction: already gone

    def cache_info(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "maxsize": self.maxsize, "currsize": len(self._d)}

    def cache_clear(self) -> None:
        self._d.clear()
        self.hits = 0
        self.misses = 0


def _canon(v: Any) -> Any:
    """Make a param value hashable/canonical for the compile cache."""
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x) for x in v)
    if isinstance(v, _np.dtype):
        return str(v)
    if isinstance(v, _np.generic):
        # np.generic scalar: already host memory, a pure-host unbox
        # mxlint: disable=hidden-host-sync — np scalar, no device
        return v.item()
    return v


#: per-Operator bound on compiled-fn caches (distinct param signatures per
#: op are few in practice; shape/dtype specialization lives in jax's own
#: per-callable jit cache underneath each entry)
OP_FN_CACHE_SIZE = 128


class Operator:
    """A registered operator (analog of ``nnvm::Op``)."""

    __slots__ = ("name", "maker", "aliases", "differentiable", "use_jit",
                 "doc", "ref", "vjp_maker", "needs_rng", "_fn_cache",
                 "_vjp_cache")

    def __init__(self, name: str, maker: Callable, aliases: Sequence[str] = (),
                 differentiable: bool = True, use_jit: bool = True,
                 doc: str = "", ref: str = "", vjp_maker: Callable = None,
                 needs_rng: bool = False):
        self.name = name
        self.maker = maker
        self.aliases = tuple(aliases)
        self.vjp_maker = vjp_maker
        self.differentiable = differentiable
        self.use_jit = use_jit
        self.doc = doc
        self.ref = ref              # reference file pointer for parity audits
        # sampling ops take a PRNG key as their LAST tensor input (the
        # jax key-threading discipline replacing the reference's per-device
        # resource RNG states, src/resource.cc): eager frontends pass it
        # explicitly; the symbol runner splits one per-forward base key
        self.needs_rng = needs_rng
        self._fn_cache = _BoundedCache(OP_FN_CACHE_SIZE)
        self._vjp_cache = _BoundedCache(OP_FN_CACHE_SIZE)

    def _fn_for_key(self, kwkey: Tuple) -> Callable:
        fn = self._fn_cache.get(kwkey)
        if fn is None:
            import jax
            fn = self.maker(**dict(kwkey))
            if self.use_jit:
                fn = jax.jit(fn)
            self._fn_cache.put(kwkey, fn)
        return fn

    def get_fn(self, kwargs: Dict[str, Any]) -> Callable:
        kwkey = tuple(sorted((k, _canon(v)) for k, v in kwargs.items()))
        try:
            return self._fn_for_key(kwkey)
        except TypeError:
            # unhashable param slipped through; build uncached
            fn = self.maker(**kwargs)
            import jax
            return jax.jit(fn) if self.use_jit else fn

    def _vjp_for_key(self, kwkey: Tuple) -> Callable:
        # the imperative-training hot path (reference stack §3.1): a bare
        # jax.vjp RE-TRACES the op on every invoke; jitting the
        # (primals -> (outs, vjp_fn)) wrapper caches the trace per shape
        # signature (vjp_fn is a jax Partial — a pytree, so jit can
        # return it).  ~3.5x per-op dispatch win measured.
        wrapper = self._vjp_cache.get(kwkey)
        if wrapper is None:
            import jax
            fn = self.maker(**dict(kwkey))
            wrapper = lambda *p: jax.vjp(fn, *p)   # noqa: E731
            if self.use_jit:
                wrapper = jax.jit(wrapper)
            self._vjp_cache.put(kwkey, wrapper)
        return wrapper

    def cache_info(self) -> dict:
        return {"fn": self._fn_cache.cache_info(),
                "vjp": self._vjp_cache.cache_info()}

    def cache_clear(self) -> None:
        self._fn_cache.cache_clear()
        self._vjp_cache.cache_clear()

    def get_vjp_fn(self, kwargs: Dict[str, Any]) -> Tuple[Callable, bool]:
        """Returns (wrapper, runner_safe).  runner_safe is True ONLY for
        the jitted cached wrapper: its returned vjp closures have a
        STABLE pytree treedef across calls, so backward()'s jitted
        runner caches one compiled backward per signature.  The other
        paths produce fresh-treedef Partials or plain closures — running
        those through the runner would recompile every backward."""
        if self.vjp_maker is not None:
            # hand-built (primals -> (outs, vjp_fn)) wrapper — the escape
            # hatch for ops whose output shape depends on input VALUES
            # (jax.vjp cannot trace those); they run eagerly by
            # construction, so no jit cache applies
            return self.vjp_maker(**kwargs), False
        kwkey = tuple(sorted((k, _canon(v)) for k, v in kwargs.items()))
        try:
            return self._vjp_for_key(kwkey), self.use_jit
        except TypeError:
            # unhashable kwargs: uncached — a fresh jax.jit here would be
            # a guaranteed cache miss (keyed on callable identity), i.e.
            # a full XLA compile EVERY invoke; eager vjp through the
            # per-primitive caches is the cheaper fallback
            import jax
            fn = self.maker(**kwargs)
            return (lambda *p: jax.vjp(fn, *p)), False


def register_op(name: str, maker: Optional[Callable] = None, *,
                aliases: Sequence[str] = (), differentiable: bool = True,
                use_jit: bool = True, doc: str = "", ref: str = "",
                vjp_maker: Optional[Callable] = None,
                needs_rng: bool = False):
    """Register an operator.  Usable directly or as a decorator on the maker."""
    def do(mk):
        op = Operator(name, mk, aliases=aliases, differentiable=differentiable,
                      use_jit=use_jit, doc=doc or (mk.__doc__ or ""), ref=ref,
                      vjp_maker=vjp_maker, needs_rng=needs_rng)
        for n in (name,) + tuple(aliases):
            # silent shadowing caused a real regression (round-4 review):
            # a later registration replaced an op under the same name with
            # different semantics.  Double registration is always a bug.
            if n in _registry:
                raise MXNetError(
                    f"operator name {n!r} is already registered "
                    f"(by {_registry[n].name!r})")
            _registry[n] = op
        return mk
    if maker is not None:
        do(maker)
        return maker
    return do


def simple_op(name: str, fn: Callable, **kw):
    """Register an op whose fn has no parameters (pure elementwise etc.)."""
    register_op(name, lambda: fn, **kw)


def add_alias(existing: str, *aliases: str) -> None:
    """Point additional names at an already-registered op (reference: the
    underscore canonical vs public-name dualities, e.g. _linalg_gemm /
    linalg_gemm).  Subject to the same duplicate check as register_op."""
    op = get_op(existing)
    for a in aliases:
        if a in _registry:
            raise MXNetError(
                f"operator name {a!r} is already registered "
                f"(by {_registry[a].name!r})")
        _registry[a] = op


def get_op(name: str) -> Operator:
    op = _registry.get(name)
    if op is None:
        raise MXNetError(f"operator {name!r} is not registered")
    return op


def list_ops() -> List[str]:
    return sorted(set(op.name for op in _registry.values()))


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _as_nd(x, ctx):
    if isinstance(x, _ND_CLS or _nd_cls()):
        return x
    from .ndarray import array
    return array(x, ctx=ctx)


# pre-dispatch rewrite hook (installed by contrib.amp to insert casts):
# fn(op_name, inputs) -> inputs
_invoke_hook = None


def set_invoke_hook(fn) -> None:
    """Install (or clear, with None) the global pre-dispatch input-rewrite
    hook — the seam contrib.amp uses for automatic mixed precision, the
    analog of the reference's amp.init() op-namespace monkey-patch."""
    global _invoke_hook
    _invoke_hook = fn


_SUBGRAPH_OPS = ("_foreach", "_while_loop", "_cond")


def node_takes_key(op_name: str, attrs: Dict[str, Any],
                   training: bool) -> bool:
    """THE single active-sampling predicate: whether one op application
    (given its attrs and the executor's train/eval mode) consumes a PRNG
    key.  Every key decision — eager invoke, the symbol runner's per-node
    split, graph-level needs_rng — routes through here, so key-feeding
    and key-consumption cannot drift apart.
      - Dropout gated to identity at inference consumes nothing.
      - Control-flow ops consume only if a SUBGRAPH samples (recursively)
        — an rng-free foreach must not advance the stream."""
    op = _registry.get(op_name)
    if op is None or not op.needs_rng:
        return False
    if op_name == "Dropout" and not training and \
            attrs.get("mode", "training") != "always":
        return False
    if op_name in _SUBGRAPH_OPS:
        return any(graph_needs_rng(v.sym, training)
                   for v in attrs.values() if hasattr(v, "sym"))
    return True


def graph_needs_rng(sym, training: bool) -> bool:
    """Any active sampling node in the graph (duck-typed Symbol: needs
    only ``_topo()``)?  The cheap form of ``sym.compile(training)
    .needs_rng`` — no runner closures are built just to read the bool."""
    return any(not n.is_var and node_takes_key(n.op, n.attrs, training)
               for n in sym._topo())


def op_takes_key(op: Operator, kwargs: Dict[str, Any]) -> bool:
    """``node_takes_key`` for an imperative invocation: kwargs play the
    role of node attrs (``_training`` rides in them on the symbol path;
    eager control flow runs in eval mode unless told otherwise)."""
    return node_takes_key(op.name, kwargs,
                          bool(kwargs.get("_training", False)))


# ---------------------------------------------------------------------------
# bulked dispatch: lazy op-fusion segments (reference: the engine's
# MXNET_EXEC_BULK_EXEC_* bulking of consecutive pushes — SURVEY.md §2.1)
# ---------------------------------------------------------------------------

_NOT_FUSABLE = object()   # sentinel: op must flush + dispatch eagerly
_EXT, _NODE = 0, 1        # argument-ref kinds inside a segment

_tls = threading.local()

#: fused executables, keyed on (taped?, op-sequence incl. param signatures
#: and wiring, external input shapes/dtypes) — the steady-state training
#: loop hits this every segment
_segment_cache = _BoundedCache(512)


def segment_cache_info() -> dict:
    return _segment_cache.cache_info()


def segment_cache_clear() -> None:
    _segment_cache.cache_clear()


# persistent compile-cache seam (installed by tuning.compile_cache when
# MXTPU_COMPILE_CACHE_DIR is set): (lookup, store) callables consulted
# ONLY on an in-memory segment-cache miss — the cold compile path.  A
# hook indirection, not an import: the frontend layer stays free of a
# tuning dependency, and the calls resolve to no edge in mxlint's call
# graph, keeping the disk tier provably off the dispatch hot path.
_persist_hooks = None


def _install_persist_hooks(lookup, store) -> None:
    global _persist_hooks
    _persist_hooks = (lookup, store)


def _segment_persist_key(needed, nodes, ext_vals) -> str:
    """Canonical string form of the segment signature for the disk tier
    — the in-memory ``_segment_cache`` key minus the device id (the
    cache's backend fingerprint covers platform/device kind, so an
    executable can be replayed by any process on the same chip type)."""
    return repr((needed, nodes,
                 tuple((tuple(v.shape), str(_np.dtype(v.dtype)))
                       for v in ext_vals)))


def clear_op_caches() -> None:
    """Drop every Operator's compiled fn/vjp caches, plus the fused-segment
    executables (which close over per-op fns) and the abstract-eval cache.
    The big hammer for tests and for env-var toggles (e.g.
    MXNET_PALLAS_INTERPRET) that change what a maker compiles to."""
    for op in set(_registry.values()):
        op.cache_clear()
    _segment_cache.cache_clear()
    _infer_out_avals.cache_clear()


# lazily-bound hot-path globals: `from .ndarray import NDArray` / `import
# jax` inside a per-op function costs a sys.modules round-trip per call
# (visible in dispatch profiles as importlib frames)
_ND_CLS = None
_TRACER_CLS = None


def _nd_cls():
    global _ND_CLS
    if _ND_CLS is None:
        from .ndarray import NDArray
        _ND_CLS = NDArray
    return _ND_CLS


def _tracer_type():
    global _TRACER_CLS
    if _TRACER_CLS is None:
        import jax
        _TRACER_CLS = jax.core.Tracer
    return _TRACER_CLS


_SDS_CLS = None


def _sds_cls():
    """jax's SingleDeviceSharding — the fast 'not a multi-chip global
    array' check (its device_set property builds a frozenset per call,
    too slow for the defer path)."""
    global _SDS_CLS
    if _SDS_CLS is None:
        from jax.sharding import SingleDeviceSharding
        _SDS_CLS = SingleDeviceSharding
    return _SDS_CLS


def _n_elems(shape: Tuple) -> int:
    n = 1
    for s in shape:
        n *= s
    return n


#: ops whose arithmetic includes a reduction/contraction even when the
#: output is not smaller than the inputs (dot grows, softmax preserves
#: shape): fusing their PENDING output into a downstream consumer lets
#: XLA re-fuse the internal accumulation (measured: ~1-ulp drift on CPU),
#: so consuming one while pending is a flush point — the same rule the
#: element-shrink heuristic applies to plain reductions
_FUSION_BARRIER_OPS = frozenset({
    "dot", "batch_dot", "FullyConnected", "Convolution", "Deconvolution",
    "Pooling", "softmax", "log_softmax", "softmin", "SoftmaxActivation",
    "SoftmaxOutput", "Softmax", "LayerNorm", "BatchNorm", "InstanceNorm",
    "GroupNorm", "L2Normalization", "LRN", "RNN", "Correlation", "moments",
    "topk", "sort", "argsort", "einsum", "khatri_rao", "Embedding",
})


def _is_barrier_op(name: str) -> bool:
    return name in _FUSION_BARRIER_OPS or name.startswith("linalg_") \
        or name.startswith("_linalg")


@functools.lru_cache(maxsize=4096)
def _infer_out_avals(op_name: str, kwkey: Tuple, in_avals: Tuple):
    """Predicted (shape, dtype) per output WITHOUT executing — the deferred
    path's replacement for the reference's FInferShape/FInferType.  One
    abstract trace per (op, params, input signature), then a dict hit."""
    import jax
    fn = _registry[op_name]._fn_for_key(kwkey)
    structs = [jax.ShapeDtypeStruct(s, d) for s, d in in_avals]
    out = jax.eval_shape(fn, *structs)
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)
    return tuple((tuple(o.shape), _np.dtype(o.dtype)) for o in outs), multi


def _build_fused(nodes: Tuple, needed: Optional[Tuple]) -> Callable:
    """The segment as one Python-composable function.  Each node calls its
    op's cached (jitted) fn — under an outer trace the inner jaxprs inline,
    so XLA sees the whole chain as a single computation.

    ``needed`` (untaped segments) lists the flat output slots whose
    NDArrays are still live at flush time: only those are returned, so
    XLA dead-code-eliminates every dropped intermediate.  Taped segments
    return everything — the tape node's cotangent slots index the full
    flat tuple."""
    resolved = [(_registry[name]._fn_for_key(kwkey), refs, multi)
                for name, kwkey, refs, multi in nodes]

    def fused(*ext):
        flat = []
        for fn, refs, multi in resolved:
            args = [ext[i] if kind == _EXT else flat[i] for kind, i in refs]
            out = fn(*args)
            if multi:
                flat.extend(out)
            else:
                flat.append(out)
        if needed is not None:
            return tuple(flat[i] for i in needed)
        return tuple(flat)

    return fused


def _compile_segment(nodes: Tuple, taped: bool,
                     needed: Optional[Tuple]) -> Callable:
    """'aggressive' codegen: one jit over the whole segment — XLA fuses
    freely (FMA contraction ⇒ up to ~1-ulp drift vs unbulked)."""
    import jax
    fused = _build_fused(nodes, needed)
    if taped:
        # one jax.vjp over the fused function — the whole segment becomes
        # ONE tape node; cached per segment signature, so the returned
        # vjp closures have a stable treedef (runner_safe)
        return jax.jit(lambda *p: jax.vjp(fused, *p))
    return jax.jit(fused)


_exact_compile_broken = False


def _compile_segment_exact(nodes: Tuple, needed: Optional[Tuple],
                           ext_vals: Sequence, device,
                           persist_key: Optional[str] = None) -> Callable:
    """'exact' codegen (the default): ONE PJRT executable per segment but
    with XLA's fusion passes disabled, so every node keeps the same
    kernels the unbulked per-op path compiles — results are BITWISE
    identical to unbulked (no cross-op FMA contraction, no refused
    reductions) while the host still pays a single dispatch for the whole
    segment (the reference's bulking economics exactly: batch the pushes,
    not the arithmetic).

    With ``persist_key`` set (the persistent compile cache is wired), a
    previously-compiled executable for the same signature+backend is
    deserialized from disk instead of compiled — the restart-without-
    recompile path; a real compile is serialized back for the next
    process.

    Falls back to a node-by-node interpreter over the per-op jitted fns
    (still bitwise, one jit dispatch per node) if the lower/compile
    internals are unavailable."""
    global _exact_compile_broken
    fused = _build_fused(nodes, needed)
    if not _exact_compile_broken:
        try:
            import jax
            from jax._src.lib import xla_client as xc
            jax_array_cls = jax.Array
            device_put = jax.device_put
            opts = xc.CompileOptions()
            opts.executable_build_options.debug_options \
                .xla_disable_hlo_passes = "fusion,cpu-instruction-fusion"
            opts.executable_build_options.device_assignment = \
                xc.DeviceAssignment.create(
                    # mxlint: disable=hot-path-purity — compile miss
                    _np.asarray([[device.id]], dtype=_np.int32))
            exe = None
            hooks = _persist_hooks
            if hooks is not None and persist_key is not None:
                exe = hooks[0](persist_key, device, opts)
            if exe is None:
                # keep_unused: liveness-DCE can leave some external
                # inputs unused; the raw executable is fed ALL of them,
                # so jit must not prune its parameter list
                # (kept_var_idx filtering is a jit-call-path service we
                # bypass here)
                lowered = jax.jit(fused,
                                  keep_unused=True).lower(*ext_vals)
                exe = device.client.compile(
                    lowered.compiler_ir().operation.get_asm(), opts)
                if hooks is not None and persist_key is not None:
                    hooks[1](persist_key, device, exe)

            def run(*vals):
                try:
                    out = exe.execute_sharded(
                        [v if isinstance(v, jax_array_cls)
                         else device_put(v, device) for v in vals])
                except Exception:  # noqa: BLE001 — a buffer on another
                    # device (NDArray ctx tags can diverge from actual
                    # placement after cross-device _set_data): align and
                    # retry once; a real failure re-raises below
                    out = exe.execute_sharded(
                        [device_put(v, device) for v in vals])
                return [a[0] for a in
                        out.disassemble_into_single_device_arrays()]

            return run
        except Exception as e:  # noqa: BLE001 — jax-internal API drift:
            # fall back, never break dispatch — but say so ONCE: the
            # silent alternative is the headline single-dispatch win
            # evaporating with healthy-looking stats
            _exact_compile_broken = True
            import warnings
            # fires ONCE on jax API drift, then the
            # _exact_compile_broken flag short-circuits
            # mxlint: disable=hot-path-purity — warn-once cold path
            warnings.warn(
                "bulked dispatch: exact-mode segment compile unavailable "
                f"({type(e).__name__}: {e}); falling back to per-op "
                "dispatch at flush (correct but slower). "
                "MXNET_ENGINE_BULK_FUSE=aggressive restores fused "
                "execution.", RuntimeWarning, stacklevel=2)
    return fused


class _BulkSegment:
    """A lazy run of fusable imperative ops (the reference's bulked engine
    push).  External input VALUES are captured at defer time, so an
    in-place write after the defer cannot be observed — exactly the read
    ordering the unbulked path has.  ``flush`` executes the whole DAG as
    one cached jitted call and fills every pending output in place."""

    __slots__ = ("ctx", "recording", "fuse", "cap", "nodes", "ext_vals",
                 "ext_parents", "_ext_ids", "avals", "barrier", "outs",
                 "tapenode", "flushed", "error", "_lock")

    def __init__(self, ctx, recording: bool, fuse: str, cap: int):
        # re-entrant: guards append-vs-flush races (a cross-thread READ
        # of a pending output flushes this segment from another thread);
        # re-entrancy covers the owner thread's cap/barrier flushes
        # while it already holds the lock in _try_defer
        # one RLock per segment, amortized over bulk_size deferred
        # ops (~1µs for ~15 ops)
        # mxlint: disable=hot-path-purity — per-segment, amortized
        self._lock = threading.RLock()
        self.ctx = ctx
        self.recording = recording    # autograd scope state at creation
        self.fuse = fuse              # 'exact' | 'aggressive' at creation
        self.cap = cap                # MXNET_ENGINE_BULK_SIZE at creation
        self.nodes: List[Tuple] = []  # (op_name, kwkey, refs, multi)
        self.ext_vals: List[Any] = []
        self.ext_parents: List[Any] = []   # AGInfo | None per external
        self._ext_ids: Dict[Tuple, int] = {}
        self.avals: List[Tuple] = []  # (shape, dtype) per flat output
        self.barrier: List[bool] = []  # per flat output: reduction-like?
        self.outs: List[Tuple] = []   # (weakref[NDArray], PendingValue)
        self.tapenode = None          # created when the first op records
        self.flushed = False
        self.error = None

    def add_ext_locked(self, val, parent) -> int:
        # callers (_try_defer's argument-collection loop) hold self._lock
        # — the ``_locked`` suffix is the lint-checked convention
        key = (id(val), id(parent))
        idx = self._ext_ids.get(key)
        if idx is None:
            idx = len(self.ext_vals)
            self._ext_ids[key] = idx
            self.ext_vals.append(val)
            self.ext_parents.append(parent)
        return idx

    @hot_path("dispatch")
    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    @hot_path("dispatch")
    def _flush_locked(self) -> None:
        if self.flushed:
            return
        self.flushed = True
        if getattr(_tls, "seg", None) is self:
            _tls.seg = None
        if not self.nodes:
            return                    # nothing was deferred
        eng = engine()
        # always timed: the per-flush latency histogram (engine.flush_us)
        # is the auto-tune signal for MXNET_ENGINE_BULK_SIZE — two
        # perf_counter() calls per SEGMENT (not per op) is noise next to
        # the dispatch they bracket
        _t0 = _perf_counter()   # mxlint: disable=timing-pair — feeds
        # engine.flush_us on the per-segment hot path (a span would add
        # a registry lookup per flush)
        taped = self.tapenode is not None
        # liveness: outputs whose NDArray died (or was overwritten by an
        # in-place write) before the flush need no buffer at all
        live = []
        for ref, marker in self.outs:
            nd = ref()
            if nd is not None and nd._data is marker:
                live.append((nd, marker))
        needed = None if taped else tuple(m.index for _, m in live)
        if not taped and not live:
            # nothing observable: the whole segment is dead code — the
            # executable cache was never consulted (cache_hit=None)
            eng.on_bulk_flush(len(self.nodes), None,
                              (_perf_counter() - _t0) * 1e6)
            return
        # device id in the key: an exact-mode executable is PINNED to its
        # device (DeviceAssignment); same-signature segments on another
        # device must compile their own
        key = (self.fuse, taped, needed, self.ctx.device.id,
               tuple(self.nodes),
               tuple((tuple(v.shape), _np.dtype(v.dtype))
                     for v in self.ext_vals))
        fn = _segment_cache.get(key)
        hit = fn is not None
        try:
            if not hit:
                if self.fuse == "exact" and not taped:
                    # the disk-tier key is only built on a true
                    # in-memory miss — the steady-state flush never
                    # pays the repr
                    pkey = None if _persist_hooks is None else \
                        _segment_persist_key(needed, tuple(self.nodes),
                                             self.ext_vals)
                    fn = _compile_segment_exact(
                        tuple(self.nodes), needed, self.ext_vals,
                        self.ctx.device, persist_key=pkey)
                else:
                    fn = _compile_segment(tuple(self.nodes), taped,
                                          needed)
                _segment_cache.put(key, fn)
            if taped:
                vals, vjp_fn = fn(*self.ext_vals)
                node = self.tapenode
                node.vjp_fn = vjp_fn
                node.parents = list(self.ext_parents)
                node.out_avals = list(self.avals)
                for nd, marker in live:
                    nd._data = vals[marker.index]
            else:
                vals = fn(*self.ext_vals)
                for (nd, _), v in zip(live, vals):
                    nd._data = v
        except Exception as e:
            # errors surface at the sync point, as async errors do in the
            # reference engine; later reads of the orphaned outputs raise
            # via NDArray._read's pending barrier
            self.error = e
            raise
        eng.on_bulk_flush(len(self.nodes), hit,
                          (_perf_counter() - _t0) * 1e6)


@hot_path("dispatch")
def flush_segment() -> None:
    """Flush the calling thread's pending bulk segment, if any (the hook
    behind every sync point: reads, wait_for_var/wait_all, non-fusable
    ops, engine-type switches)."""
    seg = getattr(_tls, "seg", None)
    if seg is not None:
        seg.flush()


_install_flush_hook(flush_segment)


@hot_path("dispatch")
def _try_defer(op: Operator, nd_inputs: Sequence, kwargs: Dict[str, Any],
               ctx, eng):
    """Append this op application to the thread's pending segment instead
    of dispatching it.  Returns the pending output NDArray(s), or
    ``_NOT_FUSABLE`` — in which case the caller flushes (a non-fusable op
    is a sync point) and dispatches eagerly."""
    NDArray = _ND_CLS or _nd_cls()
    if not op.use_jit or op.vjp_maker is not None \
            or op.name in _SUBGRAPH_OPS:
        return _NOT_FUSABLE
    if op.needs_rng and op_takes_key(op, kwargs):
        return _NOT_FUSABLE          # sampling advances the RNG stream
    fuse = eng.bulk_fuse_mode
    rec = _autograd.is_recording()
    recording_op = False
    if rec:
        recording_op = any(x._ag is not None for x in nd_inputs)
        if recording_op:
            if fuse != "aggressive":
                # in exact mode the tape stays per-op (its vjp wrappers
                # are already one-dispatch each and trivially bitwise);
                # taped SEGMENTS — one jax.vjp over the fused forward —
                # are the aggressive mode's territory
                return _NOT_FUSABLE
            differentiable = op.differentiable(kwargs) \
                if callable(op.differentiable) else op.differentiable
            if not differentiable:
                # the unbulked path would NOT record this op; fusing it
                # into a taped segment would differentiate through it
                return _NOT_FUSABLE
    kwkey = () if not kwargs else \
        tuple(sorted((k, _canon(v)) for k, v in kwargs.items()))

    seg = getattr(_tls, "seg", None)
    # materialize VIEW inputs and any value not pending on OUR segment
    # BEFORE taking the segment lock: these reads can flush (a view's
    # root, or another thread's segment), and flushing a foreign segment
    # while holding ours would be an ABBA deadlock; our own pendings are
    # handled by reference below, so after this pass no read under the
    # lock can flush anything
    for x in nd_inputs:
        if x._base is not None:
            x._read()
        else:
            d = x._data
            if type(d) is PendingValue and d.segment is not seg:
                x._read()
    if seg is not None and (seg.flushed or seg.recording != rec
                            or seg.fuse != fuse or seg.ctx != ctx):
        # a segment is all-taped or all-untaped, one fuse mode, and
        # single-context.  seg.flushed covers another THREAD having
        # flushed our segment via a cross-thread read — flush() only
        # clears the flushing thread's own _tls pointer.
        seg.flush()
        seg = None
    if seg is None:
        seg = _BulkSegment(ctx, rec, fuse, eng.bulk_size)
        _tls.seg = seg

    # argument collection + node append, under the segment lock so a
    # cross-thread flush cannot interleave (it would capture the node
    # list without our outputs and orphan their pending markers).  A
    # restart happens via the aggressive-mode reduction barrier or a
    # racing flush; both swap in a fresh segment.
    tracer = _TRACER_CLS or _tracer_type()
    sds = _SDS_CLS or _sds_cls()
    seg._lock.acquire()
    try:
        while True:
            if seg.flushed:           # raced a cross-thread flush
                seg._lock.release()
                seg = _BulkSegment(ctx, rec, fuse, eng.bulk_size)
                _tls.seg = seg
                seg._lock.acquire()
            refs = []
            in_avals = []
            restart = False
            for x in nd_inputs:
                d = x._data if x._base is None else None
                if type(d) is PendingValue and d.segment is seg:
                    if seg.barrier[d.index]:
                        # consuming a reduction-like pending output:
                        # XLA's accumulation order inside a fused
                        # consumer is not bitwise-contractual (measured
                        # ~1-ulp drift on CPU for mean fused into its
                        # consumer), so aggressive fusion materializes
                        # the reduction first; exact mode never refuses
                        # kernels, never sets the flag, and its
                        # segments run longer
                        seg._flush_locked()
                        seg._lock.release()
                        seg = _BulkSegment(ctx, rec, fuse,
                                           eng.bulk_size)
                        _tls.seg = seg
                        seg._lock.acquire()
                        restart = True
                        break
                    refs.append((_NODE, d.index))
                    in_avals.append(seg.avals[d.index])
                else:
                    v = x._read()     # concrete (pre-pass): cannot flush
                    if isinstance(v, tracer):
                        return _NOT_FUSABLE  # under a jit trace
                    sh = getattr(v, "sharding", None)
                    if sh is not None and type(sh) is not sds \
                            and len(sh.device_set) > 1:
                        return _NOT_FUSABLE  # multi-chip global arrays
                    refs.append((_EXT, seg.add_ext_locked(
                        v, x._ag if rec else None)))
                    # jax arrays already expose tuple shapes + np dtypes
                    in_avals.append((v.shape, v.dtype))
            if not restart:
                break
        try:
            out_avals, multi = _infer_out_avals(op.name, kwkey,
                                                tuple(in_avals))
        except Exception:  # noqa: BLE001 — let the EAGER path raise
            return _NOT_FUSABLE      # the op's real error (exact parity)

        if recording_op and seg.tapenode is None:
            seg.tapenode = _autograd.TapeNode(
                "_BulkSegment", None, [], [], True, runner_safe=True)

        node_base = len(seg.avals)
        seg.nodes.append((op.name, kwkey, tuple(refs), multi))
        seg.avals.extend(out_avals)
        # aggressive mode only: an output with FEWER elements than the
        # op's largest input is reduction-like (sum/mean/max/slice/...),
        # as is anything in the explicit contraction set — consuming it
        # while still pending forces a flush (see above).
        if fuse != "aggressive":
            seg.barrier.extend(False for _ in out_avals)
        elif _is_barrier_op(op.name):
            seg.barrier.extend(True for _ in out_avals)
        else:
            max_in = max((_n_elems(s) for s, _ in in_avals), default=0)
            seg.barrier.extend(_n_elems(s) < max_in
                               for s, _ in out_avals)
        outs = []
        for i, (shp, dt) in enumerate(out_avals):
            marker = PendingValue(seg, node_base + i)
            nd = NDArray(marker, ctx=ctx, _shape=shp, _dtype=dt)
            seg.outs.append((_weakref.ref(nd), marker))
            if recording_op:
                nd._ag = _autograd.AGInfo(node=seg.tapenode,
                                          index=node_base + i)
            outs.append(nd)

        eng._c_bulked.n += 1          # inlined on_bulk_push (hot-path
        # idiom: a registry Counter's .n is a plain int — same cost as
        # the former private attribute add)
        if len(seg.nodes) >= seg.cap:
            seg._flush_locked()       # MXNET_ENGINE_BULK_SIZE cap
        return outs if multi else outs[0]
    finally:
        seg._lock.release()


def invoke(op: Operator, inputs: Sequence, kwargs: Dict[str, Any],
           out=None):
    """Dispatch an op imperatively (reference stack §3.1).

    Returns one NDArray, or a list for multi-output ops.  ``out=`` writes the
    (first) result into an existing NDArray in place.

    With bulking enabled (MXNET_EXEC_BULK_EXEC_TRAIN, the default), fusable
    ops are DEFERRED into a lazy segment and only materialize at a sync
    point — see ``_try_defer`` / ``_BulkSegment`` above.
    """
    NDArray = _ND_CLS or _nd_cls()
    if _invoke_hook is not None:
        inputs = _invoke_hook(op.name, inputs)

    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            ctx = x.context
            break
    if ctx is None:
        # zero-input creation ops carry ctx as an op attribute
        # (reference init_op.cc convention) — honor it for the tag too
        ckw = kwargs.get("ctx")
        if ckw is not None:
            from ..context import Context
            ctx = ckw if isinstance(ckw, Context) else Context.from_str(ckw)
        else:
            ctx = current_context()
    nd_inputs = [_as_nd(x, ctx) for x in inputs]
    eng = engine()
    # listeners (profiler/monitor) need REAL per-op outputs — Monitor's
    # stat_func inspects every dispatched value — so bulking suspends
    # while any listener is installed; engine().stats() still aggregates
    if out is None and not eng._listeners and eng.bulk_enabled:
        res = _try_defer(op, nd_inputs, kwargs, ctx, eng)
        if res is not _NOT_FUSABLE:
            return res
    # a non-fusable op (or out=/disabled bulking/NaiveEngine) is a flush
    # point: the pending segment's effects must precede this dispatch
    flush_segment()
    in_vals = [x._read() for x in nd_inputs]
    if op_takes_key(op, kwargs):
        # sampling ops take a PRNG key as their last input; eager dispatch
        # draws it here (under a hybrid trace, next_key() yields a TRACED
        # subkey of the CachedOp's key argument — push_key in random.py —
        # so compiled graphs stay fresh per call)
        from .. import random as _grandom
        in_vals.append(_grandom.next_key())

    differentiable = op.differentiable(kwargs) \
        if callable(op.differentiable) else op.differentiable
    recording = (_autograd.is_recording() and differentiable
                 and any(getattr(x, "_ag", None) is not None
                         for x in nd_inputs))
    # timing only when someone is listening (profiler) — invoke is the
    # hottest path in the library
    _timed = bool(eng._listeners)
    _t0 = _perf_counter() if _timed else 0.0
    if recording:
        vjp_wrapper, runner_safe = op.get_vjp_fn(kwargs)
        out_vals, vjp_fn = vjp_wrapper(*in_vals)
    else:
        out_vals = op.get_fn(kwargs)(*in_vals)
    _dispatch_us = (_perf_counter() - _t0) * 1e6 if _timed else 0.0

    multi = isinstance(out_vals, (tuple, list))
    raw_outs = list(out_vals) if multi else [out_vals]
    outs = [NDArray(v, ctx=ctx) for v in raw_outs]

    if recording:
        parents = [getattr(x, "_ag", None) for x in nd_inputs]
        node = _autograd.TapeNode(op.name, vjp_fn, parents,
                                  [(o.shape, o.dtype) for o in outs], multi,
                                  runner_safe=runner_safe)
        for i, o in enumerate(outs):
            o._ag = _autograd.AGInfo(node=node, index=i)

    eng.on_push(op.name, raw_outs, _dispatch_us)

    if out is not None:
        outs_for_write = outs if multi else [outs[0]]
        targets = out if isinstance(out, (list, tuple)) else [out]
        for tgt, src in zip(targets, outs_for_write):
            val = src._read()
            # out= keeps the target's dtype (an AMP cast hook may have
            # changed the compute dtype; the write-back contract wins)
            if val.dtype != tgt.dtype:
                val = val.astype(tgt.dtype)
            tgt._set_data(val)
        return out
    return outs if multi else outs[0]


def invoke_by_name(name: str, inputs: Sequence, kwargs: Dict[str, Any],
                   out=None):
    return invoke(get_op(name), inputs, kwargs, out=out)


# scalar fallbacks for the arithmetic dunders: (forward op, reflected op)
_SCALAR_MAP = {
    "broadcast_add": ("_plus_scalar", "_plus_scalar"),
    "broadcast_sub": ("_minus_scalar", "_rminus_scalar"),
    "broadcast_mul": ("_mul_scalar", "_mul_scalar"),
    "broadcast_div": ("_div_scalar", "_rdiv_scalar"),
    "broadcast_mod": ("_mod_scalar", "_rmod_scalar"),
    "broadcast_power": ("_power_scalar", "_rpower_scalar"),
    "broadcast_equal": ("_equal_scalar", "_equal_scalar"),
    "broadcast_not_equal": ("_not_equal_scalar", "_not_equal_scalar"),
    "broadcast_greater": ("_greater_scalar", "_lesser_scalar"),
    "broadcast_greater_equal": ("_greater_equal_scalar", "_lesser_equal_scalar"),
    "broadcast_lesser": ("_lesser_scalar", "_greater_scalar"),
    "broadcast_lesser_equal": ("_lesser_equal_scalar", "_greater_equal_scalar"),
}


def invoke_binary(name: str, lhs, rhs, reverse: bool = False):
    """Binary dunder dispatch: NDArray⊕NDArray uses the broadcast op;
    NDArray⊕scalar uses the ``_*_scalar`` variant with the scalar passed as a
    0-d array input (keeps one XLA compilation per shape, not per constant)."""
    from .ndarray import NDArray
    if isinstance(rhs, NDArray):
        args = [rhs, lhs] if reverse else [lhs, rhs]
        return invoke_by_name(name, args, {})
    if isinstance(rhs, (_np.ndarray, list)):
        args = [rhs, lhs] if reverse else [lhs, rhs]
        return invoke_by_name(name, args, {})
    fwd, rev = _SCALAR_MAP[name]
    sop = rev if reverse else fwd
    scal = _np.asarray(rhs)
    return invoke_by_name(sop, [lhs, scal], {})


@functools.lru_cache(maxsize=None)
def _maker_param_names(op: Operator) -> Tuple[str, ...]:
    import inspect
    try:
        return tuple(
            p.name for p in inspect.signature(op.maker).parameters.values()
            if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                          inspect.Parameter.KEYWORD_ONLY))
    except (TypeError, ValueError):
        return ()


_JAX_ARRAY_CLS = None


def _is_param_value(v) -> bool:
    """Positional values that are op PARAMETERS, not tensor inputs.
    Tuples are parameters (shape/axes); plain lists stay tensor-ish
    (mx.nd converts lists to arrays)."""
    global _JAX_ARRAY_CLS
    if _JAX_ARRAY_CLS is None:
        import jax
        _JAX_ARRAY_CLS = jax.Array
    if isinstance(v, (bool, int, float, str, tuple, _np.generic)):
        return True
    if isinstance(v, (_np.ndarray, _JAX_ARRAY_CLS, list)):
        return False
    if hasattr(v, "_heads"):                # Symbol (duck-typed: symbol
        return False                        # imports this module)
    return not isinstance(v, _ND_CLS or _nd_cls())


def split_positional_params(op: Operator, args: Sequence,
                            kwargs: Dict[str, Any]):
    """Reference-parity calling convention for generated wrappers: the
    C-side registry gave each wrapper an explicit signature
    ``op(data..., param1, param2, ...)``, so trailing non-tensor
    positionals map onto the op's parameters in maker-declaration order
    (``nd.sum(x, 1)`` ≡ ``nd.sum(x, axis=1)``)."""
    inputs = list(args)
    split = len(inputs)
    while split > 0 and _is_param_value(inputs[split - 1]):
        split -= 1
    extra = inputs[split:]
    if not extra:
        return inputs, kwargs
    names = _maker_param_names(op)
    if len(extra) > len(names):
        return inputs, kwargs               # unmappable: legacy behavior
    for n, v in zip(names, extra):
        if n in kwargs:
            raise TypeError(
                f"{op.name}() got multiple values for argument {n!r}")
        kwargs[n] = v
    return inputs[:split], kwargs


def make_frontend(op: Operator) -> Callable:
    """Build the user-facing ``mx.nd.<op>`` function."""
    def frontend(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)        # accepted for symbol-API symmetry
        inputs, kwargs = split_positional_params(op, args, kwargs)
        return invoke(op, inputs, kwargs, out=out)
    frontend.__name__ = op.name
    frontend.__qualname__ = op.name
    frontend.__doc__ = op.doc
    return frontend
