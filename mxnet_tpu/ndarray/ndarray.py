"""NDArray: a mutable, asynchronous tensor over immutable XLA buffers.

Reference role: include/mxnet/ndarray.h + src/ndarray/ — ref-counted Chunk,
zero-copy views, async read/write ordered by the dependency engine
(SURVEY.md §2.1, §7 "Design stance").

TPU-native design (the survey's hardest-ranked problem): a ``jax.Array`` is
immutable and asynchronously computed.  ``NDArray`` therefore holds
``(buffer, version)``; every in-place op produces a *new* buffer and bumps the
version — XLA donation makes this cheap under jit, and conflicting writes are
serialized by the version update itself, which replaces the reference's
engine-side write-var queueing.  Views (``reshape``/basic slicing) are lazy
``(base, view-spec)`` pairs: reads materialize through the spec and are cached
against the root version; writes scatter back into the root buffer
(``.at[key].set``), so MXNet's write-through aliasing is preserved.  Reads are
async exactly as the reference's: jax values are futures, and ``asnumpy()`` /
``wait_to_read()`` are the sync points.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, dtype_np, jax_compute_dtype, default_dtype
from ..context import Context, current_context
from ..engine import PendingValue as _PendingValue
from .. import autograd as _autograd

__all__ = ["NDArray", "array", "from_jax", "zeros", "ones", "empty", "full",
           "arange", "zeros_like", "ones_like", "concat_context_check"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _is_bool_mask(key) -> bool:
    """A 1-D boolean array key (numpy or jax) selecting leading-axis rows."""
    dt = getattr(key, "dtype", None)
    return dt is not None and _np.dtype(dt) == _np.bool_ \
        and getattr(key, "ndim", 0) == 1


def _mask_to_rows(key, shape) -> _np.ndarray:
    """Validate a boolean mask against axis 0 and materialize row indices
    (numpy/reference contract: mismatched length is an IndexError, never a
    silent clamp)."""
    key = _np.asarray(key)
    if key.shape[0] != shape[0]:
        raise IndexError(
            f"boolean index of length {key.shape[0]} does not match "
            f"axis 0 of shape {shape}")
    return _np.nonzero(key)[0]


def _is_basic_index(key) -> bool:
    if isinstance(key, (int, slice, type(Ellipsis), type(None), _np.integer)):
        return True
    if isinstance(key, tuple):
        return all(_is_basic_index(k) for k in key)
    return False


class NDArray:
    """Mutable n-dimensional array resident on a TPU/CPU device."""

    __slots__ = ("_data", "_ctx", "_version", "_ag", "_base", "_viewspec",
                 "_cache", "_shape", "_dtype", "__weakref__")

    # make NDArray win over numpy in mixed binary ops
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, _base=None,
                 _viewspec=None, _shape=None, _dtype=None):
        self._data = data            # jax.Array (None when this is a view)
        self._ctx = ctx if ctx is not None else current_context()
        self._version = 0
        self._ag = None              # autograd.AGInfo
        self._base = _base           # parent NDArray when this is a view
        self._viewspec = _viewspec   # ("reshape", shape) | ("slice", key)
        self._cache = None           # (root_version, materialized value)
        if _shape is not None:
            self._shape = tuple(_shape)
            self._dtype = _dtype
        else:
            self._shape = tuple(data.shape)
            self._dtype = _np.dtype(data.dtype)

    # ------------------------------------------------------------------
    # buffer discipline
    # ------------------------------------------------------------------
    def _root(self) -> "NDArray":
        nd = self
        while nd._base is not None:
            nd = nd._base
        return nd

    def _read(self):
        """Current jax value (possibly an in-flight future).

        The pending-value barrier: if this array's producer sits in an
        unflushed bulk segment (register.py), the whole segment executes
        as one fused dispatch before the value is returned — reads are
        sync points exactly as in the reference engine."""
        if self._base is None:
            d = self._data
            if type(d) is _PendingValue:
                d.segment.flush()
                d = self._data
                if type(d) is _PendingValue:
                    raise MXNetError(
                        "bulked segment failed at an earlier sync point: "
                        f"{d.segment.error!r}")
            return d
        rootver = self._root()._version
        if self._cache is not None and self._cache[0] == rootver:
            return self._cache[1]
        parent = self._base._read()
        op, arg = self._viewspec
        val = parent.reshape(arg) if op == "reshape" else parent[arg]
        self._cache = (rootver, val)
        return val

    def _set_data(self, val) -> None:
        """Replace contents (the in-place write primitive).

        On a view, scatters back through the view chain into the root buffer,
        so sibling views observe the write — MXNet's shared-memory semantics.
        """
        if self._base is None:
            self._data = val
            self._version += 1
        else:
            parent = self._base._read()
            op, arg = self._viewspec
            if op == "reshape":
                newp = val.reshape(parent.shape)
            else:
                newp = parent.at[arg].set(val)
            self._base._set_data(newp)
            self._cache = (self._root()._version, val)

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def size(self) -> int:
        n = 1
        for s in self._shape:
            n *= s
        return n

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    def tostype(self, stype: str):
        """Convert storage type (reference: NDArray.tostype)."""
        if stype == "default":
            return self
        from ..sparse import cast_storage
        return cast_storage(self, stype)

    @property
    def grad(self) -> Optional["NDArray"]:
        info = self._ag
        return info.grad if info is not None and info.is_variable else None

    @property
    def T(self) -> "NDArray":
        from . import transpose
        return transpose(self)

    def __repr__(self):
        # repr is an interactive/debug surface — materializing IS
        # the point
        # mxlint: disable=hidden-host-sync — interactive repr
        return (f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self._shape))}"
                f" @{self._ctx}>")

    def __len__(self):
        if not self._shape:
            raise TypeError("len() of 0-d NDArray")
        return self._shape[0]

    # ------------------------------------------------------------------
    # sync / conversion
    # ------------------------------------------------------------------
    def asnumpy(self) -> _np.ndarray:
        """Copy to host memory; blocks until the value is computed
        (reference sync point: NDArray::SyncCopyToCPU)."""
        return _np.asarray(self._read())

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar-sized")
        # THE documented sync point for scalars (reference
        # NDArray::SyncCopyToCPU semantics) — callers opt in
        # mxlint: disable=hidden-host-sync — the sanctioned sync API
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def wait_to_read(self) -> None:
        """Block until this array's value is ready (Engine::WaitForVar)."""
        import jax
        jax.block_until_ready(self._read())

    def __array__(self, dtype=None):
        # np-protocol boundary: numpy asked for host memory
        # mxlint: disable=hidden-host-sync — numpy protocol hook
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------------
    # movement / copies
    # ------------------------------------------------------------------
    def copyto(self, other) -> "NDArray":
        """Copy into an existing NDArray or onto a Context."""
        import jax
        if isinstance(other, Context):
            val = jax.device_put(self._read(), other.device)
            return NDArray(val, ctx=other)
        if not isinstance(other, NDArray):
            raise TypeError(f"copyto target must be NDArray/Context, got {type(other)}")
        if other.shape != self.shape:
            raise ValueError(f"shape mismatch {self.shape} vs {other.shape}")
        val = self._read()
        if other.dtype != self.dtype:
            val = val.astype(_np.dtype(other.dtype))
        val = jax.device_put(val, other.context.device)
        other._set_data(val)
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copy(self) -> "NDArray":
        return NDArray(self._read(), ctx=self._ctx)

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        npdt = jax_compute_dtype(dtype)   # documented int64->int32 contract
        if not copy and npdt == self.dtype:
            return self
        return NDArray(self._read().astype(npdt), ctx=self._ctx)

    def detach(self) -> "NDArray":
        out = NDArray(self._read(), ctx=self._ctx) if self._base is None else \
            NDArray(None, ctx=self._ctx, _base=self._base,
                    _viewspec=self._viewspec, _shape=self._shape,
                    _dtype=self._dtype)
        if self._base is None:
            out._data = self._data
        return out

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        """Allocate a gradient buffer and mark this array as a variable."""
        g = zeros(self._shape, ctx=self._ctx, dtype=self._dtype)
        self._ag = _autograd.AGInfo(node=None, index=0, grad=g,
                                    grad_req=grad_req)

    def backward(self, out_grad: Optional["NDArray"] = None,
                 retain_graph: bool = False, train_mode: bool = True) -> None:
        _autograd.backward([self], [out_grad], retain_graph=retain_graph,
                           train_mode=train_mode)

    # ------------------------------------------------------------------
    # views & indexing
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        shape = _infer_reshape(self._shape, tuple(int(s) for s in shape),
                               reverse=bool(kwargs.get("reverse", False)))
        if _autograd.is_recording():
            from .register import invoke_by_name
            return invoke_by_name("reshape", [self], {"shape": shape})
        dt = self._dtype
        return NDArray(None, ctx=self._ctx, _base=self,
                       _viewspec=("reshape", shape), _shape=shape, _dtype=dt)

    def reshape_like(self, other) -> "NDArray":
        return self.reshape(other.shape)

    def expand_dims(self, axis: int) -> "NDArray":
        from . import expand_dims
        return expand_dims(self, axis=axis)

    def squeeze(self, axis=None) -> "NDArray":
        from . import squeeze
        return squeeze(self, axis=axis)

    def flatten(self) -> "NDArray":
        return self.reshape((self._shape[0], -1)) if self.ndim > 1 else self

    def slice(self, begin, end, step=None) -> "NDArray":
        from . import slice as _slice
        return _slice(self, begin=begin, end=end, step=step)

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._read()
        if isinstance(key, list):              # bool lists are masks too
            key = _np.asarray(key)
        if _is_bool_mask(key):
            # boolean-mask indexing (reference ndarray.py advanced
            # indexing): data-dependent output shape, so the mask is
            # materialized host-side into integer rows — same eager
            # stance as boolean_mask the op
            key = _mask_to_rows(key, self._shape)
        if _is_basic_index(key):
            if _autograd.is_recording():
                from .register import invoke_by_name
                return invoke_by_name("_basic_index", [self], {"key": _freeze_key(key)})
            val_shape = _index_shape(self._shape, key)
            return NDArray(None, ctx=self._ctx, _base=self,
                           _viewspec=("slice", key), _shape=val_shape,
                           _dtype=self._dtype)
        # advanced indexing → gather copy (differentiable through the op path)
        from .register import invoke_by_name
        return invoke_by_name("_advanced_index", [self, array(key, ctx=self._ctx)], {})

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            key = key._read()
        if isinstance(key, list):
            key = _np.asarray(key)
        if _is_bool_mask(key):
            key = _mask_to_rows(key, self._shape)
        if isinstance(value, NDArray):
            value = value._read()
        cur = self._read()
        if isinstance(value, (int, float, bool, _np.generic)):
            new = cur.at[key].set(_jnp().asarray(value, dtype=cur.dtype))
        else:
            new = cur.at[key].set(_jnp().asarray(value).astype(cur.dtype))
        self._set_data(new)

    # ------------------------------------------------------------------
    # arithmetic — routed through the op registry so autograd records them
    # ------------------------------------------------------------------
    def _binop(self, name, other, reverse=False):
        from .register import invoke_binary
        return invoke_binary(name, self, other, reverse=reverse)

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    def __radd__(self, o):
        return self._binop("broadcast_add", o, reverse=True)

    def __iadd__(self, o):
        r = self._binop("broadcast_add", o)
        self._set_data(r._read())
        return self

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, reverse=True)

    def __isub__(self, o):
        r = self._binop("broadcast_sub", o)
        self._set_data(r._read())
        return self

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binop("broadcast_mul", o, reverse=True)

    def __imul__(self, o):
        r = self._binop("broadcast_mul", o)
        self._set_data(r._read())
        return self

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, reverse=True)

    def __itruediv__(self, o):
        r = self._binop("broadcast_div", o)
        self._set_data(r._read())
        return self

    def __mod__(self, o):
        return self._binop("broadcast_mod", o)

    def __rmod__(self, o):
        return self._binop("broadcast_mod", o, reverse=True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __rpow__(self, o):
        return self._binop("broadcast_power", o, reverse=True)

    def __neg__(self):
        from .register import invoke_by_name
        return invoke_by_name("negative", [self], {})

    def __abs__(self):
        from .register import invoke_by_name
        return invoke_by_name("abs", [self], {})

    def __eq__(self, o):
        return self._binop("broadcast_equal", o)

    def __ne__(self, o):
        return self._binop("broadcast_not_equal", o)

    def __lt__(self, o):
        return self._binop("broadcast_lesser", o)

    def __le__(self, o):
        return self._binop("broadcast_lesser_equal", o)

    def __gt__(self, o):
        return self._binop("broadcast_greater", o)

    def __ge__(self, o):
        return self._binop("broadcast_greater_equal", o)

    def __and__(self, o):
        return self._binop("broadcast_logical_and", o)

    def __rand__(self, o):
        return self._binop("broadcast_logical_and", o, reverse=True)

    def __or__(self, o):
        return self._binop("broadcast_logical_or", o)

    def __ror__(self, o):
        return self._binop("broadcast_logical_or", o, reverse=True)

    def __xor__(self, o):
        return self._binop("broadcast_logical_xor", o)

    def __rxor__(self, o):
        return self._binop("broadcast_logical_xor", o, reverse=True)

    def __invert__(self):
        from .register import invoke_by_name
        return invoke_by_name("logical_not", [self], {})

    __hash__ = None  # mutable

    # reductions / convenience mirrors of mx.nd methods
    def sum(self, axis=None, keepdims=False):
        from . import sum as _sum
        return _sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        from . import mean as _mean
        return _mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        from . import max as _max
        return _max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        from . import min as _min
        return _min(self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None):
        from . import argmax
        return argmax(self, axis=axis)

    def argmin(self, axis=None):
        from . import argmin
        return argmin(self, axis=axis)

    def transpose(self, axes=None):
        from . import transpose
        return transpose(self, axes=axes)

    def dot(self, other):
        from . import dot
        return dot(self, other)

    # clip/relu/sigmoid/exp/log/sqrt/square/softmax/one_hot/tile are
    # attached by the generic fluent loop in __init__.py (full frontend
    # kwargs incl. out=) — hand-written duplicates were deleted

    def broadcast_to(self, shape):
        from . import broadcast_to
        return broadcast_to(self, shape=shape)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _infer_reshape(old: Tuple[int, ...], new: Tuple[int, ...],
                   reverse: bool = False) -> Tuple[int, ...]:
    """Resolve MXNet reshape placeholders (0/-1/-2/-3/-4) — delegates to the
    shared resolver in base so the op path and this view path agree."""
    from ..base import resolve_reshape_spec
    return resolve_reshape_spec(old, new, reverse)


def _freeze_key(key):
    """Make an index key hashable for the jit cache."""
    if isinstance(key, list):
        return tuple(key)
    if isinstance(key, tuple):
        return tuple(_freeze_key(k) for k in key)
    if isinstance(key, slice):
        return ("__slice__", key.start, key.stop, key.step)
    return key


def _thaw_key(key):
    if isinstance(key, tuple):
        if len(key) == 4 and key[0] == "__slice__":
            return slice(key[1], key[2], key[3])
        return tuple(_thaw_key(k) for k in key)
    return key


def _index_shape(shape, key) -> Tuple[int, ...]:
    return _np.empty(shape, dtype=_np.bool_)[key].shape


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

def from_jax(val, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(val, ctx=ctx)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference: mx.nd.array)."""
    import jax
    ctx = ctx if ctx is not None else current_context()
    if isinstance(source, NDArray):
        val = source._read()
        if dtype is not None:
            val = val.astype(jax_compute_dtype(dtype))
        return NDArray(jax.device_put(val, ctx.device), ctx=ctx)
    if dtype is None:
        if isinstance(source, _np.ndarray):
            npv = source
            if npv.dtype == _np.float64:
                npv = npv.astype(_np.float32)  # MXNet default dtype is float32
        else:
            # python lists/scalars default to float32 (MXNet convention)
            npv = _np.asarray(source)
            if npv.dtype.kind in "ifu" and npv.dtype != _np.float32:
                npv = npv.astype(_np.float32)
    else:
        # build at the REQUESTED width first, then cast to the jax
        # compute dtype: asarray(python_ints, int32) raises OverflowError
        # past 2^31, while the documented large-tensor contract is
        # wraparound truncation (what jax's own canonicalization did)
        npv = _np.asarray(source, dtype=dtype_np(dtype))
        jcd = jax_compute_dtype(dtype)
        if jcd != npv.dtype:
            npv = npv.astype(jcd)
    return NDArray(jax.device_put(npv, ctx.device), ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    import jax
    ctx = ctx if ctx is not None else current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(ctx.device):
        val = _jnp().zeros(shape, dtype=jax_compute_dtype(dtype))
    return NDArray(val, ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    import jax
    ctx = ctx if ctx is not None else current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(ctx.device):
        val = _jnp().ones(shape, dtype=jax_compute_dtype(dtype))
    return NDArray(val, ctx=ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    import jax
    ctx = ctx if ctx is not None else current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(ctx.device):
        out = _jnp().full(shape, val, dtype=jax_compute_dtype(dtype))
    return NDArray(out, ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.device):
        val = _jnp().arange(start, stop, step, dtype=jax_compute_dtype(dtype))
        if repeat != 1:
            val = _jnp().repeat(val, repeat)
    return NDArray(val, ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    """Identity-like matrix (reference mx.nd.eye: M=0 means square)."""
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.device):
        val = _jnp().eye(int(N), int(M) if M else int(N), k=int(k),
                         dtype=jax_compute_dtype(dtype))
    return NDArray(val, ctx=ctx)


def moveaxis(data: "NDArray", source, destination) -> NDArray:
    """Reference mx.nd.moveaxis — thin transpose wrapper."""
    return NDArray(_jnp().moveaxis(data._read(), source, destination),
                   ctx=data.context)


def linspace(start, stop, num, endpoint=True, ctx=None,
             dtype=None) -> NDArray:
    import jax
    ctx = ctx if ctx is not None else current_context()
    with jax.default_device(ctx.device):
        val = _jnp().linspace(start, stop, int(num), endpoint=endpoint,
                              dtype=jax_compute_dtype(dtype))
    return NDArray(val, ctx=ctx)


def zeros_like(other: NDArray) -> NDArray:
    return zeros(other.shape, ctx=other.context, dtype=other.dtype)


def ones_like(other: NDArray) -> NDArray:
    return ones(other.shape, ctx=other.context, dtype=other.dtype)


def concat_context_check(arrays: Sequence[NDArray]) -> Context:
    ctxs = {a.context for a in arrays}
    if len(ctxs) != 1:
        raise MXNetError(f"arrays live on different contexts: {ctxs}")
    return next(iter(ctxs))
