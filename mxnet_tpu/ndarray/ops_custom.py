"""The ``Custom`` registry op: python-callback operators inside graphs.

Reference role: src/operator/custom/custom.cc — the "Custom" op that
looks up a registered ``CustomOpProp`` by ``op_type`` and runs the user's
Python ``forward``/``backward`` from within a composed graph, which is
how Symbol-era models embedded python losses/layers.

TPU-native design: the reference ran the callback on a dedicated engine
thread so the async engine kept flowing; under XLA the graph is a single
compiled computation, so the callback becomes a ``jax.pure_callback``
(host round-trip at the op's position in the graph) wrapped in a
``jax.custom_vjp`` whose backward is a second pure_callback into the
user's ``backward`` — jit/symbol-executor compatible, gradients exact.
Eager ``mx.nd.Custom`` keeps the tape-bridge in mxnet_tpu/operator.py
(no host round-trip needed there); this op serves the SYMBOL path, the
C ABI (MXImperativeInvoke of "Custom"), and hybridized graphs.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .register import register_op


def _register():
    import jax

    def custom_maker(op_type=None, _training=False, **user_kwargs):
        def fn(*ins):
            from ..operator import _custom_registry
            if op_type not in _custom_registry:
                raise MXNetError(
                    f"unknown custom op_type {op_type!r}; registered: "
                    f"{sorted(_custom_registry)}")
            prop = _custom_registry[op_type](**user_kwargs)
            in_shapes = [tuple(x.shape) for x in ins]
            in_dtypes = [_np.dtype(x.dtype) for x in ins]
            default_dt = in_dtypes[0] if in_dtypes else \
                _np.dtype(_np.float32)        # zero-input custom source op
            _, out_shapes, _ = prop.infer_shape(
                [list(s) for s in in_shapes])
            try:
                _, out_types, _ = prop.infer_type(list(in_dtypes))
            except (NotImplementedError, IndexError):
                out_types = [default_dt] * len(out_shapes)
            out_struct = tuple(
                jax.ShapeDtypeStruct(
                    tuple(s),
                    out_types[i] if i < len(out_types)
                    and out_types[i] is not None else default_dt)
                for i, s in enumerate(out_shapes))
            in_struct = tuple(
                jax.ShapeDtypeStruct(tuple(s), in_dtypes[i])
                for i, s in enumerate(in_shapes))
            n_in, n_out = len(in_shapes), len(out_shapes)

            def _nd(a):
                from .ndarray import array
                return array(_np.asarray(a))

            # ONE operator instance per graph node, shared by the
            # forward and backward callbacks (custom.cc semantics): ops
            # that stash state on self in forward read it in backward
            op_box = {}

            def _the_op():
                if "op" not in op_box:
                    from ..context import current_context
                    op_box["op"] = prop.create_operator(
                        current_context(), [list(s) for s in in_shapes],
                        list(in_dtypes))
                return op_box["op"]

            def host_forward(*np_ins):
                from .. import autograd as _ag
                from .ndarray import zeros as nd_zeros
                op = _the_op()
                ins_nd = [_nd(a) for a in np_ins]
                outs = [nd_zeros(tuple(s)) for s in out_shapes]
                with _ag.pause():
                    op.forward(is_train=bool(_training),
                               req=["write"] * n_out, in_data=ins_nd,
                               out_data=outs, aux=[])
                return tuple(
                    _np.asarray(o.asnumpy(), out_struct[i].dtype)
                    for i, o in enumerate(outs))

            def host_backward(*flat):
                from .. import autograd as _ag
                from .ndarray import zeros as nd_zeros
                op = _the_op()
                ins_nd = [_nd(a) for a in flat[:n_in]]
                outs_nd = [_nd(a) for a in flat[n_in:n_in + n_out]]
                cts_nd = [_nd(a) for a in flat[n_in + n_out:]]
                igrads = [nd_zeros(tuple(s)) for s in in_shapes]
                with _ag.pause():
                    op.backward(req=["write"] * n_in, out_grad=cts_nd,
                                in_data=ins_nd, out_data=outs_nd,
                                in_grad=igrads, aux=[])
                return tuple(
                    _np.asarray(g.asnumpy(), in_struct[i].dtype)
                    for i, g in enumerate(igrads))

            def call_fwd(*args):
                return tuple(jax.pure_callback(host_forward, out_struct,
                                               *args))

            cfn = jax.custom_vjp(call_fwd)

            def vjp_fwd(*args):
                outs = call_fwd(*args)
                return outs, (args, outs)

            def vjp_bwd(res, cts):
                args, outs = res
                grads = jax.pure_callback(host_backward, in_struct,
                                          *args, *outs, *cts)
                return tuple(grads)

            cfn.defvjp(vjp_fwd, vjp_bwd)
            out = cfn(*ins)
            return out if n_out > 1 else out[0]
        return fn
    # use_jit=False: user kwargs may be unhashable and the body is a host
    # callback — there is nothing for a per-op jit to fuse; under an outer
    # jitted graph the callback is staged into that compilation anyway
    register_op("Custom", custom_maker, use_jit=False,
                ref="src/operator/custom/custom.cc")


_register()
