"""Linear-algebra operators (reference: src/operator/tensor/la_op.cc —
``mx.nd.linalg_*``, SURVEY.md §2.2).

All map 1:1 onto jax.numpy.linalg / lax.linalg, which XLA lowers to the
TPU's native QR/Cholesky/triangular-solve paths; batch dims broadcast the
way the reference's batched LAPACK wrappers did.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op, simple_op


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def gemm_maker(transpose_a=False, transpose_b=False, alpha=1.0,
                   beta=1.0, axis=-2):
        def fn(a, b, c):
            av = jnp.swapaxes(a, -1, -2) if transpose_a else a
            bv = jnp.swapaxes(b, -1, -2) if transpose_b else b
            return alpha * jnp.matmul(av, bv) + beta * c
        return fn
    register_op("linalg_gemm", gemm_maker)
    # linalg_gemm2 already lives in ops_matrix.py (batch_dot's sibling)

    def potrf_maker(lower=True):
        def fn(a):
            l = jnp.linalg.cholesky(a)
            return l if lower else jnp.swapaxes(l, -1, -2)
        return fn
    register_op("linalg_potrf", potrf_maker)

    def potri_maker(lower=True):
        # inverse from the Cholesky factor: A^-1 where A = L L^T
        def fn(l):
            lv = l if lower else jnp.swapaxes(l, -1, -2)
            eye = jnp.broadcast_to(jnp.eye(lv.shape[-1], dtype=lv.dtype),
                                   lv.shape)
            linv = lax.linalg.triangular_solve(
                lv, eye, left_side=True, lower=True)
            return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)
        return fn
    register_op("linalg_potri", potri_maker)

    def trsm_maker(transpose=False, rightside=False, lower=True,
                   alpha=1.0):
        def fn(a, b):
            out = lax.linalg.triangular_solve(
                a, alpha * b, left_side=not rightside, lower=lower,
                transpose_a=transpose)
            return out
        return fn
    register_op("linalg_trsm", trsm_maker)

    def trmm_maker(transpose=False, rightside=False, lower=True,
                   alpha=1.0):
        def fn(a, b):
            tri = jnp.tril(a) if lower else jnp.triu(a)
            if transpose:
                tri = jnp.swapaxes(tri, -1, -2)
            return alpha * (jnp.matmul(b, tri) if rightside
                            else jnp.matmul(tri, b))
        return fn
    register_op("linalg_trmm", trmm_maker)

    def syrk_maker(transpose=False, alpha=1.0):
        def fn(a):
            at = jnp.swapaxes(a, -1, -2)
            return alpha * (jnp.matmul(at, a) if transpose
                            else jnp.matmul(a, at))
        return fn
    register_op("linalg_syrk", syrk_maker)

    def gelqf_maker():
        # LQ decomposition: A = L Q (reference returns (Q, L))
        def fn(a):
            q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
            return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)
        return fn
    register_op("linalg_gelqf", gelqf_maker)

    simple_op("linalg_sumlogdiag",
              lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2,
                                                     axis2=-1)), axis=-1))

    def extractdiag_maker(offset=0):
        def fn(a):
            return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)
        return fn
    register_op("linalg_extractdiag", extractdiag_maker)

    def makediag_maker(offset=0):
        def fn(a):
            base = jnp.zeros(a.shape[:-1] + (a.shape[-1] + abs(offset),) * 2,
                             dtype=a.dtype)
            idx = jnp.arange(a.shape[-1])
            r = idx + max(-offset, 0)
            c = idx + max(offset, 0)
            return base.at[..., r, c].set(a)
        return fn
    register_op("linalg_makediag", makediag_maker)

    def extracttrian_maker(offset=0, lower=True):
        def fn(a):
            n = a.shape[-1]
            rows, cols = _np.tril_indices(n, k=offset) if lower else \
                _np.triu_indices(n, k=offset)
            return a[..., rows, cols]
        return fn
    register_op("linalg_extracttrian", extracttrian_maker)

    def maketrian_maker(offset=0, lower=True):
        def fn(a):
            # invert extracttrian: k elements -> n x n triangle
            k = a.shape[-1]
            n = int(round((_np.sqrt(8 * k + 1) - 1) / 2))
            if lower and offset < 0 or not lower and offset > 0:
                n += abs(offset)
            rows, cols = _np.tril_indices(n, k=offset) if lower else \
                _np.triu_indices(n, k=offset)
            base = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
            return base.at[..., rows, cols].set(a)
        return fn
    register_op("linalg_maketrian", maketrian_maker)

    simple_op("linalg_inverse", jnp.linalg.inv)
    simple_op("linalg_det", jnp.linalg.det)

    def slogdet_fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return sign, logdet
    simple_op("linalg_slogdet", slogdet_fn)

    def syevd_fn(a):
        # reference la_op.cc syevd: A = U^T * diag(L) * U with the ROWS
        # of U as eigenvectors (jnp.linalg.eigh returns columns, so U is
        # the transpose), eigenvalues ascending.  symmetrize_input=False
        # matches LAPACK 'L' — only the lower triangle is read, as the
        # reference documents.  eigh has a defined JVP, so autograd works
        # away from degeneracies.
        w, v = jnp.linalg.eigh(a, symmetrize_input=False)
        return jnp.swapaxes(v, -1, -2), w
    simple_op("linalg_syevd", syevd_fn)

    def khatri_rao_fn(*mats):
        # column-wise Kronecker product (reference: khatri_rao op)
        out = mats[0]
        for m in mats[1:]:
            out = (out[:, None, :] * m[None, :, :]).reshape(
                -1, out.shape[-1])
        return out
    simple_op("khatri_rao", khatri_rao_fn)

    # reference canonical names are the underscore forms (_linalg_gemm
    # etc. in src/operator/tensor/la_op.cc); public linalg_* are aliases
    from .register import add_alias
    for base in ("gemm", "gemm2", "potrf", "potri", "trsm", "trmm",
                 "syrk", "gelqf", "sumlogdiag", "extractdiag", "makediag",
                 "extracttrian", "maketrian", "inverse", "det",
                 "slogdet", "syevd"):
        add_alias(f"linalg_{base}", f"_linalg_{base}")


_register()
