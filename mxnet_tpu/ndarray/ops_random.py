"""Sampling operators: the ``_random_*`` / ``_sample_*`` registry families.

Reference role: src/operator/random/sample_op.cc (scalar-parameter draws),
src/operator/random/multisample_op.cc (per-element parameter draws) and
src/operator/random/shuffle_op.cc — the raw ops behind ``mx.nd.random.*`` /
``mx.sym.random.*`` (SURVEY.md §2.2 random/ row).

TPU-native design: every sampling op is a *pure* function taking a PRNG key
as its LAST input (``Operator.needs_rng``).  Eager frontends split the key
off the process-global stream (mxnet_tpu/random.py) per call; the symbol
runner splits one base key per forward across all sampling nodes
(symbol.py ``compile``).  This replaces the reference's per-device resource
RNG states (src/resource.cc) with the jax key discipline: draws are
reproducible from ``mx.random.seed`` yet jit-compatible — the key is an
argument, so compiled graphs get fresh randomness per call without
recompiling.
"""
from __future__ import annotations

import numpy as _np

from ..base import jax_compute_dtype
from .register import register_op


def _canon_shape(shape):
    if shape is None:
        return (1,)
    if isinstance(shape, (int, _np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _draw_shape(shape):
    """Trailing draw shape for _sample_* ops (default: one draw/element)."""
    if shape is None or shape == () or shape == 0:
        return ()
    if isinstance(shape, (int, _np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _register():
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    def _placed(fn, ctx):
        """Honor the reference's ctx-as-op-attribute convention (init_op.cc
        creation ops do the same — see ops_misc._place): place the draw on
        the requested device."""
        if ctx is None:
            return fn
        from ..context import Context
        dev = (ctx if isinstance(ctx, Context)
               else Context.from_str(ctx)).device

        def placed(*a):
            import jax
            return jax.device_put(fn(*a), dev)
        return placed

    # -- scalar-parameter draws (sample_op.cc) ----------------------------
    # use_jit=False throughout this family: distribution parameters live in
    # the maker closure, so a jitted fn would trigger one permanent XLA
    # compilation PER PARAMETER VALUE (unbounded for loops sweeping lam/
    # low/high).  Eager jax.random calls cache their kernels by shape, so
    # the eager path costs nothing extra — and inside a jitted GRAPH
    # (symbol runner / CachedOp) the fn is traced into the enclosing
    # compilation anyway, where use_jit is irrelevant.

    def uniform_maker(low=0.0, high=1.0, shape=None, dtype=None, ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            return jr.uniform(key, shp, dt, float(low), float(high))
        return _placed(fn, ctx)
    register_op("_random_uniform", uniform_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    def normal_maker(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            return (jr.normal(key, shp, dt) * scale + loc).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_normal", normal_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    def gamma_maker(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            a = jnp.asarray(float(alpha), dt)
            return (jr.gamma(key, a, shp, dt) * beta).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_gamma", gamma_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    def exponential_maker(lam=1.0, shape=None, dtype=None, ctx=None,
                          scale=None):
        # reference parameterizes by rate lambda; the eager frontend's
        # historical `scale` (=1/lambda) is accepted too
        sc = float(scale) if scale is not None else 1.0 / float(lam)
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            return (jr.exponential(key, shp, dt) * sc).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_exponential", exponential_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    def poisson_maker(lam=1.0, shape=None, dtype=None, ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            return jr.poisson(key, float(lam), shp).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_poisson", poisson_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    def negative_binomial_maker(k=1, p=1.0, shape=None, dtype=None,
                                ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            kg, kp = jr.split(key)
            # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
            g = jr.gamma(kg, jnp.asarray(float(k), jnp.float32), shp)
            lam = g * ((1.0 - float(p)) / float(p))
            return jr.poisson(kp, lam, shp).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_negative_binomial", negative_binomial_maker,
                needs_rng=True, differentiable=False, use_jit=False)

    def gnb_maker(mu=1.0, alpha=1.0, shape=None, dtype=None, ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)
        k = 1.0 / float(alpha)
        p = k / (k + float(mu))

        def fn(key):
            kg, kp = jr.split(key)
            g = jr.gamma(kg, jnp.asarray(k, jnp.float32), shp)
            lam = g * ((1.0 - p) / p)
            return jr.poisson(kp, lam, shp).astype(dt)
        return _placed(fn, ctx)
    register_op("_random_generalized_negative_binomial", gnb_maker,
                needs_rng=True, differentiable=False, use_jit=False)

    def randint_maker(low=0, high=1, shape=None, dtype="int32", ctx=None):
        shp, dt = _canon_shape(shape), jax_compute_dtype(dtype)

        def fn(key):
            return jr.randint(key, shp, int(low), int(high), dt)
        return _placed(fn, ctx)
    register_op("_random_randint", randint_maker, needs_rng=True,
                differentiable=False, use_jit=False)

    # -- *_like draws: shape/dtype follow the data input ------------------

    def _like(drawer):
        def like_maker(dtype=None, **params):
            def fn(data, key):
                dt = data.dtype if dtype is None else jax_compute_dtype(dtype)
                return drawer(key, data.shape, dt, params)
            return fn
        return like_maker

    register_op("_random_uniform_like", _like(
        lambda key, s, dt, p: jr.uniform(key, s, dt, float(p.get("low", 0.0)),
                                         float(p.get("high", 1.0)))),
        needs_rng=True, differentiable=False, use_jit=False)
    register_op("_random_normal_like", _like(
        lambda key, s, dt, p: jr.normal(key, s, dt)
        * float(p.get("scale", 1.0)) + float(p.get("loc", 0.0))),
        needs_rng=True, differentiable=False, use_jit=False)
    register_op("_random_gamma_like", _like(
        lambda key, s, dt, p: jr.gamma(
            key, jnp.asarray(float(p.get("alpha", 1.0)), dt), s, dt)
        * float(p.get("beta", 1.0))),
        needs_rng=True, differentiable=False, use_jit=False)
    register_op("_random_exponential_like", _like(
        lambda key, s, dt, p: jr.exponential(key, s, dt)
        / float(p.get("lam", 1.0))),
        needs_rng=True, differentiable=False, use_jit=False)
    register_op("_random_poisson_like", _like(
        lambda key, s, dt, p: jr.poisson(
            key, float(p.get("lam", 1.0)), s).astype(dt)),
        needs_rng=True, differentiable=False, use_jit=False)

    # -- per-element-parameter draws (multisample_op.cc) ------------------
    # Params are tensor inputs of a common (broadcast) shape s; output is
    # s + shape, one independent draw block per parameter element.

    def _bcast(vals):
        return jnp.broadcast_arrays(*vals) if len(vals) > 1 else list(vals)

    def _expand(v, ndraw):
        return jnp.reshape(v, v.shape + (1,) * ndraw)

    def sample_uniform_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(low, high, key):
            low, high = _bcast([low, high])
            out_shape = tuple(low.shape) + draw
            u = jr.uniform(key, out_shape, dt)
            lo, hi = _expand(low, len(draw)), _expand(high, len(draw))
            return (lo + u * (hi - lo)).astype(dt)
        return fn
    register_op("_sample_uniform", sample_uniform_maker, needs_rng=True,
                differentiable=False)

    def sample_normal_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(mu, sigma, key):
            mu, sigma = _bcast([mu, sigma])
            out_shape = tuple(mu.shape) + draw
            z = jr.normal(key, out_shape, dt)
            return (_expand(mu, len(draw))
                    + z * _expand(sigma, len(draw))).astype(dt)
        return fn
    register_op("_sample_normal", sample_normal_maker, needs_rng=True,
                differentiable=False)

    def sample_gamma_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(alpha, beta, key):
            alpha, beta = _bcast([alpha, beta])
            out_shape = tuple(alpha.shape) + draw
            a = jnp.broadcast_to(_expand(alpha, len(draw)), out_shape)
            g = jr.gamma(key, a.astype(dt), out_shape, dt)
            return (g * _expand(beta, len(draw))).astype(dt)  # beta = scale
        return fn
    register_op("_sample_gamma", sample_gamma_maker, needs_rng=True,
                differentiable=False)

    def sample_exponential_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(lam, key):
            out_shape = tuple(lam.shape) + draw
            e = jr.exponential(key, out_shape, dt)
            return (e / _expand(lam, len(draw))).astype(dt)
        return fn
    register_op("_sample_exponential", sample_exponential_maker,
                needs_rng=True, differentiable=False)

    def sample_poisson_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(lam, key):
            out_shape = tuple(lam.shape) + draw
            lam_b = jnp.broadcast_to(_expand(lam, len(draw)), out_shape)
            return jr.poisson(key, lam_b.astype(_np.float32),
                              out_shape).astype(dt)
        return fn
    register_op("_sample_poisson", sample_poisson_maker, needs_rng=True,
                differentiable=False)

    def sample_nb_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(k, p, key):
            k, p = _bcast([k, p])
            out_shape = tuple(k.shape) + draw
            kg, kp = jr.split(key)
            k_b = jnp.broadcast_to(_expand(k, len(draw)), out_shape)
            p_b = jnp.broadcast_to(_expand(p, len(draw)), out_shape)
            g = jr.gamma(kg, k_b.astype(_np.float32), out_shape)
            lam = g * (1.0 - p_b) / p_b
            return jr.poisson(kp, lam, out_shape).astype(dt)
        return fn
    register_op("_sample_negative_binomial", sample_nb_maker,
                needs_rng=True, differentiable=False)

    def sample_gnb_maker(shape=None, dtype=None, ctx=None):
        draw = _draw_shape(shape)
        dt = jax_compute_dtype(dtype)

        def fn(mu, alpha, key):
            mu, alpha = _bcast([mu, alpha])
            out_shape = tuple(mu.shape) + draw
            # gnb(mu, alpha) == NB(k=1/alpha, p=1/(1+alpha*mu))
            k = 1.0 / jnp.maximum(alpha, 1e-12)
            p = 1.0 / (1.0 + alpha * mu)
            kg, kp = jr.split(key)
            k_b = jnp.broadcast_to(_expand(k, len(draw)), out_shape)
            p_b = jnp.broadcast_to(_expand(p, len(draw)), out_shape)
            g = jr.gamma(kg, k_b.astype(_np.float32), out_shape)
            lam = g * (1.0 - p_b) / p_b
            return jr.poisson(kp, lam, out_shape).astype(dt)
        return fn
    register_op("_sample_generalized_negative_binomial", sample_gnb_maker,
                needs_rng=True, differentiable=False)

    def sample_multinomial_maker(shape=None, get_prob=False, dtype="int32",
                                 ctx=None):
        n = 1 if shape in (None, ()) else (
            int(shape) if isinstance(shape, (int, _np.integer))
            else int(_np.prod(shape)))
        squeeze = shape in (None, ())
        dt = jax_compute_dtype(dtype)

        def draw(p, key):
            logits = jnp.log(jnp.maximum(p, 1e-30))
            batch = p.shape[:-1]
            samples = jr.categorical(key, logits[..., None, :], axis=-1,
                                     shape=batch + (n,))
            lp = jnp.take_along_axis(
                logits.reshape(-1, p.shape[-1]),
                samples.reshape(-1, n), axis=-1).reshape(batch + (n,))
            return samples, lp

        if not get_prob:
            def fn(p, key):
                samples, _ = draw(p, key)
                out = samples.astype(dt)
                return out[..., 0] if squeeze else out
            return fn

        # get_prob=True: the log-prob output is DIFFERENTIABLE wrt p
        # (reference sample_multinomial backward — the REINFORCE idiom:
        # d logp_i / d p_j = 1/p_c for the sampled class c, else 0)
        @jax.custom_vjp
        def fn(p, key):
            samples, lp = draw(p, key)
            out = samples.astype(dt)
            return ((out[..., 0], lp[..., 0]) if squeeze
                    else (out, lp))

        def fwd(p, key):
            samples, lp = draw(p, key)
            out = samples.astype(dt)
            res = (p, samples)
            return (((out[..., 0], lp[..., 0]) if squeeze
                     else (out, lp)), res)

        def bwd(res, cts):
            p, samples = res
            _, ct_lp = cts
            ct = ct_lp[..., None] if squeeze else ct_lp   # (batch, n)
            p_c = jnp.take_along_axis(p, samples, axis=-1)  # (batch, n)
            oh = jax.nn.one_hot(samples, p.shape[-1], dtype=p.dtype)
            grad_p = ((ct / jnp.maximum(p_c, 1e-30))[..., None]
                      * oh).sum(axis=-2)
            return grad_p, None
        fn.defvjp(fwd, bwd)
        return fn
    # differentiable only in the get_prob=True form: the samples-only
    # mode must NOT silently record zero gradients (a forgotten
    # get_prob=True in an RL loop should fail loudly, as before)
    register_op("_sample_multinomial", sample_multinomial_maker,
                needs_rng=True,
                differentiable=lambda kw: bool(kw.get("get_prob")))

    def shuffle_maker(ctx=None):
        def fn(data, key):
            perm = jr.permutation(key, data.shape[0])
            return jnp.take(data, perm, axis=0)
        return fn
    register_op("_shuffle", shuffle_maker, needs_rng=True,
                differentiable=False)


_register()
