"""Elementwise unary/binary/scalar operators.

Reference parity: src/operator/tensor/elemwise_unary_op*.cc,
elemwise_binary_broadcast_op*.cc, elemwise_binary_scalar_op*.cc (SURVEY.md
§2.2 — "mostly 1:1 with jax.numpy/lax").  Parity quirks preserved:
comparison and logical ops return 0/1 in the *input float dtype*, not bool,
and scalar operands are cast to the array's dtype before the op (both are
MXNet conventions that differ from numpy).
"""
from __future__ import annotations

import numpy as _np

from .register import register_op, simple_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jsp():
    import jax.scipy.special as jsp
    return jsp


# --------------------------------------------------------------------------
# unary
# --------------------------------------------------------------------------

def _register_unary():
    import jax
    import jax.numpy as jnp
    import jax.scipy.special as jsp

    unary = {
        "relu": lambda x: jnp.maximum(x, 0),
        "sigmoid": jax.nn.sigmoid,
        "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
        "tanh": jnp.tanh,
        "softsign": lambda x: x / (1 + jnp.abs(x)),
        "softrelu": jax.nn.softplus,
        "exp": jnp.exp,
        "expm1": jnp.expm1,
        "log": jnp.log,
        "log10": jnp.log10,
        "log2": jnp.log2,
        "log1p": jnp.log1p,
        "sqrt": jnp.sqrt,
        "rsqrt": lambda x: 1.0 / jnp.sqrt(x),
        "cbrt": jnp.cbrt,
        "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
        "square": jnp.square,
        "abs": jnp.abs,
        "sign": jnp.sign,
        "round": jnp.round,
        "rint": jnp.rint,
        "fix": jnp.trunc,
        "floor": jnp.floor,
        "ceil": jnp.ceil,
        "trunc": jnp.trunc,
        "negative": jnp.negative,
        "reciprocal": lambda x: 1.0 / x,
        "erf": jax.lax.erf,
        "erfinv": jax.lax.erf_inv,
        "gamma": lambda x: jnp.exp(jsp.gammaln(x)),
        "gammaln": jsp.gammaln,
        "digamma": jsp.digamma,
        "sin": jnp.sin,
        "cos": jnp.cos,
        "tan": jnp.tan,
        "arcsin": jnp.arcsin,
        "arccos": jnp.arccos,
        "arctan": jnp.arctan,
        "sinh": jnp.sinh,
        "cosh": jnp.cosh,
        "arcsinh": jnp.arcsinh,
        "arccosh": jnp.arccosh,
        "arctanh": jnp.arctanh,
        "degrees": jnp.degrees,
        "radians": jnp.radians,
    }
    for name, fn in unary.items():
        simple_op(name, fn)

    simple_op("logical_not",
              lambda x: (x == 0).astype(x.dtype))
    register_op("clip", lambda a_min, a_max:
                (lambda x: jnp.clip(x, a_min, a_max)))


# --------------------------------------------------------------------------
# binary broadcast
# --------------------------------------------------------------------------

def _cmp(fn):
    """MXNet comparisons return 0/1 in the lhs dtype (not bool)."""
    def f(x, y):
        return fn(x, y).astype(x.dtype)
    return f


def _register_binary():
    import jax.numpy as jnp

    binary = {
        "broadcast_add": jnp.add,
        "broadcast_sub": jnp.subtract,
        "broadcast_mul": jnp.multiply,
        "broadcast_div": jnp.divide,
        "broadcast_mod": jnp.mod,
        "broadcast_power": jnp.power,
        "broadcast_maximum": jnp.maximum,
        "broadcast_minimum": jnp.minimum,
        "broadcast_hypot": jnp.hypot,
    }
    alias = {
        "broadcast_add": ("elemwise_add", "_plus", "broadcast_plus"),
        "broadcast_sub": ("elemwise_sub", "_minus", "broadcast_minus"),
        "broadcast_mul": ("elemwise_mul", "_mul"),
        "broadcast_div": ("elemwise_div", "_div"),
        "broadcast_mod": ("_mod",),
        "broadcast_power": ("_power", "pow"),
        "broadcast_maximum": ("maximum", "_maximum"),
        "broadcast_minimum": ("minimum", "_minimum"),
    }
    for name, fn in binary.items():
        simple_op(name, fn, aliases=alias.get(name, ()))

    cmps = {
        "broadcast_equal": jnp.equal,
        "broadcast_not_equal": jnp.not_equal,
        "broadcast_greater": jnp.greater,
        "broadcast_greater_equal": jnp.greater_equal,
        "broadcast_lesser": jnp.less,
        "broadcast_lesser_equal": jnp.less_equal,
        "broadcast_logical_and": lambda x, y: jnp.logical_and(x != 0, y != 0),
        "broadcast_logical_or": lambda x, y: jnp.logical_or(x != 0, y != 0),
        "broadcast_logical_xor": lambda x, y: jnp.logical_xor(x != 0, y != 0),
    }
    cmp_alias = {
        "broadcast_logical_and": ("logical_and",),
        "broadcast_logical_or": ("logical_or",),
        "broadcast_logical_xor": ("logical_xor",),
        # same-shape elemwise duals (elemwise_binary_op_logic.cc)
        "broadcast_equal": ("_equal",),
        "broadcast_not_equal": ("_not_equal",),
        "broadcast_greater": ("_greater",),
        "broadcast_greater_equal": ("_greater_equal",),
        "broadcast_lesser": ("_lesser",),
        "broadcast_lesser_equal": ("_lesser_equal",),
    }
    for name, fn in cmps.items():
        simple_op(name, _cmp(fn), differentiable=False,
                  aliases=cmp_alias.get(name, ()))


# --------------------------------------------------------------------------
# scalar variants — the scalar arrives as a 0-d array input (one compile per
# shape rather than per constant) and is cast to the array dtype (MXNet rule)
# --------------------------------------------------------------------------

def _scalar(fn, reverse=False):
    def f(x, s):
        s = s.astype(x.dtype)
        return fn(s, x) if reverse else fn(x, s)
    return f


def _scalar_cmp(fn, reverse=False):
    def f(x, s):
        s = s.astype(x.dtype)
        r = fn(s, x) if reverse else fn(x, s)
        return r.astype(x.dtype)
    return f


def _register_scalar():
    import jax.numpy as jnp

    pairs = {
        "_plus_scalar": (jnp.add, False),
        "_minus_scalar": (jnp.subtract, False),
        "_rminus_scalar": (jnp.subtract, True),
        "_mul_scalar": (jnp.multiply, False),
        "_div_scalar": (jnp.divide, False),
        "_rdiv_scalar": (jnp.divide, True),
        "_mod_scalar": (jnp.mod, False),
        "_rmod_scalar": (jnp.mod, True),
        "_power_scalar": (jnp.power, False),
        "_rpower_scalar": (jnp.power, True),
        "_maximum_scalar": (jnp.maximum, False),
        "_minimum_scalar": (jnp.minimum, False),
    }
    for name, (fn, rev) in pairs.items():
        simple_op(name, _scalar(fn, rev))

    cmp_pairs = {
        "_equal_scalar": (jnp.equal, False),
        "_not_equal_scalar": (jnp.not_equal, False),
        "_greater_scalar": (jnp.greater, False),
        "_greater_equal_scalar": (jnp.greater_equal, False),
        "_lesser_scalar": (jnp.less, False),
        "_lesser_equal_scalar": (jnp.less_equal, False),
    }
    for name, (fn, rev) in cmp_pairs.items():
        simple_op(name, _scalar_cmp(fn, rev), differentiable=False)


_register_unary()
_register_binary()
_register_scalar()
