"""Fused optimizer update ops.

Reference parity: src/operator/optimizer_op.cc — sgd_update, sgd_mom_update,
multi-precision variants (fp32 master weights for fp16/bf16 params),
adam_update, ftrl_update, signum/signsgd (SURVEY.md §2.2).  TPU-native
design: each update is one jitted XLA computation; the learning rate arrives
as a 0-d array *input* (not a baked constant) so LR schedules do not trigger
recompilation.  The frontends in mxnet_tpu.optimizer call these with
``out=weight`` so the update is in-place in the NDArray sense (new donated
buffer, version bump).
"""
from __future__ import annotations

from .register import register_op


def _register():
    import jax.numpy as jnp

    def _prep_grad(grad, wd, weight, rescale_grad, clip_gradient):
        g = grad * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g + wd * weight

    def sgd_update_maker(wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                         lazy_update=True):
        def fn(weight, grad, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            return weight - lr * g
        return fn
    register_op("sgd_update", sgd_update_maker, differentiable=False)

    def sgd_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0, lazy_update=True):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom - lr * g
            return (weight + mom_new, mom_new)
        return fn
    register_op("sgd_mom_update", sgd_mom_update_maker, differentiable=False)

    def mp_sgd_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                                clip_gradient=-1.0, lazy_update=True):
        def fn(weight, grad, mom, weight32, lr):
            # master weights in fp32 (reference multi-precision SGD)
            lr = lr.astype(jnp.float32)
            g32 = grad.astype(jnp.float32)
            g = _prep_grad(g32, wd, weight32, rescale_grad, clip_gradient)
            mom_new = momentum * mom - lr * g
            w32 = weight32 + mom_new
            return (w32.astype(weight.dtype), mom_new, w32)
        return fn
    register_op("mp_sgd_mom_update", mp_sgd_mom_update_maker,
                differentiable=False)

    def nag_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom + g
            return (weight - lr * (g + momentum * mom_new), mom_new)
        return fn
    register_op("nag_mom_update", nag_mom_update_maker, differentiable=False)

    def adam_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          lazy_update=True):
        def fn(weight, grad, mean, var, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            w = weight - lr * m / (jnp.sqrt(v) + epsilon)
            return (w, m, v)
        return fn
    register_op("adam_update", adam_update_maker, differentiable=False)

    def ftrl_update_maker(lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
        def fn(weight, grad, z, n, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            n_new = n + jnp.square(g)
            sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
            z_new = z + g - sigma * weight
            w = jnp.where(
                jnp.abs(z_new) <= lamda1,
                jnp.zeros_like(weight),
                -(z_new - jnp.sign(z_new) * lamda1) /
                ((beta + jnp.sqrt(n_new)) / lr + wd))
            return (w, z_new, n_new)
        return fn
    register_op("ftrl_update", ftrl_update_maker, differentiable=False)

    def signsgd_update_maker(wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            return weight - lr * jnp.sign(g)
        return fn
    register_op("signsgd_update", signsgd_update_maker, differentiable=False)

    def signum_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, wd_lh=0.0):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom - (1 - momentum) * g
            # wd_lh: decoupled weight decay (Signum paper / reference op)
            return ((1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new),
                    mom_new)
        return fn
    register_op("signum_update", signum_update_maker, differentiable=False)

    def rmsprop_update_maker(gamma1=0.95, epsilon=1e-8, wd=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             clip_weights=-1.0):
        def fn(weight, grad, n, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
            w = weight - lr * g / jnp.sqrt(n_new + epsilon)
            if clip_weights > 0:
                w = jnp.clip(w, -clip_weights, clip_weights)
            return (w, n_new)
        return fn
    register_op("rmsprop_update", rmsprop_update_maker, differentiable=False)

    def rmspropalex_update_maker(gamma1=0.95, gamma2=0.9, epsilon=1e-8,
                                 wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                                 clip_weights=-1.0):
        # centered RMSProp (Graves 2013) — reference rmspropalex_update
        def fn(weight, grad, n, g_avg, delta, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
            g_new = gamma1 * g_avg + (1 - gamma1) * g
            d_new = gamma2 * delta - \
                lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
            w = weight + d_new
            if clip_weights > 0:
                w = jnp.clip(w, -clip_weights, clip_weights)
            return (w, n_new, g_new, d_new)
        return fn
    register_op("rmspropalex_update", rmspropalex_update_maker,
                differentiable=False)

    def adagrad_update_maker(epsilon=1e-7, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
        def fn(weight, grad, history, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            h_new = history + jnp.square(g)
            w = weight - lr * (g / jnp.sqrt(h_new + epsilon) + wd * weight)
            return (w, h_new)
        return fn
    register_op("adagrad_update", adagrad_update_maker, differentiable=False)

    # ---- multi-tensor apply (Pallas kernel; reference multi_sgd_update
    # family, src/operator/optimizer_op.cc) — ONE launch updates every
    # parameter; inputs interleaved per reference convention ------------

    # The last two inputs of each multi-tensor op are the per-tensor lr
    # and wd ARRAYS (shape (num_weights,)) — array inputs, not baked
    # attrs, so LR schedules never retrigger compilation.

    def multi_sgd_update_maker(rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=None):
        from ..kernels import fused_multi_sgd

        def fn(*data):  # w0, g0, w1, g1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws, gs = arrs[0::2], arrs[1::2]
            return tuple(fused_multi_sgd(
                ws, gs, lrs, wds, rescale_grad, clip_gradient))
        return fn
    register_op("multi_sgd_update", multi_sgd_update_maker,
                differentiable=False)

    def multi_sgd_mom_update_maker(momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=None):
        from ..kernels import fused_multi_sgd_mom

        def fn(*data):  # w0, g0, m0, w1, g1, m1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws, gs, ms = arrs[0::3], arrs[1::3], arrs[2::3]
            w_out, m_out = fused_multi_sgd_mom(
                ws, gs, ms, lrs, wds, momentum, rescale_grad, clip_gradient)
            out = []
            for w, m in zip(w_out, m_out):
                out.extend((w, m))
            return tuple(out)
        return fn
    register_op("multi_sgd_mom_update", multi_sgd_mom_update_maker,
                differentiable=False)

    def multi_mp_sgd_mom_update_maker(momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0, num_weights=None):
        from ..kernels import fused_multi_sgd_mom

        def fn(*data):  # w0, g0, m0, w32_0, w1, g1, m1, w32_1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws = arrs[0::4]
            gs = [g.astype(jnp.float32) for g in arrs[1::4]]
            ms, w32s = arrs[2::4], arrs[3::4]
            w32_out, m_out = fused_multi_sgd_mom(
                w32s, gs, ms, lrs, wds, momentum, rescale_grad,
                clip_gradient)
            out = []
            for w, w32, m in zip(ws, w32_out, m_out):
                out.extend((w32.astype(w.dtype), m, w32))
            return tuple(out)
        return fn
    register_op("multi_mp_sgd_mom_update", multi_mp_sgd_mom_update_maker,
                differentiable=False)

    # ---- AdamW (decoupled weight decay; reference:
    # src/operator/contrib/adamw.cc _contrib_adamw_update) ----------------
    def adamw_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                           eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, mean, var, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            # decoupled decay: wd applies to the weight directly, NOT
            # through the adaptive preconditioner
            w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) +
                                wd * weight)
            return (w, m, v)
        return fn
    register_op("_contrib_adamw_update", adamw_update_maker,
                aliases=("adamw_update",), differentiable=False)

    def mp_adamw_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                              eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, mean, var, w32, lr):
            lr32 = lr.astype(jnp.float32)
            g = grad.astype(jnp.float32) * rescale_grad
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            new32 = w32 - eta * (lr32 * m / (jnp.sqrt(v) + epsilon) +
                                 wd * w32)
            return (new32.astype(weight.dtype), m, v, new32)
        return fn
    register_op("_contrib_mp_adamw_update", mp_adamw_update_maker,
                aliases=("mp_adamw_update",), differentiable=False)

    # ---- LARS ingredients (reference: src/operator/contrib/
    # multi_lars-inl.h lars_update path) ----------------------------------
    def lars_trust_maker(eta=0.001, epsilon=1e-8, rescale_grad=1.0):
        def fn(weight, grad, wd):
            w_norm = jnp.sqrt(jnp.sum(
                jnp.square(weight.astype(jnp.float32))))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(
                grad.astype(jnp.float32) * rescale_grad)))
            trust = eta * w_norm / (g_norm + wd * w_norm + epsilon)
            # layers with zero/degenerate norms fall back to trust=1
            return jnp.where((w_norm > 0) & (g_norm > 0), trust,
                             jnp.float32(1.0))
        return fn
    register_op("lars_trust", lars_trust_maker, differentiable=False)


_register()
