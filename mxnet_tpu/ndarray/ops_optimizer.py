"""Fused optimizer update ops.

Reference parity: src/operator/optimizer_op.cc — sgd_update, sgd_mom_update,
multi-precision variants (fp32 master weights for fp16/bf16 params),
adam_update, ftrl_update, signum/signsgd (SURVEY.md §2.2).  TPU-native
design: each update is one jitted XLA computation; the learning rate arrives
as a 0-d array *input* (not a baked constant) so LR schedules do not trigger
recompilation.  The frontends in mxnet_tpu.optimizer call these with
``out=weight`` so the update is in-place in the NDArray sense (new donated
buffer, version bump).
"""
from __future__ import annotations

from .register import register_op


def _register():
    import jax.numpy as jnp

    def _prep_grad(grad, wd, weight, rescale_grad, clip_gradient):
        g = grad * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g + wd * weight

    def sgd_update_maker(wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                         lazy_update=True):
        def fn(weight, grad, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            return weight - lr * g
        return fn
    register_op("sgd_update", sgd_update_maker, differentiable=False)

    def sgd_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0, lazy_update=True):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom - lr * g
            return (weight + mom_new, mom_new)
        return fn
    register_op("sgd_mom_update", sgd_mom_update_maker, differentiable=False)

    def mp_sgd_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                                clip_gradient=-1.0, lazy_update=True):
        def fn(weight, grad, mom, weight32, lr):
            # master weights in fp32 (reference multi-precision SGD)
            lr = lr.astype(jnp.float32)
            g32 = grad.astype(jnp.float32)
            g = _prep_grad(g32, wd, weight32, rescale_grad, clip_gradient)
            mom_new = momentum * mom - lr * g
            w32 = weight32 + mom_new
            return (w32.astype(weight.dtype), mom_new, w32)
        return fn
    register_op("mp_sgd_mom_update", mp_sgd_mom_update_maker,
                differentiable=False)

    def nag_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom + g
            return (weight - lr * (g + momentum * mom_new), mom_new)
        return fn
    register_op("nag_mom_update", nag_mom_update_maker, differentiable=False)

    def adam_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          lazy_update=True):
        def fn(weight, grad, mean, var, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            w = weight - lr * m / (jnp.sqrt(v) + epsilon)
            return (w, m, v)
        return fn
    register_op("adam_update", adam_update_maker, differentiable=False)

    def ftrl_update_maker(lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
        def fn(weight, grad, z, n, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            n_new = n + jnp.square(g)
            sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
            z_new = z + g - sigma * weight
            w = jnp.where(
                jnp.abs(z_new) <= lamda1,
                jnp.zeros_like(weight),
                -(z_new - jnp.sign(z_new) * lamda1) /
                ((beta + jnp.sqrt(n_new)) / lr + wd))
            return (w, z_new, n_new)
        return fn
    register_op("ftrl_update", ftrl_update_maker, differentiable=False)

    def signsgd_update_maker(wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            return weight - lr * jnp.sign(g)
        return fn
    register_op("signsgd_update", signsgd_update_maker, differentiable=False)

    def signum_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, wd_lh=0.0):
        def fn(weight, grad, mom, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            mom_new = momentum * mom - (1 - momentum) * g
            # wd_lh: decoupled weight decay (Signum paper / reference op)
            return ((1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new),
                    mom_new)
        return fn
    register_op("signum_update", signum_update_maker, differentiable=False)

    def rmsprop_update_maker(gamma1=0.95, epsilon=1e-8, wd=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             clip_weights=-1.0):
        def fn(weight, grad, n, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
            w = weight - lr * g / jnp.sqrt(n_new + epsilon)
            if clip_weights > 0:
                w = jnp.clip(w, -clip_weights, clip_weights)
            return (w, n_new)
        return fn
    register_op("rmsprop_update", rmsprop_update_maker, differentiable=False)

    def rmspropalex_update_maker(gamma1=0.95, gamma2=0.9, epsilon=1e-8,
                                 wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                                 clip_weights=-1.0):
        # centered RMSProp (Graves 2013) — reference rmspropalex_update
        def fn(weight, grad, n, g_avg, delta, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
            n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
            g_new = gamma1 * g_avg + (1 - gamma1) * g
            d_new = gamma2 * delta - \
                lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
            w = weight + d_new
            if clip_weights > 0:
                w = jnp.clip(w, -clip_weights, clip_weights)
            return (w, n_new, g_new, d_new)
        return fn
    register_op("rmspropalex_update", rmspropalex_update_maker,
                differentiable=False)

    def adagrad_update_maker(epsilon=1e-7, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
        def fn(weight, grad, history, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            h_new = history + jnp.square(g)
            w = weight - lr * (g / jnp.sqrt(h_new + epsilon) + wd * weight)
            return (w, h_new)
        return fn
    register_op("adagrad_update", adagrad_update_maker, differentiable=False)

    # ---- multi-tensor apply (Pallas kernel; reference multi_sgd_update
    # family, src/operator/optimizer_op.cc) — ONE launch updates every
    # parameter; inputs interleaved per reference convention ------------

    # The last two inputs of each multi-tensor op are the per-tensor lr
    # and wd ARRAYS (shape (num_weights,)) — array inputs, not baked
    # attrs, so LR schedules never retrigger compilation.

    def multi_sgd_update_maker(rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=None, interpret=None):
        # interpret is a STATIC attr (jit-cache-keyed): the Mosaic-vs-
        # interpret choice cannot be made inside the trace (tracers have
        # no device), so the frontend passes it from the NDArray context
        from ..kernels import fused_multi_sgd

        def fn(*data):  # w0, g0, w1, g1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws, gs = arrs[0::2], arrs[1::2]
            return tuple(fused_multi_sgd(
                ws, gs, lrs, wds, rescale_grad, clip_gradient,
                interpret=interpret))
        return fn
    # preloaded_* variants ARE this signature: lrs/wds ride as array
    # inputs (reference preloaded_multi_sgd_update)
    register_op("multi_sgd_update", multi_sgd_update_maker,
                aliases=("preloaded_multi_sgd_update",),
                differentiable=False)

    def multi_sgd_mom_update_maker(momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=None,
                                   interpret=None):
        from ..kernels import fused_multi_sgd_mom

        def fn(*data):  # w0, g0, m0, w1, g1, m1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws, gs, ms = arrs[0::3], arrs[1::3], arrs[2::3]
            w_out, m_out = fused_multi_sgd_mom(
                ws, gs, ms, lrs, wds, momentum, rescale_grad, clip_gradient,
                interpret=interpret)
            out = []
            for w, m in zip(w_out, m_out):
                out.extend((w, m))
            return tuple(out)
        return fn
    register_op("multi_sgd_mom_update", multi_sgd_mom_update_maker,
                aliases=("preloaded_multi_sgd_mom_update",),
                differentiable=False)

    def multi_mp_sgd_mom_update_maker(momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0, num_weights=None,
                                      interpret=None):
        from ..kernels import fused_multi_sgd_mom

        def fn(*data):  # w0, g0, m0, w32_0, w1, g1, m1, w32_1, ..., lrs, wds
            arrs, lrs, wds = data[:-2], data[-2], data[-1]
            ws = arrs[0::4]
            gs = [g.astype(jnp.float32) for g in arrs[1::4]]
            ms, w32s = arrs[2::4], arrs[3::4]
            w32_out, m_out = fused_multi_sgd_mom(
                w32s, gs, ms, lrs, wds, momentum, rescale_grad,
                clip_gradient, interpret=interpret)
            out = []
            for w, w32, m in zip(ws, w32_out, m_out):
                out.extend((w32.astype(w.dtype), m, w32))
            return tuple(out)
        return fn
    register_op("multi_mp_sgd_mom_update", multi_mp_sgd_mom_update_maker,
                aliases=("preloaded_multi_mp_sgd_mom_update",),
                differentiable=False)

    # ---- AdamW (decoupled weight decay; reference:
    # src/operator/contrib/adamw.cc _contrib_adamw_update) ----------------
    def adamw_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                           eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, mean, var, lr):
            lr = lr.astype(weight.dtype)
            g = grad * rescale_grad
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            # decoupled decay: wd applies to the weight directly, NOT
            # through the adaptive preconditioner
            w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) +
                                wd * weight)
            return (w, m, v)
        return fn
    register_op("_contrib_adamw_update", adamw_update_maker,
                aliases=("adamw_update",), differentiable=False)

    def mp_adamw_update_maker(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                              eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
        def fn(weight, grad, mean, var, w32, lr):
            lr32 = lr.astype(jnp.float32)
            g = grad.astype(jnp.float32) * rescale_grad
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            m = beta1 * mean + (1 - beta1) * g
            v = beta2 * var + (1 - beta2) * jnp.square(g)
            new32 = w32 - eta * (lr32 * m / (jnp.sqrt(v) + epsilon) +
                                 wd * w32)
            return (new32.astype(weight.dtype), m, v, new32)
        return fn
    register_op("_contrib_mp_adamw_update", mp_adamw_update_maker,
                aliases=("mp_adamw_update",), differentiable=False)

    # ---- LARS ingredients (reference: src/operator/contrib/
    # multi_lars-inl.h lars_update path) ----------------------------------
    def lars_trust_maker(eta=0.001, epsilon=1e-8, rescale_grad=1.0):
        def fn(weight, grad, wd):
            w_norm = jnp.sqrt(jnp.sum(
                jnp.square(weight.astype(jnp.float32))))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(
                grad.astype(jnp.float32) * rescale_grad)))
            trust = eta * w_norm / (g_norm + wd * w_norm + epsilon)
            # layers with zero/degenerate norms fall back to trust=1
            return jnp.where((w_norm > 0) & (g_norm > 0), trust,
                             jnp.float32(1.0))
        return fn
    register_op("lars_trust", lars_trust_maker, differentiable=False)

    # ---- mp_sgd_update (no momentum; fp32 master) -----------------------
    def mp_sgd_update_maker(wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                            lazy_update=True):
        def fn(weight, grad, weight32, lr):
            lr = lr.astype(jnp.float32)
            g = _prep_grad(grad.astype(jnp.float32), wd, weight32,
                           rescale_grad, clip_gradient)
            w32 = weight32 - lr * g
            return (w32.astype(weight.dtype), w32)
        return fn
    register_op("mp_sgd_update", mp_sgd_update_maker, differentiable=False)

    def mp_nag_mom_update_maker(momentum=0.0, wd=0.0, rescale_grad=1.0,
                                clip_gradient=-1.0):
        def fn(weight, grad, mom, weight32, lr):
            lr = lr.astype(jnp.float32)
            g = _prep_grad(grad.astype(jnp.float32), wd, weight32,
                           rescale_grad, clip_gradient)
            mom_new = momentum * mom + g
            w32 = weight32 - lr * (g + momentum * mom_new)
            return (w32.astype(weight.dtype), mom_new, w32)
        return fn
    register_op("mp_nag_mom_update", mp_nag_mom_update_maker,
                differentiable=False)

    # ---- GroupAdaGrad (src/operator/contrib/optimizer_op.cc): AdaGrad
    # with ONE history scalar per row (group) — the sparse-embedding
    # optimizer of GluonNLP ------------------------------------------------
    def group_adagrad_update_maker(epsilon=1e-5, rescale_grad=1.0,
                                   clip_gradient=-1.0):
        def fn(weight, grad, history, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, 0.0, weight, rescale_grad, clip_gradient)
            red_axes = tuple(range(1, g.ndim))
            h_new = history + jnp.mean(jnp.square(g), axis=red_axes,
                                       keepdims=True) if g.ndim > 1 \
                else history + jnp.square(g)
            denom = jnp.sqrt(h_new) + epsilon
            return (weight - lr * g / denom, h_new)
        return fn
    register_op("_contrib_group_adagrad_update", group_adagrad_update_maker,
                aliases=("group_adagrad_update",), differentiable=False)

    # ---- FTML (reference: src/operator/optimizer_op.cc ftml_update) -----
    def ftml_update_maker(beta1=0.6, beta2=0.999, epsilon=1e-8, t=1,
                          wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
        def fn(weight, grad, d, v, z, lr):
            lr = lr.astype(weight.dtype)
            g = _prep_grad(grad, wd, weight, rescale_grad, clip_grad)
            v_new = beta2 * v + (1 - beta2) * g * g
            d_new = (1 - beta1 ** t) / lr * (
                jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
            sigma = d_new - beta1 * d
            z_new = beta1 * z + (1 - beta1) * g - sigma * weight
            w_new = -z_new / d_new
            return (w_new, d_new, v_new, z_new)
        return fn
    register_op("ftml_update", ftml_update_maker, differentiable=False)

    # ---- LAMB (reference: src/operator/optimizer_op.cc
    # lamb_update_phase1/phase2) — phase1 emits the adam-style direction,
    # phase2 applies it with the layerwise trust ratio ----------------------
    def lamb_phase1_maker(beta1=0.9, beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
        def fn(weight, grad, mean, var):
            g = grad.astype(jnp.float32) * rescale_grad
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            w32 = weight.astype(jnp.float32)
            m_new = beta1 * mean + (1 - beta1) * g
            v_new = beta2 * var + (1 - beta2) * g * g
            if bias_correction:
                m_hat = m_new / (1 - beta1 ** t)
                v_hat = v_new / (1 - beta2 ** t)
            else:
                m_hat, v_hat = m_new, v_new
            direction = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w32
            return (direction.astype(weight.dtype), m_new, v_new)
        return fn
    register_op("lamb_update_phase1", lamb_phase1_maker,
                differentiable=False)

    def lamb_phase2_maker(lower_bound=-1.0, upper_bound=-1.0):
        def fn(weight, g, r1, r2, lr):
            # r1 = ||w||, r2 = ||direction|| (0-d inputs from the frontend)
            r1c = r1
            if lower_bound > 0:
                r1c = jnp.maximum(r1c, lower_bound)
            if upper_bound > 0:
                r1c = jnp.minimum(r1c, upper_bound)
            ratio = jnp.where((r1c > 0) & (r2 > 0), r1c / r2,
                              jnp.ones_like(r1c))
            lr = lr.astype(weight.dtype)
            return weight - lr * ratio.astype(weight.dtype) * g
        return fn
    register_op("lamb_update_phase2", lamb_phase2_maker,
                differentiable=False)

    def mp_lamb_phase1_maker(beta1=0.9, beta2=0.999, epsilon=1e-6, t=1,
                             bias_correction=True, wd=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
        inner = lamb_phase1_maker(beta1, beta2, epsilon, t, bias_correction,
                                  wd, rescale_grad, clip_gradient)

        def fn(weight, grad, mean, var, weight32):
            d, m, v = inner(weight32, grad, mean, var)
            return (d.astype(jnp.float32), m, v)
        return fn
    register_op("mp_lamb_update_phase1", mp_lamb_phase1_maker,
                differentiable=False)

    def mp_lamb_phase2_maker(lower_bound=-1.0, upper_bound=-1.0):
        inner = lamb_phase2_maker(lower_bound, upper_bound)

        def fn(weight, g, r1, r2, weight32, lr):
            w32 = inner(weight32, g, r1, r2, lr)
            return (w32.astype(weight.dtype), w32)
        return fn
    register_op("mp_lamb_update_phase2", mp_lamb_phase2_maker,
                differentiable=False)

    # ---- multi_lars (reference: src/operator/contrib/multi_lars.cc) -----
    # Batched trust-ratio computation over stacked per-layer norms.
    def multi_lars_maker(eta=0.001, eps=1e-8, rescale_grad=1.0):
        def fn(lrs, weights_sum_sq, grads_sum_sq, wds):
            w_norm = jnp.sqrt(weights_sum_sq)
            g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
            trust = eta * w_norm / (g_norm + wds * w_norm + eps)
            trust = jnp.where((w_norm > 0) & (g_norm > 0), trust,
                              jnp.ones_like(trust))
            return lrs * trust
        return fn
    register_op("multi_lars", multi_lars_maker, differentiable=False)


_register()
