"""Control-flow operators: ``_foreach`` / ``_while_loop`` / ``_cond``.

Reference parity (SURVEY.md §2.2 contrib long tail):
  src/operator/control_flow.cc registers _foreach/_while_loop/_cond as ops
  whose bodies are NNVM *subgraphs* stored in node attributes, so dynamic
  models (variable-step RNNs, beam search) run inside ONE executor graph.

TPU-first design: the subgraph attribute here is a traced ``Symbol`` and
the op bodies ARE the structured-control-flow primitives XLA requires —
this is the one place the reference's design and the TPU's constraints
coincide exactly (the reference added these ops so control flow could live
inside the graph; jit *demands* it live inside the graph):

  - ``_foreach``    ≡ ``lax.scan`` over axis 0.
  - ``_while_loop`` ≡ a masked ``lax.scan`` over ``max_iterations`` steps.
    ``lax.while_loop`` is not reverse-mode differentiable (XLA cannot
    record a dynamic trip count), so the registry op — which the symbol
    executor differentiates through ``jax.vjp`` — trades early exit for a
    bounded scan with an ``active`` mask, keeping backward exact.  The
    imperative frontend (ndarray/contrib.py) keeps the early-exiting
    ``lax.while_loop`` for inference.
  - ``_cond``       ≡ ``lax.cond`` (both branches traced once).

Free variables (weights captured by the body closure) become explicit op
inputs, so executor backward produces their gradients — same contract as
the reference's subgraph FGradient.
"""
from __future__ import annotations

import json as _json

from .register import register_op

__all__ = ["SubgraphAttr"]


class SubgraphAttr:
    """A Symbol-valued node attribute.

    Identity-hashed so the op compile cache can key on it (Symbol itself
    defines arithmetic dunders and must not be hashed); serializes to the
    subgraph's JSON so control-flow graphs round-trip through
    ``Symbol.tojson`` / ``load_json`` like the reference's subgraph attrs.
    """

    __slots__ = ("sym",)

    def __init__(self, sym):
        self.sym = sym

    def __hash__(self):
        return id(self.sym)

    def __eq__(self, other):
        return isinstance(other, SubgraphAttr) and other.sym is self.sym

    def __str__(self):
        return self.sym.tojson()

    def __repr__(self):
        return f"<SubgraphAttr {self.sym!r}>"


def _names(v):
    """Attr tuples may arrive as JSON-parsed lists after a load round-trip."""
    if isinstance(v, str):
        v = _json.loads(v)
    return tuple(v)


def _register():
    import jax
    import jax.numpy as jnp

    def foreach_maker(subgraph=None, data_names=(), state_names=(),
                      free_names=(), n_outs=1):
        data_names = _names(data_names)
        state_names = _names(state_names)
        free_names = _names(free_names)
        run = subgraph.sym.compile()
        nd_, ns = len(data_names), len(state_names)

        def fn(*vals):
            data = vals[:nd_]
            states = tuple(vals[nd_:nd_ + ns])
            feed_free = dict(zip(free_names, vals[nd_ + ns:]))

            def step(carry, xs):
                feed = dict(zip(data_names, xs))
                feed.update(zip(state_names, carry))
                feed.update(feed_free)
                res = run(feed)
                return tuple(res[n_outs:]), tuple(res[:n_outs])

            carry, ys = jax.lax.scan(step, states, tuple(data))
            out = tuple(ys) + tuple(carry)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_foreach", foreach_maker,
                ref="src/operator/control_flow.cc (foreach)")

    def while_loop_maker(cond_subgraph=None, body_subgraph=None,
                         loop_names=(), free_names=(), n_outs=1,
                         max_iterations=0):
        loop_names = _names(loop_names)
        free_names = _names(free_names)
        cond_run = cond_subgraph.sym.compile()
        body_run = body_subgraph.sym.compile()
        nl = len(loop_names)
        T = int(max_iterations)

        def fn(*vals):
            lv0 = tuple(vals[:nl])
            feed_free = dict(zip(free_names, vals[nl:]))

            def feed_of(lv):
                feed = dict(zip(loop_names, lv))
                feed.update(feed_free)
                return feed

            def step(carry, _):
                active, lv = carry
                active = jnp.logical_and(
                    active,
                    jnp.asarray(cond_run(feed_of(lv))[0]).reshape(())
                    .astype(bool))
                res = body_run(feed_of(lv))
                outs = tuple(jnp.where(active, o, jnp.zeros_like(o))
                             for o in res[:n_outs])
                new_lv = tuple(
                    jnp.where(active, n, p)
                    for n, p in zip(res[n_outs:], lv))
                return (active, new_lv), outs

            (_, lv), bufs = jax.lax.scan(
                step, (jnp.asarray(True), lv0), None, length=T)
            out = tuple(bufs) + tuple(lv)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_while_loop", while_loop_maker,
                ref="src/operator/control_flow.cc (while_loop)")

    def cond_maker(then_subgraph=None, else_subgraph=None, free_names=(),
                   n_outs=1):
        free_names = _names(free_names)
        then_run = then_subgraph.sym.compile()
        else_run = else_subgraph.sym.compile()

        def fn(pred, *frees):
            feed = dict(zip(free_names, frees))
            p = jnp.asarray(pred).reshape(()).astype(bool)
            out = jax.lax.cond(p,
                               lambda f: tuple(then_run(f)[:n_outs]),
                               lambda f: tuple(else_run(f)[:n_outs]),
                               feed)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_cond", cond_maker,
                ref="src/operator/control_flow.cc (cond)")


_register()
