"""Control-flow operators: ``_foreach`` / ``_while_loop`` / ``_cond``.

Reference parity (SURVEY.md §2.2 contrib long tail):
  src/operator/control_flow.cc registers _foreach/_while_loop/_cond as ops
  whose bodies are NNVM *subgraphs* stored in node attributes, so dynamic
  models (variable-step RNNs, beam search) run inside ONE executor graph.

TPU-first design: the subgraph attribute here is a traced ``Symbol`` and
the op bodies ARE the structured-control-flow primitives XLA requires —
this is the one place the reference's design and the TPU's constraints
coincide exactly (the reference added these ops so control flow could live
inside the graph; jit *demands* it live inside the graph):

  - ``_foreach``    ≡ ``lax.scan`` over axis 0.
  - ``_while_loop`` ≡ a masked ``lax.scan`` over ``max_iterations`` steps.
    ``lax.while_loop`` is not reverse-mode differentiable (XLA cannot
    record a dynamic trip count), so the registry op — which the symbol
    executor differentiates through ``jax.vjp`` — trades early exit for a
    bounded scan with an ``active`` mask, keeping backward exact.  The
    imperative frontend (ndarray/contrib.py) keeps the early-exiting
    ``lax.while_loop`` for inference.
  - ``_cond``       ≡ ``lax.cond`` (both branches traced once).

Free variables (weights captured by the body closure) become explicit op
inputs, so executor backward produces their gradients — same contract as
the reference's subgraph FGradient.
"""
from __future__ import annotations

import json as _json

from .register import register_op

__all__ = ["SubgraphAttr"]


class SubgraphAttr:
    """A Symbol-valued node attribute.

    Identity-hashed so the op compile cache can key on it (Symbol itself
    defines arithmetic dunders and must not be hashed); serializes to the
    subgraph's JSON so control-flow graphs round-trip through
    ``Symbol.tojson`` / ``load_json`` like the reference's subgraph attrs.
    """

    __slots__ = ("sym",)

    def __init__(self, sym):
        self.sym = sym

    def __hash__(self):
        return id(self.sym)

    def __eq__(self, other):
        return isinstance(other, SubgraphAttr) and other.sym is self.sym

    def __str__(self):
        return self.sym.tojson()

    def __repr__(self):
        return f"<SubgraphAttr {self.sym!r}>"


def _names(v):
    """Attr tuples may arrive as JSON-parsed lists after a load round-trip."""
    if isinstance(v, str):
        v = _json.loads(v)
    return tuple(v)


def _register():
    import jax
    import jax.numpy as jnp

    # The three control-flow ops are registered needs_rng=True so a base
    # PRNG key always arrives as their LAST input (eager invoke appends
    # one; the symbol runner splits one off the per-forward key).  Bodies
    # containing sampling nodes (Dropout under is_train, _random_*) get
    # per-iteration subkeys threaded through the scan carry — fresh draws
    # every step, still one XLA compilation.  Bodies without sampling
    # ignore the key.  The executor's train/eval mode reaches the body
    # through the ``_training`` parameter (the BatchNorm convention), so
    # Dropout inside a body is real dropout under is_train=True and
    # identity at inference.

    def foreach_maker(subgraph=None, data_names=(), state_names=(),
                      free_names=(), n_outs=1, _training=False):
        data_names = _names(data_names)
        state_names = _names(state_names)
        free_names = _names(free_names)
        run = subgraph.sym.compile(training=_training)
        nd_, ns = len(data_names), len(state_names)

        takes_key = run.needs_rng    # must mirror register.op_takes_key

        def fn(*vals):
            import jax.random as jr
            key = None
            if takes_key:
                key, vals = vals[-1], vals[:-1]
            data = vals[:nd_]
            states = tuple(vals[nd_:nd_ + ns])
            feed_free = dict(zip(free_names, vals[nd_ + ns:]))

            def step(carry, xs):
                key, state = carry
                feed = dict(zip(data_names, xs))
                feed.update(zip(state_names, state))
                feed.update(feed_free)
                if takes_key:
                    key, sub = jr.split(key)
                    feed["__rng_key__"] = sub
                res = run(feed)
                return (key, tuple(res[n_outs:])), tuple(res[:n_outs])

            if not takes_key:
                key = jnp.zeros((), jnp.uint32)   # inert carry slot
            (_, carry), ys = jax.lax.scan(step, (key, states), tuple(data))
            out = tuple(ys) + tuple(carry)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_foreach", foreach_maker, needs_rng=True,
                ref="src/operator/control_flow.cc (foreach)")

    def while_loop_maker(cond_subgraph=None, body_subgraph=None,
                         loop_names=(), free_names=(), n_outs=1,
                         max_iterations=0, _training=False):
        loop_names = _names(loop_names)
        free_names = _names(free_names)
        cond_run = cond_subgraph.sym.compile(training=_training)
        body_run = body_subgraph.sym.compile(training=_training)
        nl = len(loop_names)
        T = int(max_iterations)

        takes_key = cond_run.needs_rng or body_run.needs_rng

        def fn(*vals):
            import jax.random as jr
            key = None
            if takes_key:
                key, vals = vals[-1], vals[:-1]
            lv0 = tuple(vals[:nl])
            feed_free = dict(zip(free_names, vals[nl:]))

            def feed_of(lv, sub):
                feed = dict(zip(loop_names, lv))
                feed.update(feed_free)
                if sub is not None:
                    feed["__rng_key__"] = sub
                return feed

            def step(carry, _):
                key, active, lv = carry
                kc = kb = None
                if takes_key:
                    key, kc, kb = jr.split(key, 3)
                active = jnp.logical_and(
                    active,
                    jnp.asarray(cond_run(feed_of(
                        lv, kc if cond_run.needs_rng else None))[0])
                    .reshape(()).astype(bool))
                res = body_run(feed_of(
                    lv, kb if body_run.needs_rng else None))
                outs = tuple(jnp.where(active, o, jnp.zeros_like(o))
                             for o in res[:n_outs])
                new_lv = tuple(
                    jnp.where(active, n, p)
                    for n, p in zip(res[n_outs:], lv))
                return (key, active, new_lv), outs

            if not takes_key:
                key = jnp.zeros((), jnp.uint32)   # inert carry slot
            (_, _, lv), bufs = jax.lax.scan(
                step, (key, jnp.asarray(True), lv0), None, length=T)
            out = tuple(bufs) + tuple(lv)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_while_loop", while_loop_maker, needs_rng=True,
                ref="src/operator/control_flow.cc (while_loop)")

    def cond_maker(then_subgraph=None, else_subgraph=None, free_names=(),
                   n_outs=1, _training=False):
        free_names = _names(free_names)
        then_run = then_subgraph.sym.compile(training=_training)
        else_run = else_subgraph.sym.compile(training=_training)

        takes_key = then_run.needs_rng or else_run.needs_rng

        def fn(pred, *frees):
            import jax.random as jr
            if takes_key:
                key, frees = frees[-1], frees[:-1]
                kt, ke = jr.split(key)
            feed = dict(zip(free_names, frees))
            p = jnp.asarray(pred).reshape(()).astype(bool)

            def then_branch(f):
                if then_run.needs_rng:
                    f = dict(f, __rng_key__=kt)
                return tuple(then_run(f)[:n_outs])

            def else_branch(f):
                if else_run.needs_rng:
                    f = dict(f, __rng_key__=ke)
                return tuple(else_run(f)[:n_outs])

            out = jax.lax.cond(p, then_branch, else_branch, feed)
            return out if len(out) > 1 else out[0]
        return fn
    register_op("_cond", cond_maker, needs_rng=True,
                ref="src/operator/control_flow.cc (cond)")


_register()
