"""Spatial warping operators (reference: src/operator/
{grid_generator,bilinear_sampler,spatial_transformer}.cc — SURVEY.md §2.2).

GridGenerator/BilinearSampler/SpatialTransformer back spatial-transformer
networks; on TPU the sampler is a gather+lerp that XLA fuses cleanly.
Grid convention follows the reference: normalized coords in [-1, 1],
grid layout (B, 2, H, W) with (x, y) channels.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op


def _register():
    import jax
    import jax.numpy as jnp

    def _affine_grid(theta, h, w):
        # theta (B, 6) -> sampling grid (B, 2, h, w) in [-1, 1]
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, hw)
        th = theta.reshape(-1, 2, 3)
        out = jnp.einsum("bij,jk->bik", th, base)                # (B,2,hw)
        return out.reshape(-1, 2, h, w)

    def grid_generator_maker(transform_type="affine", target_shape=(0, 0)):
        th, tw = int(target_shape[0]), int(target_shape[1])

        def fn(data):
            if transform_type == "affine":
                return _affine_grid(data, th, tw)
            # 'warp': data is (B, 2, H, W) flow field added to identity
            b, _, h, w = data.shape
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            ident = jnp.stack([gx, gy], axis=0)
            # flow is in pixels; normalize like the reference warp mode
            norm = jnp.stack([data[:, 0] * 2.0 / max(w - 1, 1),
                              data[:, 1] * 2.0 / max(h - 1, 1)], axis=1)
            return ident[None] + norm
        return fn
    register_op("GridGenerator", grid_generator_maker,
                aliases=("grid_generator",))

    def _bilinear_sample(img, grid):
        # img (C, H, W); grid (2, HO, WO) in [-1, 1] -> (C, HO, WO)
        c, h, w = img.shape
        gx = (grid[0] + 1.0) * (w - 1) / 2.0
        gy = (grid[1] + 1.0) * (h - 1) / 2.0
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        lx = gx - x0
        ly = gy - y0

        def at(yy, xx):
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = img[:, yi, xi]              # (C, HO, WO)
            return jnp.where(inb[None], v, 0.0)   # zero-pad outside

        v00 = at(y0, x0)
        v01 = at(y0, x0 + 1)
        v10 = at(y0 + 1, x0)
        v11 = at(y0 + 1, x0 + 1)
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                v10 * ly * (1 - lx) + v11 * ly * lx)[None][0]

    def bilinear_sampler_maker(cudnn_off=None):
        def fn(data, grid):
            return jax.vmap(_bilinear_sample)(data, grid)
        return fn
    register_op("BilinearSampler", bilinear_sampler_maker,
                aliases=("bilinear_sampler",))

    def spatial_transformer_maker(target_shape=(0, 0),
                                  transform_type="affine",
                                  sampler_type="bilinear",
                                  cudnn_off=None):
        th, tw = int(target_shape[0]), int(target_shape[1])

        def fn(data, loc):
            grid = _affine_grid(loc, th, tw)
            return jax.vmap(_bilinear_sample)(data, grid)
        return fn
    register_op("SpatialTransformer", spatial_transformer_maker,
                aliases=("spatial_transformer",))

    def batch_take_fn(a, indices):
        flat = a.reshape(a.shape[0], -1)
        return jnp.take_along_axis(
            flat, indices.reshape(-1, 1).astype(jnp.int32), axis=1)[:, 0]
    from .register import simple_op
    simple_op("batch_take", batch_take_fn)

    def ravel_multi_index_maker(shape=None):
        dims = tuple(int(s) for s in shape)

        def fn(idx):
            strides = _np.cumprod((1,) + dims[::-1][:-1])[::-1]
            return jnp.sum(idx * jnp.asarray(strides.copy())[:, None],
                           axis=0).astype(idx.dtype)
        return fn
    register_op("_ravel_multi_index", ravel_multi_index_maker,
                aliases=("ravel_multi_index",), differentiable=False)

    def unravel_index_maker(shape=None):
        dims = tuple(int(s) for s in shape)

        def fn(idx):
            outs = []
            rem = idx
            strides = _np.cumprod((1,) + dims[::-1][:-1])[::-1]
            for s in strides:
                outs.append(rem // s)
                rem = rem % s
            return jnp.stack(outs, axis=0).astype(idx.dtype)
        return fn
    register_op("_unravel_index", unravel_index_maker,
                aliases=("unravel_index",), differentiable=False)


_register()
