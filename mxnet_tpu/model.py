"""Checkpoint helpers + BatchEndParam (reference: python/mxnet/model.py).

The reference file also carries the legacy ``FeedForward`` API; its role was
subsumed by ``mx.mod.Module`` years before the fork era, so here only the
pieces the Module/callback paths need are kept: ``BatchEndParam``,
``save_checkpoint``/``load_checkpoint`` with the reference's on-disk layout
(``prefix-symbol.json`` + ``prefix-%04d.params``; ``arg:``/``aux:`` key
prefixes inside the params dict — SURVEY.md §5.4).
"""
from __future__ import annotations

import collections
from typing import Dict, Tuple

from .ndarray import NDArray
from .ndarray.utils import save as nd_save, load as nd_load

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, remove_amp_cast: bool = True) -> None:
    """Write ``prefix-symbol.json`` and ``prefix-%04d.params``."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(fname: str) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
    """Split a saved dict into (arg_params, aux_params) by key prefix."""
    save_dict = nd_load(fname)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:                       # un-prefixed: Gluon-style params file
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """Load (symbol, arg_params, aux_params) written by save_checkpoint."""
    from . import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(f"{prefix}-{epoch:04d}.params")
    return symbol, arg_params, aux_params
