"""Checkpoint helpers + BatchEndParam (reference: python/mxnet/model.py).

The reference file also carries the legacy ``FeedForward`` API (kept below
as a thin Module adapter), plus: ``BatchEndParam``,
``save_checkpoint``/``load_checkpoint`` with the reference's on-disk layout
(``prefix-symbol.json`` + ``prefix-%04d.params``; ``arg:``/``aux:`` key
prefixes inside the params dict — SURVEY.md §5.4).
"""
from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.utils import save as nd_save, load as nd_load

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "FeedForward"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, remove_amp_cast: bool = True) -> None:
    """Write ``prefix-symbol.json`` and ``prefix-%04d.params``."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(fname: str) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
    """Split a saved dict into (arg_params, aux_params) by key prefix."""
    save_dict = nd_load(fname)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:                       # un-prefixed: Gluon-style params file
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """Load (symbol, arg_params, aux_params) written by save_checkpoint."""
    from . import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(f"{prefix}-{epoch:04d}.params")
    return symbol, arg_params, aux_params


class FeedForward:
    """The pre-Module training API (reference: python/mxnet/model.py
    FeedForward) — kept as a thin adapter over ``mx.mod.Module``, which is
    what the reference itself deprecated it in favor of.  Old tutorials'
    ``FeedForward.create(sym, X=..., y=...)`` keep working; numpy inputs
    wrap into NDArrayIter automatically."""

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        from .context import cpu as _cpu
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else _cpu()
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._opt_kwargs = kwargs
        self._module = None

    # -- helpers -----------------------------------------------------------
    def _names(self):
        """(data_names, label_names) derived from the symbol: label vars
        follow the reference ``*_label`` naming convention; everything the
        symbol itself declares a variable for is excluded from params by
        Module via these lists."""
        inputs = self.symbol.list_inputs()
        label_names = tuple(n for n in inputs if n.endswith("_label"))
        if "data" in inputs:
            data_names = ("data",)
        else:
            params = {n for n in inputs
                      if n.endswith(("weight", "bias", "gamma", "beta"))}
            cands = [n for n in inputs
                     if n not in params and n not in label_names]
            data_names = tuple(cands[:1]) or ("data",)
        return data_names, label_names

    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter
        if isinstance(X, DataIter):
            return X
        dn, ln = self._names()
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                           shuffle=shuffle, data_name=dn[0],
                           label_name=ln[0] if ln else "softmax_label")

    # -- API ---------------------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None):
        import logging as _logging
        from .module import Module
        it = self._as_iter(X, y, shuffle=True)
        if eval_data is not None:
            # legacy (val_x, val_y) tuple form accepted HERE only — a
            # bare 2-tuple of X would be ambiguous elsewhere
            if isinstance(eval_data, tuple) and len(eval_data) == 2:
                eval_data = self._as_iter(*eval_data)
            else:
                eval_data = self._as_iter(eval_data)
        dn, ln = self._names()
        self._module = Module(self.symbol, data_names=dn, label_names=ln,
                              context=self.ctx, logger=logger or _logging)
        self._label_shapes = it.provide_label
        opt_params = dict(self._opt_kwargs)
        opt_params.setdefault("learning_rate", 0.01)
        self._module.fit(
            it, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer, optimizer_params=opt_params,
            initializer=self.initializer,
            arg_params=self.arg_params, aux_params=self.aux_params,
            allow_missing=self.arg_params is not None,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    @staticmethod
    def _num_examples(X):
        # dict/list inputs are legal everywhere NDArrayIter is
        if isinstance(X, dict):
            X = next(iter(X.values()))
        elif isinstance(X, (list, tuple)):
            X = X[0]
        return len(X)

    def _lazy_bind(self, it, label_shapes=None) -> None:
        if self._module is not None:
            return
        if self.arg_params is None:
            raise MXNetError(
                "FeedForward: model has no parameters — call fit() or "
                "load() before predict()/score()")
        from .module import Module
        dn, ln = self._names()
        self._module = Module(self.symbol, data_names=dn, label_names=ln,
                              context=self.ctx)
        self._module.bind(data_shapes=it.provide_data,
                          label_shapes=label_shapes or it.provide_label,
                          for_training=False)
        self._module.init_params(arg_params=self.arg_params,
                                 aux_params=self.aux_params)

    def predict(self, X, num_batch=None, label_shapes=None):
        """Predict over numpy/dict/DataIter input.  Loss heads keep
        their label input in the graph but ignore it at inference, so
        zero labels are fed; non-(N,)-shaped labels can be described via
        ``label_shapes`` (defaults to the shapes seen at fit time)."""
        import numpy as _np
        from .io import DataIter
        if label_shapes is None:
            label_shapes = getattr(self, "_label_shapes", None)
        _, label_names = self._names()
        if not isinstance(X, DataIter):
            if not label_names:
                it = self._as_iter(X)      # pure-prediction graph
            else:
                n = self._num_examples(X)
                if label_shapes:
                    # one zero array PER declared label input
                    y = {d.name: _np.zeros((n,) + tuple(d.shape[1:]),
                                           _np.float32)
                         for d in label_shapes}
                else:
                    y = _np.zeros((n,), _np.float32)
                it = self._as_iter(X, y)
        else:
            it = X
        self._lazy_bind(it, label_shapes=label_shapes)
        out = self._module.predict(it, num_batch=num_batch)
        if isinstance(out, (list, tuple)):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def score(self, X, y=None, eval_metric="acc"):
        """Single metric: returns its value; composite metrics: returns
        the full {name: value} dict (nothing silently dropped)."""
        it = self._as_iter(X, y)
        self._lazy_bind(it)
        res = dict(self._module.score(it, eval_metric))
        if len(res) == 1:
            return next(iter(res.values()))
        return res

    def save(self, prefix: str, epoch: Optional[int] = None) -> None:
        e = epoch if epoch is not None else (self.num_epoch or 0)
        save_checkpoint(prefix, e, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @classmethod
    def load(cls, prefix: str, epoch: int, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, ctx=ctx, arg_params=arg_params,
                   aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @classmethod
    def create(cls, symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               **kwargs):
        """Reference one-shot constructor+fit."""
        model = cls(symbol, ctx=ctx, num_epoch=num_epoch,
                    optimizer=optimizer, initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger)
        return model
