"""Training callbacks (reference: python/mxnet/callback.py, SURVEY.md §5.5).

``Speedometer`` prints the samples/sec number the BASELINE metric reads;
``do_checkpoint`` is the epoch-level fault-tolerance story (SURVEY.md §5.3).
"""
from __future__ import annotations

import logging

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "log_train_metric", "ProgressBar"]


class Speedometer:
    """Log throughput (samples/sec) and metrics every ``frequent`` batches.

    The batch window is measured through ``observability.trace.span``
    (``callback.speed_window_us``), so the same number that prints here
    surfaces as a histogram on the metrics endpoint and as a block on
    the unified chrome-trace timeline — one clock, three views."""

    def __init__(self, batch_size: int, frequent: int = 50,
                 auto_reset: bool = True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.last_count = 0
        self._window = None           # open span over the current window

    def _restart_window(self):
        from .observability.trace import span
        self._window = span("callback.speed_window_us",
                            args={"frequent": self.frequent})
        self._window.__enter__()

    def __call__(self, param) -> None:
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                win, self._window = self._window, None
                if win is None:
                    return
                win.__exit__(None, None, None)
                speed = self.frequent * self.batch_size / \
                    (win.duration_us / 1e6)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" + \
                        "".join(f"\t{n}={v:f}" for n, v in name_value)
                    logging.info(msg, param.epoch, count, speed)
                else:
                    logging.info(
                        "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self._restart_window()
        else:
            self.init = True
            self._restart_window()


def do_checkpoint(prefix: str, period: int = 1):
    """Epoch-end callback saving ``prefix-symbol.json`` +
    ``prefix-%04d.params`` (reference: callback.do_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def module_checkpoint(mod, prefix: str, period: int = 1,
                      save_optimizer_states: bool = False):
    """Epoch-end callback on a Module (reference: callback.module_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def log_train_metric(period: int, auto_reset: bool = False):
    """Batch-end callback logging the metric every ``period`` batches."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class ProgressBar:
    """Text progress bar over total batches (reference: callback.ProgressBar)."""

    def __init__(self, total: int, length: int = 80):
        self.bar_len = length
        self.total = total

    def __call__(self, param) -> None:
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
