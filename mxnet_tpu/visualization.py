"""Network visualization (reference: python/mxnet/visualization.py —
``mx.viz.print_summary`` / ``mx.viz.plot_network``).

``print_summary`` walks the Symbol graph with inferred shapes and prints
the reference's layer table (name, output shape, params, previous
layers).  ``plot_network`` emits Graphviz dot source; rendering needs the
graphviz binary, which this image lacks, so the dot TEXT is returned
(write it to a file and render elsewhere).
"""
from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _node_shapes(sym, shape: Optional[Dict] = None):
    """ONE inference pass over a Symbol whose heads are every op node:
    returns ({name -> output shape}, {arg/aux name -> shape})."""
    if not shape:
        return {}, {}
    from .symbol.symbol import Symbol
    heads, names = [], []
    for node in sym._topo():
        if not node.is_var:
            heads.append((node, 0))
            names.append(node.name)
    big = Symbol(heads) if heads else sym
    try:
        arg_shapes, out_shapes, aux_shapes = big.infer_shape(**shape)
    except MXNetError:
        return {}, {}
    arg_map = dict(zip(big.list_arguments(),
                       (tuple(s) for s in arg_shapes)))
    arg_map.update(zip(big.list_auxiliary_states(),
                       (tuple(s) for s in aux_shapes)))
    shapes = dict(zip(names, (tuple(s) for s in out_shapes)))
    shapes.update(arg_map)
    return shapes, arg_map


def print_summary(symbol, shape: Optional[Dict] = None,
                  line_length: int = 98, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a Keras-style layer table (reference: mx.viz.print_summary).
    ``shape``: dict of input name -> shape enabling output-shape and
    param counting."""
    shapes, arg_shapes = _node_shapes(symbol, shape)
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #",
              "Previous Layer"]

    def print_row(vals):
        line = ""
        for v, pos in zip(vals, positions):
            line = (line + str(v))[:pos - 1].ljust(pos)
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total = 0
    import numpy as _np
    data_names = {n for n in symbol.list_inputs()
                  if shape and n in shape}
    for node in symbol._topo():
        if node.is_var:
            if node.name in data_names:
                print_row([f"{node.name} (null)",
                           shapes.get(node.name, ""), 0, ""])
            continue
        n_params = 0
        prevs = []
        for p, _i in node.inputs:
            if p.is_var and p.name not in data_names:
                s = arg_shapes.get(p.name)
                if s:
                    n_params += int(_np.prod(s))
            else:
                prevs.append(p.name)
        total += n_params
        print_row([f"{node.name} ({node.op})",
                   shapes.get(node.name, ""), n_params,
                   ", ".join(prevs[:2])])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("_" * line_length)
    return total


def plot_network(symbol, title: str = "plot", shape: Optional[Dict] = None,
                 node_attrs: Optional[Dict] = None, save_format="dot"):
    """Return Graphviz dot source for the Symbol graph (reference:
    mx.viz.plot_network returns a graphviz.Digraph; no graphviz binary
    in this image, so the dot text itself is the artifact)."""
    shapes, _ = _node_shapes(symbol, shape)
    lines = [f'digraph "{title}" {{',
             "  node [shape=box, style=filled, fillcolor=lightblue];"]
    for node in symbol._topo():
        nid = f"n{id(node)}"
        label = node.name if node.is_var else f"{node.name}\\n{node.op}"
        if node.name in shapes:
            label += f"\\n{shapes[node.name]}"
        color = "lightgray" if node.is_var else "lightblue"
        lines.append(f'  {nid} [label="{label}", fillcolor={color}];')
        for p, _i in node.inputs:
            lines.append(f"  n{id(p)} -> {nid};")
    lines.append("}")
    return "\n".join(lines)
