// C NDArray + imperative-invoke ABI: the universal embedding seam.
//
// Reference parity: src/c_api/c_api.cc + c_api_ndarray.cc (SURVEY.md §2.1
// L9) — the slice every reference language binding is built from:
//   MXNDArrayCreate(Ex) / MXNDArrayFree / MXNDArrayGetShape /
//   MXNDArrayGetDType / MXNDArraySyncCopyFromCPU / MXNDArraySyncCopyToCPU /
//   MXNDArrayWaitAll / MXListAllOpNames / NNGetOpHandle /
//   MXImperativeInvoke, errors via MXNDGetLastError.
// Same contracts as the reference: opaque handles, CSR-free POD arguments,
// op parameters passed as STRINGS (the reference's attr parser does the
// string->typed conversion; here ast.literal_eval does), the output-handle
// array owned by a thread-local scratch valid until the next invoke on the
// thread (the reference's MXAPIThreadLocalEntry ret_handles discipline).
//
// TPU-native design: the reference backs these with its C++ NDArray/engine;
// here a handle IS a Python mxnet_tpu NDArray reached through embedded
// CPython, and the "engine push" is the registry's cached-jit dispatch —
// the C surface proves the seam without duplicating the runtime.

#include <Python.h>
#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_nd_last_error;

void nd_set_err(const std::string& m) { g_nd_last_error = m; }

void nd_set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* u = PyUnicode_AsUTF8(s);
      if (u) msg = u;
      Py_DECREF(s);
    }
  }
  PyErr_Clear();  // a failed str()/utf8 conversion must not leak an
                  // exception into the caller's next CPython call
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  nd_set_err(msg);
}

struct NDHandle {
  PyObject* obj = nullptr;                 // mxnet_tpu NDArray
  std::vector<uint32_t> shape_cache;
};

const char kNDBootstrap[] = R"PY(
import ast as _ast
import sys as _sys
if _MXTPU_ROOT not in _sys.path:
    _sys.path.insert(0, _MXTPU_ROOT)
import numpy as _np
import mxnet_tpu as _mx
from mxnet_tpu.ndarray.register import invoke_by_name as _invoke

# mshadow dtype codes (reference: include/mxnet/base.h TypeFlag)
_DT = {0: "float32", 1: "float64", 2: "float16", 3: "uint8", 4: "int32",
       5: "int8", 6: "int64"}
_DT_REV = {v: k for k, v in _DT.items()}

# reference OpReqType codes (include/mxnet/op_attr_types.h): null /
# write / write-inplace (same buffer semantics here) / add
_GRAD_REQ = {0: "null", 1: "write", 2: "write", 3: "add"}


def _parse_attr(v):
    """String -> typed param (the reference's dmlc::Parameter parser
    accepts lowercase booleans, which are not Python literals)."""
    if v in ("true", "True"):
        return True
    if v in ("false", "False"):
        return False
    try:
        return _ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


class _CCachedOp:
    """The C-ABI CachedOp (reference: src/imperative/cached_op.cc).

    Holds a composed Symbol; ``invoke`` walks the graph in topo order and
    dispatches every node through the registry's imperative invoke — the
    same cached-jit path MXImperativeInvoke rides — so autograd recording,
    RNG key threading, and the per-(op, shape) XLA compile cache all come
    for free, and MXAutogradBackward sees an ordinary tape.  (The
    whole-graph-jit CachedOp lives in gluon/block.py behind hybridize();
    this slice favors tape interop, the property the C training loop
    needs.)  Inputs bind to ``list_inputs()`` order — the reference
    contract for MXInvokeCachedOp's argument array."""

    def __init__(self, sym):
        if not hasattr(sym, "_heads"):
            raise TypeError("CachedOp requires a composed Symbol")
        self.sym = sym
        self.input_names = sym.list_inputs()

    def invoke(self, arrays):
        from mxnet_tpu import autograd as _ag
        from mxnet_tpu.ndarray.register import invoke_by_name
        from mxnet_tpu.symbol.symbol import _op_kwargs, _scalar_extra
        if len(arrays) != len(self.input_names):
            raise ValueError(
                f"CachedOp expects {len(self.input_names)} inputs "
                f"({self.input_names}), got {len(arrays)}")
        feed = dict(zip(self.input_names, arrays))
        vals = {}
        for node in self.sym._topo():
            if node.is_var:
                vals[(id(node), 0)] = feed[node.name]
                continue
            kwargs = _op_kwargs(node.attrs)
            if node.op in ("BatchNorm", "BatchNorm_v1", "Custom",
                           "_foreach", "_while_loop", "_cond", "Dropout"):
                kwargs.setdefault("_training", _ag.is_training())
            ins = [vals[(id(p), i)] for p, i in node.inputs]
            ins += _scalar_extra(node.op, kwargs)
            out = invoke_by_name(node.op, ins, kwargs)
            outs = out if isinstance(out, list) else [out]
            for i, o in enumerate(outs):
                vals[(id(node), i)] = o
        return [vals[(id(n), i)] for n, i in self.sym._heads]


class _CIter:
    """C-side data-iterator state.  Reference contract (c_api.cc
    MXDataIterGetData): each Get* call returns a NEW NDArray handle the
    CALLER frees with MXNDArrayFree — the iterator owns only itself."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def before_first(self):
        self.it.reset()
        self.batch = None

    def next(self):
        try:
            self.batch = self.it.next()
            return 1
        except StopIteration:
            self.batch = None
            return 0

    def current(self, field):
        if self.batch is None:
            raise RuntimeError("no current batch: call MXDataIterNext "
                               "(and check its return) first")
        v = getattr(self.batch, field)
        if isinstance(v, (list, tuple)):
            v = v[0] if v else None
        if v is None:
            raise RuntimeError(f"batch carries no {field}")
        return v

    def pad(self):
        return int(self.batch.pad or 0) if self.batch is not None else 0


class _NDCore:
    @staticmethod
    def create(shape, dev_type, dev_id, dtype):
        ctx = _mx.cpu(dev_id) if dev_type == 1 else _mx.tpu(dev_id)
        return _mx.nd.zeros(tuple(shape), dtype=_DT[dtype], ctx=ctx)

    @staticmethod
    def shape(arr):
        return tuple(arr.shape)

    @staticmethod
    def dtype_code(arr):
        return _DT_REV[_np.dtype(arr.dtype).name]

    @staticmethod
    def copy_from(arr, raw):
        a = _np.frombuffer(raw, _np.dtype(arr.dtype)).reshape(arr.shape)
        arr[:] = _mx.nd.array(a, ctx=arr.context, dtype=arr.dtype)

    @staticmethod
    def copy_to(arr):
        return arr.asnumpy().tobytes()

    @staticmethod
    def invoke(op_name, inputs, keys, vals, out=None):
        kwargs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
        res = _invoke(op_name, list(inputs), kwargs, out=out)
        return list(res) if isinstance(res, (list, tuple)) else [res]

    @staticmethod
    def list_ops():
        return _mx.nd.list_ops()

    @staticmethod
    def wait_all():
        _mx.nd.waitall()

    # ---- kvstore (reference c_api.cc MXKVStore*): handles share this
    # bootstrap so pushed/pulled arrays ARE the MXNDArray* handles ------
    @staticmethod
    def kv_create(kv_type):
        return _mx.kv.create(kv_type)

    @staticmethod
    def kv_init(kv, keys, vals, priority=0):
        # priority accepted (and ignored) so the C side's shared
        # pair-call helper can drive init too
        kv.init(list(keys), list(vals))

    @staticmethod
    def kv_push(kv, keys, vals, priority):
        # repeated keys = multi-device push of one key: group values
        groups = {}
        order = []
        for k, v in zip(keys, vals):
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(v)
        push_keys = order
        push_vals = [groups[k][0] if len(groups[k]) == 1 else groups[k]
                     for k in order]
        kv.push(push_keys, push_vals, priority=priority)

    @staticmethod
    def kv_pull(kv, keys, outs, priority):
        kv.pull(list(keys), out=list(outs), priority=priority)

    @staticmethod
    def kv_type(kv):
        return kv.type

    @staticmethod
    def kv_rank(kv):
        return kv.rank

    @staticmethod
    def kv_group_size(kv):
        return kv.num_workers

    @staticmethod
    def kv_barrier(kv):
        kv.barrier()

    # ---- autograd (reference c_api_ndarray.cc MXAutograd* entry points):
    # with MXImperativeInvoke/MXInvokeCachedOp these complete the C
    # training loop ------------------------------------------------------
    @staticmethod
    def ag_set_recording(flag):
        from mxnet_tpu import autograd as _ag
        st = _ag._st()
        prev, st.recording = st.recording, bool(flag)
        return int(prev)

    @staticmethod
    def ag_set_training(flag):
        from mxnet_tpu import autograd as _ag
        st = _ag._st()
        prev, st.training = st.training, bool(flag)
        return int(prev)

    # variables marked through the C ABI: their AGInfo's write-freshness
    # must be re-armed per MXAutogradBackward call (below).  Weak refs
    # keyed by array identity: re-marking replaces (never accumulates),
    # and freed arrays prune themselves — a long-lived C host's per-step
    # cost stays proportional to the LIVE marked set.
    _c_marked = {}

    @classmethod
    def ag_mark_variables(cls, arrs, reqs, grads):
        # the caller's grad handles ARE the accumulation buffers:
        # backward writes them in place (autograd._accum_var), so the C
        # host reads gradients back through its own MXNDArray* handles
        import weakref
        from mxnet_tpu import autograd as _ag
        arrs = list(arrs)
        _ag.mark_variables(arrs, list(grads),
                           [_GRAD_REQ[int(r)] for r in reqs])
        for a in arrs:
            cls._c_marked[id(a)] = weakref.ref(a)

    @classmethod
    def ag_backward(cls, heads, ograds, retain_graph):
        from mxnet_tpu import autograd as _ag
        # reference OpReqType contract: kWriteTo OVERWRITES on every
        # backward.  Internally 'write' uses a one-shot freshness flag
        # (the gluon Trainer re-arms it after consuming the grad); a C
        # host has no trainer, so re-arm here to keep the ABI's write
        # semantics identical to the reference's per-backward overwrite.
        dead = []
        for k, ref in cls._c_marked.items():
            a = ref()
            if a is None:
                dead.append(k)
                continue
            info = getattr(a, "_ag", None)
            if info is not None and info.grad_req == "write":
                info.fresh = True
        for k in dead:
            del cls._c_marked[k]
        _ag.backward(list(heads),
                     list(ograds) if ograds else None,
                     retain_graph=bool(retain_graph))

    # ---- data iterators (reference c_api.cc MXDataIter* over
    # src/io/iter_*.cc): creators are the string-constructible io
    # iterators; a created handle owns its current batch --------------
    _ITER_CREATORS = ("ImageRecordIter", "CSVIter", "MNISTIter",
                      "LibSVMIter", "NDArrayIter")

    @staticmethod
    def list_data_iters():
        return list(_NDCore._ITER_CREATORS)

    @staticmethod
    def iter_create(name, keys, vals):
        if name not in _NDCore._ITER_CREATORS:
            raise ValueError(f"unknown data iter creator {name!r}")
        import mxnet_tpu.io as _io
        kwargs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
        return _CIter(getattr(_io, name)(**kwargs))

    @staticmethod
    def iter_before_first(it):
        it.before_first()

    @staticmethod
    def iter_next(it):
        return it.next()

    @staticmethod
    def iter_getdata(it):
        return it.current("data")

    @staticmethod
    def iter_getlabel(it):
        return it.current("label")

    @staticmethod
    def iter_getpad(it):
        return it.pad()

    # ---- misc runtime (reference c_api.cc): version / seed / views /
    # .params-format save+load over shared handles ------------------------
    @staticmethod
    def version():
        # reference encoding: major*10000 + minor*100 + patch
        parts = (_mx.__version__.split("+")[0].split(".") + ["0", "0"])[:3]
        nums = [int("".join(ch for ch in p if ch.isdigit()) or 0)
                for p in parts]
        return nums[0] * 10000 + nums[1] * 100 + nums[2]

    @staticmethod
    def random_seed(s):
        _mx.random.seed(int(s))

    @staticmethod
    def nd_at(arr, idx):
        return arr[int(idx)]

    @staticmethod
    def nd_slice(arr, lo, hi):
        return arr[int(lo):int(hi)]

    @staticmethod
    def nd_reshape(arr, shape):
        return arr.reshape(tuple(int(s) for s in shape))

    @staticmethod
    def nd_save(fname, arrs, keys):
        if keys:
            if len(set(keys)) != len(keys):
                # a dict would silently drop arrays; the reference
                # preserves every (key, array) pair
                raise ValueError("duplicate keys in MXNDArraySave")
            _mx.nd.save(fname, dict(zip(keys, arrs)))
        else:
            _mx.nd.save(fname, list(arrs))

    @staticmethod
    def nd_load(fname):
        got = _mx.nd.load(fname)
        if isinstance(got, dict):
            ks = list(got.keys())
            return ks, [got[k] for k in ks]
        return [], list(got)

    # ---- CachedOp ------------------------------------------------------
    @staticmethod
    def cachedop_create(sym_obj):
        return _CCachedOp(sym_obj)

    @staticmethod
    def cachedop_create_json(js):
        from mxnet_tpu.symbol.symbol import load_json
        return _CCachedOp(load_json(js))

    @staticmethod
    def cachedop_invoke(cop, arrays):
        return cop.invoke(list(arrays))
)PY";

PyObject* g_ndcore_cls = nullptr;

std::once_flag g_py_init_once;

bool nd_ensure_python() {
  // PyGILState_Ensure cannot guard this (it needs a live interpreter), so
  // a once_flag serializes first-touch from concurrent C host threads
  std::call_once(g_py_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
  return true;
}

bool nd_ensure_bootstrap() {
  if (g_ndcore_cls) return true;
  Dl_info info;
  std::string root = ".";
  if (dladdr(reinterpret_cast<void*>(&nd_ensure_bootstrap), &info) &&
      info.dli_fname) {
    std::string p = info.dli_fname;
    for (int up = 0; up < 3; ++up) {
      auto pos = p.find_last_of('/');
      if (pos == std::string::npos) break;
      p = p.substr(0, pos);
    }
    if (!p.empty()) root = p;
  }
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* rootstr = PyUnicode_FromString(root.c_str());
  PyDict_SetItemString(globals, "_MXTPU_ROOT", rootstr);
  Py_DECREF(rootstr);
  PyObject* res = PyRun_String(kNDBootstrap, Py_file_input, globals, globals);
  if (!res) {
    nd_set_err_from_python();
    Py_DECREF(globals);
    return false;
  }
  Py_DECREF(res);
  g_ndcore_cls = PyDict_GetItemString(globals, "_NDCore");
  Py_XINCREF(g_ndcore_cls);
  Py_DECREF(globals);
  if (!g_ndcore_cls) {
    nd_set_err("bootstrap did not define _NDCore");
    return false;
  }
  return true;
}

// one dtype-code -> byte-size table (mirrors the bootstrap's _DT map)
bool nd_elem_size(NDHandle* h, size_t* out) {
  static const size_t kBytes[] = {4, 8, 2, 1, 4, 1, 8};
  PyObject* dt = PyObject_CallMethod(g_ndcore_cls, "dtype_code", "O",
                                     h->obj);
  if (!dt) {
    nd_set_err_from_python();
    return false;
  }
  long code = PyLong_AsLong(dt);
  Py_DECREF(dt);
  if (code < 0 ||
      code >= static_cast<long>(sizeof(kBytes) / sizeof(kBytes[0]))) {
    nd_set_err("unknown dtype code");
    return false;
  }
  *out = kBytes[code];
  return true;
}

// thread-local output scratch (reference: MXAPIThreadLocalEntry) — the
// handle-pointer array returned by MXImperativeInvoke lives here until the
// thread's next invoke
thread_local std::vector<void*> g_ret_handles;
// op-name table for MXListAllOpNames: interned once, immortal
std::vector<std::string>* g_op_names = nullptr;
std::vector<const char*>* g_op_name_ptrs = nullptr;

}  // namespace

extern "C" {

const char* MXNDGetLastError() { return g_nd_last_error.c_str(); }

int MXNDArrayCreateEx(const uint32_t* shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype, void** out) {
  (void)delay_alloc;  // XLA owns allocation; the flag is accepted for parity
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* tup = PyTuple_New(ndim);
    for (uint32_t i = 0; i < ndim; ++i)
      PyTuple_SET_ITEM(tup, i, PyLong_FromUnsignedLong(shape[i]));
    PyObject* obj = PyObject_CallMethod(g_ndcore_cls, "create", "Oiii",
                                        tup, dev_type, dev_id, dtype);
    Py_DECREF(tup);
    if (!obj) {
      nd_set_err_from_python();
      break;
    }
    auto* h = new NDHandle();
    h->obj = obj;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                    int dev_id, int delay_alloc, void** out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc,
                           /*dtype=float32*/ 0, out);
}

int MXNDArrayFree(void* handle) {
  auto* h = static_cast<NDHandle*>(handle);
  if (!h) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
  return 0;
}

int MXNDArrayGetShape(void* handle, uint32_t* out_dim,
                      const uint32_t** out_pdata) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "shape", "O", h->obj);
  if (r) {
    Py_ssize_t n = PyTuple_Size(r);
    h->shape_cache.resize(n);
    for (Py_ssize_t i = 0; i < n; ++i)
      h->shape_cache[i] = static_cast<uint32_t>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, i)));
    *out_dim = static_cast<uint32_t>(n);
    *out_pdata = h->shape_cache.data();
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetDType(void* handle, int* out_dtype) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "dtype_code", "O",
                                    h->obj);
  if (r) {
    *out_dtype = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyFromCPU(void* handle, const void* data, size_t size) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  // size is an ELEMENT count (reference contract); bytes follow dtype
  size_t esize = 0;
  if (nd_elem_size(h, &esize)) {
    PyObject* raw = PyBytes_FromStringAndSize(
        static_cast<const char*>(data), size * esize);
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "copy_from", "OO",
                                      h->obj, raw);
    Py_DECREF(raw);
    if (r) {
      Py_DECREF(r);
      rc = 0;
    } else {
      nd_set_err_from_python();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyToCPU(void* handle, void* data, size_t size) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "copy_to", "O", h->obj);
  if (r) {
    char* buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
      // size is the caller's buffer ELEMENT count (reference contract):
      // never write more than the caller allocated
      size_t esize = 0;
      if (nd_elem_size(h, &esize)) {
        if (static_cast<size_t>(n) > size * esize) {
          nd_set_err("destination buffer too small for array");
        } else {
          std::memcpy(data, buf, n);
          rc = 0;
        }
      }
    } else {
      nd_set_err("output buffer read failed");
    }
    Py_DECREF(r);
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayWaitAll() {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  if (nd_ensure_bootstrap()) {
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "wait_all", nullptr);
    if (r) {
      Py_DECREF(r);
      rc = 0;
    } else {
      nd_set_err_from_python();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

int MXListAllOpNames(uint32_t* out_size, const char*** out_array) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    if (!g_op_names) {
      PyObject* r = PyObject_CallMethod(g_ndcore_cls, "list_ops", nullptr);
      if (!r) {
        nd_set_err_from_python();
        break;
      }
      g_op_names = new std::vector<std::string>();
      g_op_name_ptrs = new std::vector<const char*>();
      Py_ssize_t n = PyList_Size(r);
      g_op_names->reserve(n);
      for (Py_ssize_t i = 0; i < n; ++i) {
        const char* u = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
        if (u) g_op_names->emplace_back(u);
        else PyErr_Clear();
      }
      for (auto& s : *g_op_names) g_op_name_ptrs->push_back(s.c_str());
      Py_DECREF(r);
    }
    *out_size = static_cast<uint32_t>(g_op_name_ptrs->size());
    *out_array = g_op_name_ptrs->data();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

// An op handle is the interned name pointer from the table above — stable
// for the process lifetime (the reference hands out nnvm::Op*; the name is
// this registry's primary key).
int NNGetOpHandle(const char* op_name, void** out) {
  uint32_t n = 0;
  const char** names = nullptr;
  if (MXListAllOpNames(&n, &names) != 0) return -1;
  for (uint32_t i = 0; i < n; ++i) {
    if (std::strcmp(names[i], op_name) == 0) {
      *out = const_cast<char*>(names[i]);
      return 0;
    }
  }
  nd_set_err(std::string("operator not registered: ") + op_name);
  return -1;
}

int MXImperativeInvoke(void* creator, int num_inputs, void** inputs,
                       int* num_outputs, void*** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  const char* op_name = static_cast<const char*>(creator);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  // reference contract (c_api_ndarray.cc): caller-supplied output handles
  // (*outputs non-NULL, *num_outputs > 0) request an IN-PLACE write into
  // those arrays (the out= path); otherwise the library allocates
  bool in_place = (*outputs != nullptr && *num_outputs > 0);
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* ins = PyList_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i) {
      PyObject* o = static_cast<NDHandle*>(inputs[i])->obj;
      Py_INCREF(o);
      PyList_SET_ITEM(ins, i, o);
    }
    PyObject* keys = PyList_New(num_params);
    PyObject* vals = PyList_New(num_params);
    for (int i = 0; i < num_params; ++i) {
      PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
      PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
    }
    PyObject* out_arg;
    if (in_place) {
      out_arg = PyList_New(*num_outputs);
      for (int i = 0; i < *num_outputs; ++i) {
        PyObject* o = static_cast<NDHandle*>((*outputs)[i])->obj;
        Py_INCREF(o);
        PyList_SET_ITEM(out_arg, i, o);
      }
    } else {
      out_arg = Py_None;
      Py_INCREF(out_arg);
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "invoke", "sOOOO",
                                      op_name, ins, keys, vals, out_arg);
    Py_DECREF(ins);
    Py_DECREF(keys);
    Py_DECREF(vals);
    Py_DECREF(out_arg);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    if (in_place) {
      // results were written into the caller's handles; leave them be
      Py_DECREF(r);
      rc = 0;
      break;
    }
    Py_ssize_t n = PyList_Size(r);
    g_ret_handles.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      auto* h = new NDHandle();
      h->obj = PyList_GET_ITEM(r, i);
      Py_INCREF(h->obj);
      g_ret_handles.push_back(h);
    }
    Py_DECREF(r);
    *num_outputs = static_cast<int>(n);
    *outputs = g_ret_handles.data();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// MXKVStore*: the store C ABI (reference src/c_api/c_api.cc kvstore slice).
// Lives in THIS library so pushed/pulled values are the same NDHandle
// objects MXNDArrayCreate hands out — one seam, like the reference's
// single libmxnet.so.  Int keys (the classic surface); a handle is a
// Python mxnet_tpu KVStore.
// ---------------------------------------------------------------------------

namespace {

struct KVHandle {
  PyObject* obj = nullptr;     // mxnet_tpu KVStore
  std::string type_cache;
};

// shared body: build [keys], [value-objs] lists and call a _NDCore kv_*
// classmethod.  vals[i] are NDHandle*.
int kv_call_pairs(const char* method, void* handle, uint32_t num,
                  const int* keys, void** vals, int priority) {
  auto* h = static_cast<KVHandle*>(handle);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* klist = PyList_New(num);
    PyObject* vlist = PyList_New(num);
    bool bad = false;
    for (uint32_t i = 0; i < num; ++i) {
      PyList_SET_ITEM(klist, i, PyLong_FromLong(keys[i]));
      auto* nd = static_cast<NDHandle*>(vals[i]);
      if (!nd || !nd->obj) {
        bad = true;
        break;
      }
      Py_INCREF(nd->obj);
      PyList_SET_ITEM(vlist, i, nd->obj);
    }
    if (bad) {
      Py_DECREF(klist);
      Py_DECREF(vlist);
      nd_set_err("null NDArray handle in kvstore call");
      break;
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, method, "OOOi",
                                      h->obj, klist, vlist, priority);
    Py_DECREF(klist);
    Py_DECREF(vlist);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    Py_DECREF(r);
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

}  // namespace

extern "C" {

int MXKVStoreCreate(const char* type, void** out) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* obj = PyObject_CallMethod(g_ndcore_cls, "kv_create", "s",
                                        type ? type : "local");
    if (!obj) {
      nd_set_err_from_python();
      break;
    }
    auto* h = new KVHandle();
    h->obj = obj;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreFree(void* handle) {
  auto* h = static_cast<KVHandle*>(handle);
  if (!h) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
  return 0;
}

int MXKVStoreInit(void* handle, uint32_t num, const int* keys,
                  void** vals) {
  // init has no priority in the C signature; the shared helper (which
  // also guards null handles) passes a dummy 0 the bootstrap ignores
  return kv_call_pairs("kv_init", handle, num, keys, vals, 0);
}

int MXKVStorePush(void* handle, uint32_t num, const int* keys, void** vals,
                  int priority) {
  return kv_call_pairs("kv_push", handle, num, keys, vals, priority);
}

int MXKVStorePull(void* handle, uint32_t num, const int* keys, void** outs,
                  int priority) {
  return kv_call_pairs("kv_pull", handle, num, keys, outs, priority);
}

int MXKVStoreGetType(void* handle, const char** out_type) {
  auto* h = static_cast<KVHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "kv_type", "O", h->obj);
  if (r) {
    const char* u = PyUnicode_AsUTF8(r);
    h->type_cache = u ? u : "";
    *out_type = h->type_cache.c_str();
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreGetRank(void* handle, int* out) {
  auto* h = static_cast<KVHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "kv_rank", "O", h->obj);
  if (r) {
    *out = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreGetGroupSize(void* handle, int* out) {
  auto* h = static_cast<KVHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "kv_group_size", "O",
                                    h->obj);
  if (r) {
    *out = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreBarrier(void* handle) {
  auto* h = static_cast<KVHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, "kv_barrier", "O",
                                    h->obj);
  if (r) {
    Py_DECREF(r);
    rc = 0;
  } else {
    nd_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// MXAutograd* + MXCreateCachedOp/MXInvokeCachedOp: the TRAINING slice of the
// C ABI (reference: src/c_api/c_api_ndarray.cc autograd entry points +
// src/imperative/cached_op.cc).  With the MXNDArray*/MXImperativeInvoke
// surface above, a pure-C host can run a full training step: create arrays,
// mark variables with gradient buffers, record a forward (imperative ops or
// a CachedOp over a symbol), call backward, and apply sgd_update — the loop
// the reference's Scala/Horovod integrations drive through libmxnet.so.
//
// Symbol interop: MXCreateCachedOp accepts a SymbolHandle from the
// symbol-slice library.  Both libraries embed the SAME CPython interpreter
// (one process), and every handle type in this ABI family starts with its
// PyObject* — the shared-layout contract that lets the slices exchange
// handles the way the reference's single libmxnet.so shares nnvm pointers
// across c_api files.  MXCreateCachedOpFromJSON needs only THIS library.
// ---------------------------------------------------------------------------

namespace {

struct CachedOpHandle {
  PyObject* obj = nullptr;     // bootstrap _CCachedOp
};

// any ABI handle whose first member is its PyObject* (NDHandle, SymHandle)
struct AnyPyHandle {
  PyObject* obj;
};

int ag_set_flag(const char* method, int value, int* prev) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  if (nd_ensure_bootstrap()) {
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, method, "i", value);
    if (r) {
      if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
      Py_DECREF(r);
      rc = 0;
    } else {
      nd_set_err_from_python();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

PyObject* handle_list(void** handles, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    auto* h = static_cast<AnyPyHandle*>(handles[i]);
    if (!h || !h->obj) {
      Py_DECREF(lst);
      return nullptr;
    }
    Py_INCREF(h->obj);
    PyList_SET_ITEM(lst, i, h->obj);
  }
  return lst;
}

}  // namespace

extern "C" {

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  return ag_set_flag("ag_set_recording", is_recording, prev);
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  return ag_set_flag("ag_set_training", is_training, prev);
}

int MXAutogradMarkVariables(uint32_t num_var, void** var_handles,
                            uint32_t* reqs_array, void** grad_handles) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* vars = handle_list(var_handles, num_var);
    PyObject* grads = handle_list(grad_handles, num_var);
    if (!vars || !grads) {
      Py_XDECREF(vars);
      Py_XDECREF(grads);
      nd_set_err("null NDArray handle in MXAutogradMarkVariables");
      break;
    }
    PyObject* reqs = PyList_New(num_var);
    for (uint32_t i = 0; i < num_var; ++i)
      PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(reqs_array[i]));
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "ag_mark_variables",
                                      "OOO", vars, reqs, grads);
    Py_DECREF(vars);
    Py_DECREF(reqs);
    Py_DECREF(grads);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    Py_DECREF(r);
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

// NOTE: deliberately no MXAutogradBackwardEx export — the reference's Ex
// variant has a 10-parameter signature (num_variables/create_graph/
// is_train/grad_stypes...); exporting the name with THIS 4-arg layout
// would silently misparse a header-conformant caller's arguments.
int MXAutogradBackward(uint32_t num_output, void** output_handles,
                       void** ograd_handles, int retain_graph) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* heads = handle_list(output_handles, num_output);
    if (!heads) {
      nd_set_err("null NDArray handle in MXAutogradBackward");
      break;
    }
    PyObject* ograds;
    if (ograd_handles) {
      // reference contract: a NULL ENTRY inside the array means "default
      // (ones-like) head gradient for this head" — map it to None
      ograds = PyList_New(num_output);
      if (!ograds) {
        Py_DECREF(heads);
        nd_set_err("ograd list allocation failed");
        break;
      }
      for (uint32_t i = 0; i < num_output; ++i) {
        auto* h = static_cast<AnyPyHandle*>(ograd_handles[i]);
        PyObject* o = (h && h->obj) ? h->obj : Py_None;
        Py_INCREF(o);
        PyList_SET_ITEM(ograds, i, o);
      }
    } else {
      ograds = Py_None;
      Py_INCREF(ograds);
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "ag_backward", "OOi",
                                      heads, ograds, retain_graph);
    Py_DECREF(heads);
    Py_DECREF(ograds);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    Py_DECREF(r);
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXCreateCachedOp(void* sym_handle, void** out) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    auto* sh = static_cast<AnyPyHandle*>(sym_handle);
    if (!sh || !sh->obj) {
      nd_set_err("null symbol handle");
      break;
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "cachedop_create", "O",
                                      sh->obj);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    auto* h = new CachedOpHandle();
    h->obj = r;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXCreateCachedOpFromJSON(const char* json, void** out) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "cachedop_create_json",
                                      "s", json);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    auto* h = new CachedOpHandle();
    h->obj = r;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXFreeCachedOp(void* handle) {
  auto* h = static_cast<CachedOpHandle*>(handle);
  if (!h) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
  return 0;
}

int MXInvokeCachedOp(void* handle, int num_inputs, void** inputs,
                     int* num_outputs, void*** outputs) {
  auto* h = static_cast<CachedOpHandle*>(handle);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* ins = handle_list(inputs, static_cast<uint32_t>(num_inputs));
    if (!ins) {
      nd_set_err("null NDArray handle in MXInvokeCachedOp");
      break;
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "cachedop_invoke",
                                      "OO", h->obj, ins);
    Py_DECREF(ins);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    Py_ssize_t n = PyList_Size(r);
    g_ret_handles.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      auto* nh = new NDHandle();
      nh->obj = PyList_GET_ITEM(r, i);
      Py_INCREF(nh->obj);
      g_ret_handles.push_back(nh);
    }
    Py_DECREF(r);
    *num_outputs = static_cast<int>(n);
    *outputs = g_ret_handles.data();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// MXDataIter*: the data-iterator C ABI (reference: src/c_api/c_api.cc
// MXDataIter slice over src/io/iter_*.cc).  Creator handles are interned
// name pointers (the MXListAllOpNames discipline).  Ownership follows the
// reference contract exactly: every MXDataIterGetData/GetLabel call
// returns a NEW NDArray handle that the CALLER releases with
// MXNDArrayFree (upstream language bindings wrap it in an NDArray whose
// destructor does so); the iterator handle owns only itself.
// ---------------------------------------------------------------------------

namespace {

struct IterHandle {
  PyObject* obj = nullptr;                 // bootstrap _CIter
};

std::vector<std::string>* g_iter_names = nullptr;
std::vector<const char*>* g_iter_name_ptrs = nullptr;

int iter_simple_call(void* handle, const char* method, PyObject** out) {
  auto* h = static_cast<IterHandle*>(handle);
  if (!nd_ensure_bootstrap()) return -1;
  PyObject* r = PyObject_CallMethod(g_ndcore_cls, method, "O", h->obj);
  if (!r) {
    nd_set_err_from_python();
    return -1;
  }
  *out = r;
  return 0;
}

}  // namespace

extern "C" {

int MXListDataIters(uint32_t* out_size, void*** out_array) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    if (!g_iter_names) {
      PyObject* r = PyObject_CallMethod(g_ndcore_cls, "list_data_iters",
                                        nullptr);
      if (!r) {
        nd_set_err_from_python();
        break;
      }
      g_iter_names = new std::vector<std::string>();
      g_iter_name_ptrs = new std::vector<const char*>();
      for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
        const char* u = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
        if (u) g_iter_names->emplace_back(u);
        else PyErr_Clear();
      }
      for (auto& s : *g_iter_names)
        g_iter_name_ptrs->push_back(s.c_str());
      Py_DECREF(r);
    }
    *out_size = static_cast<uint32_t>(g_iter_name_ptrs->size());
    *out_array = reinterpret_cast<void**>(
        const_cast<char**>(g_iter_name_ptrs->data()));
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterCreateIter(void* creator, uint32_t num_param,
                         const char** keys, const char** vals, void** out) {
  const char* name = static_cast<const char*>(creator);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* klist = PyList_New(num_param);
    PyObject* vlist = PyList_New(num_param);
    if (!klist || !vlist) {
      Py_XDECREF(klist);
      Py_XDECREF(vlist);
      nd_set_err("param list allocation failed");
      break;
    }
    for (uint32_t i = 0; i < num_param; ++i) {
      PyList_SET_ITEM(klist, i, PyUnicode_FromString(keys[i]));
      PyList_SET_ITEM(vlist, i, PyUnicode_FromString(vals[i]));
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "iter_create", "sOO",
                                      name, klist, vlist);
    Py_DECREF(klist);
    Py_DECREF(vlist);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    auto* h = new IterHandle();
    h->obj = r;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterBeforeFirst(void* handle) {
  auto* h = static_cast<IterHandle*>(handle);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = nullptr;
  int rc = iter_simple_call(handle, "iter_before_first", &r);
  if (rc == 0) Py_DECREF(r);
  (void)h;
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterNext(void* handle, int* out) {
  auto* h = static_cast<IterHandle*>(handle);
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = nullptr;
  int rc = iter_simple_call(handle, "iter_next", &r);
  if (rc == 0) {
    *out = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
  }
  (void)h;
  PyGILState_Release(gil);
  return rc;
}

static int iter_get_field(void* handle, const char* method, void** out_nd) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = nullptr;
  int rc = iter_simple_call(handle, method, &r);
  if (rc == 0) {
    // a NEW caller-owned handle per call (reference contract): release
    // with MXNDArrayFree like any other MXNDArray* handle
    auto* nh = new NDHandle();
    nh->obj = r;                 // steal the reference
    *out_nd = nh;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterGetData(void* handle, void** out_nd) {
  return iter_get_field(handle, "iter_getdata", out_nd);
}

int MXDataIterGetLabel(void* handle, void** out_nd) {
  return iter_get_field(handle, "iter_getlabel", out_nd);
}

int MXDataIterGetPadNum(void* handle, int* pad) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = nullptr;
  int rc = iter_simple_call(handle, "iter_getpad", &r);
  if (rc == 0) {
    *pad = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterFree(void* handle) {
  auto* h = static_cast<IterHandle*>(handle);
  if (!h) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Misc runtime slice (reference c_api.cc): MXGetVersion / MXRandomSeed /
// NDArray views (At / Slice / Reshape — new handles over the SAME
// write-through view machinery the Python frontend uses) and the
// .params-format MXNDArraySave / MXNDArrayLoad.
// ---------------------------------------------------------------------------

extern "C" {

int MXGetVersion(int* out) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  if (nd_ensure_bootstrap()) {
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "version", nullptr);
    if (r) {
      *out = static_cast<int>(PyLong_AsLong(r));
      Py_DECREF(r);
      rc = 0;
    } else {
      nd_set_err_from_python();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

int MXRandomSeed(int seed) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  if (nd_ensure_bootstrap()) {
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "random_seed", "i",
                                      seed);
    if (r) {
      Py_DECREF(r);
      rc = 0;
    } else {
      nd_set_err_from_python();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

namespace {

int nd_view_call(PyObject* r, void** out) {
  if (!r) {
    nd_set_err_from_python();
    return -1;
  }
  auto* h = new NDHandle();
  h->obj = r;
  *out = h;
  return 0;
}

}  // namespace

int MXNDArrayAt(void* handle, uint32_t idx, void** out) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = nd_view_call(PyObject_CallMethod(
      g_ndcore_cls, "nd_at", "OI", h->obj, idx), out);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySlice(void* handle, uint32_t lo, uint32_t hi, void** out) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = nd_view_call(PyObject_CallMethod(
      g_ndcore_cls, "nd_slice", "OII", h->obj, lo, hi), out);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayReshape(void* handle, int ndim, const int* dims, void** out) {
  auto* h = static_cast<NDHandle*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLong(dims[i]));
  int rc = nd_view_call(PyObject_CallMethod(
      g_ndcore_cls, "nd_reshape", "OO", h->obj, shp), out);
  Py_DECREF(shp);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySave(const char* fname, uint32_t num_args, void** args,
                  const char** keys) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* arrs = handle_list(args, num_args);
    if (!arrs) {
      nd_set_err("null NDArray handle in MXNDArraySave");
      break;
    }
    PyObject* klist;
    if (keys) {
      klist = PyList_New(num_args);
      for (uint32_t i = 0; i < num_args; ++i)
        PyList_SET_ITEM(klist, i, PyUnicode_FromString(keys[i]));
    } else {
      klist = Py_None;
      Py_INCREF(klist);
    }
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "nd_save", "sOO",
                                      fname, arrs, klist);
    Py_DECREF(arrs);
    Py_DECREF(klist);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    Py_DECREF(r);
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

namespace {
// MXNDArrayLoad output scratch (reference MXAPIThreadLocalEntry): valid
// until the thread's next Load
thread_local std::vector<void*> g_load_handles;
thread_local std::vector<std::string> g_load_names;
thread_local std::vector<const char*> g_load_name_ptrs;
}  // namespace

int MXNDArrayLoad(const char* fname, uint32_t* out_size, void*** out_arr,
                  uint32_t* out_name_size, const char*** out_names) {
  nd_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!nd_ensure_bootstrap()) break;
    PyObject* r = PyObject_CallMethod(g_ndcore_cls, "nd_load", "s",
                                      fname);
    if (!r) {
      nd_set_err_from_python();
      break;
    }
    PyObject* ks = PyTuple_GET_ITEM(r, 0);
    PyObject* vs = PyTuple_GET_ITEM(r, 1);
    g_load_handles.clear();
    g_load_names.clear();
    g_load_name_ptrs.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(vs); ++i) {
      auto* h = new NDHandle();
      h->obj = PyList_GET_ITEM(vs, i);
      Py_INCREF(h->obj);
      g_load_handles.push_back(h);
    }
    for (Py_ssize_t i = 0; i < PyList_Size(ks); ++i) {
      const char* u = PyUnicode_AsUTF8(PyList_GET_ITEM(ks, i));
      g_load_names.emplace_back(u ? u : "");
      if (!u) PyErr_Clear();
    }
    for (auto& s : g_load_names) g_load_name_ptrs.push_back(s.c_str());
    Py_DECREF(r);
    *out_size = static_cast<uint32_t>(g_load_handles.size());
    *out_arr = g_load_handles.data();
    *out_name_size = static_cast<uint32_t>(g_load_name_ptrs.size());
    *out_names = g_load_name_ptrs.data();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
