// Native input-pipeline core: RecordIO scan + JPEG decode + augment + batch.
//
// Reference parity: src/io/iter_image_recordio_2.cc + image_aug_default.cc
// + dmlc recordio framing (SURVEY.md §2.4).  The reference keeps JPEG
// decode and augmentation in threaded C++ so the training loop never
// blocks on image IO; this is the same design for the TPU build: an
// mmap'd .rec file, a persistent worker pool decoding a batch's samples
// in parallel with libjpeg, and augment (resize-shorter / random-or-center
// crop / mirror / mean-std normalize) fused into the float32 NCHW fill of
// the caller's batch buffer.  Exposed as a flat C ABI (the L9 discipline:
// opaque handle + plain C types) consumed by ctypes from io.py — no
// Python dependency in this translation unit.
//
// Record framing (must match recordio.py byte-for-byte):
//   [u32 magic=0xced7230a][u32 len(29bit)] payload pad-to-4
// Payload: IRHeader {u32 flag, f32 label, u64 id, u64 id2}
//   then flag>0 ? flag*f32 labels : (scalar label in header)
//   then image bytes: JPEG/PNG stream, or "RAWN" + u8 ndim + ndim*u32 shape
//   + raw uint8 pixels (recordio.py pack_img fallback).

#include <cstddef>
#include <cstdio>
#include <jpeglib.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <numeric>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Header {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

// ---------------------------------------------------------------------------
// libjpeg with error-longjmp (default handler exit()s the process)
// ---------------------------------------------------------------------------
struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode a JPEG stream to RGB u8 (h, w, 3).  Returns false on corrupt data.
// ``min_side_hint`` > 0 engages libjpeg's DCT-domain scaled decode: the
// largest 1/2^k scale whose output still keeps a 2x oversampling margin
// over the hint (the downstream bilinear resize needs headroom to stay
// visually equivalent to a full-resolution decode).  Decoding 1/2-scale
// reads ~1/4 of the DCT work — the big per-image cost on the host.
bool DecodeJpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                int* oh, int* ow, int min_side_hint = 0) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;   // libjpeg upsamples grayscale for us
  if (min_side_hint > 0) {
    const int min_side = std::min(static_cast<int>(cinfo.image_height),
                                  static_cast<int>(cinfo.image_width));
    int denom = 1;
    while (denom < 8 && min_side / (denom * 2) >= min_side_hint * 2)
      denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = static_cast<unsigned>(denom);
    // the fast-path decode also takes the fast IDCT: ~1-2 LSB pixel
    // difference, meaningful decode-time cut; the exact path (hint==0,
    // the parity-tested configuration) keeps ISLOW
    cinfo.dct_method = JDCT_IFAST;
  }
  jpeg_start_decompress(&cinfo);
  const int h = cinfo.output_height, w = cinfo.output_width;
  out->resize(static_cast<size_t>(h) * w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out->data() +
        static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *oh = h;
  *ow = w;
  return true;
}

// Bilinear resize RGB u8 (ih,iw,3) -> (oh,ow,3), align-corners=false
// (pixel-center sampling, the convention PIL/OpenCV use).
void ResizeBilinear(const uint8_t* src, int ih, int iw,
                    std::vector<uint8_t>* dst, int oh, int ow) {
  dst->resize(static_cast<size_t>(oh) * ow * 3);
  const float sy = static_cast<float>(ih) / oh;
  const float sx = static_cast<float>(iw) / ow;
  for (int y = 0; y < oh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = static_cast<int>(std::floor(fy));
    float wy = fy - y0;
    int y1 = y0 + 1;
    y0 = y0 < 0 ? 0 : (y0 >= ih ? ih - 1 : y0);
    y1 = y1 < 0 ? 0 : (y1 >= ih ? ih - 1 : y1);
    for (int x = 0; x < ow; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = static_cast<int>(std::floor(fx));
      float wx = fx - x0;
      int x1 = x0 + 1;
      x0 = x0 < 0 ? 0 : (x0 >= iw ? iw - 1 : x0);
      x1 = x1 < 0 ? 0 : (x1 >= iw ? iw - 1 : x1);
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(static_cast<size_t>(y0) * iw + x0) * 3 + c];
        float v01 = src[(static_cast<size_t>(y0) * iw + x1) * 3 + c];
        float v10 = src[(static_cast<size_t>(y1) * iw + x0) * 3 + c];
        float v11 = src[(static_cast<size_t>(y1) * iw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[(static_cast<size_t>(y) * ow + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

struct Iter {
  // config
  int batch, c, h, w, resize, label_width, nthreads;
  int decode_hint = 0;   // >0: DCT-scaled decode floor (min output side)
  bool rand_crop, rand_mirror, shuffle, round_batch;
  uint64_t seed;
  float mean[3], stdv[3];

  // file
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t file_len = 0;
  std::vector<size_t> offsets;

  // epoch state
  std::vector<uint32_t> order;
  size_t cursor = 0;       // batch index within epoch
  size_t n_batches = 0;
  uint64_t epoch = 0;

  // worker pool
  std::vector<std::thread> pool;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  bool stopping = false;
  int job_gen = 0;
  std::atomic<int> next_sample{0};
  int n_samples_job = 0;
  std::atomic<int> done_count{0};
  // per-job views
  const uint32_t* sel = nullptr;
  float* out_data = nullptr;
  float* out_label = nullptr;
  std::atomic<bool> job_failed{false};

  std::string last_error;

  ~Iter() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_work.notify_all();
    for (auto& t : pool) t.join();
    if (base) munmap(const_cast<uint8_t*>(base), file_len);
    if (fd >= 0) close(fd);
  }

  bool DecodeOne(int i, uint64_t sample_seed);
  void WorkerLoop();
  int Next(float* data, float* label, std::vector<uint32_t>* sel_buf);
  void Reset();
};

void Iter::WorkerLoop() {
  int seen_gen = 0;
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_work.wait(lk, [&] { return stopping || job_gen != seen_gen; });
      if (stopping) return;
      seen_gen = job_gen;
    }
    for (;;) {
      int i = next_sample.fetch_add(1);
      if (i >= n_samples_job) break;
      uint64_t ss = seed * 0x9e3779b97f4a7c15ULL + epoch * 0x100000001b3ULL +
                    static_cast<uint64_t>(sel[i]) * 1099511628211ULL + i;
      if (!DecodeOne(i, ss)) job_failed.store(true);
      done_count.fetch_add(1);
    }
    cv_done.notify_one();
  }
}

bool Iter::DecodeOne(int i, uint64_t sample_seed) {
  const size_t off = offsets[sel[i]];
  if (off + 8 > file_len) return false;
  uint32_t magic, lrec;
  std::memcpy(&magic, base + off, 4);
  std::memcpy(&lrec, base + off + 4, 4);
  if (magic != kMagic) return false;
  const size_t len = lrec & ((1u << 29) - 1);
  if (off + 8 + len > file_len) return false;
  const uint8_t* payload = base + off + 8;

  Header hdr;
  if (len < sizeof(Header)) return false;
  std::memcpy(&hdr, payload, sizeof(Header));
  const uint8_t* img = payload + sizeof(Header);
  size_t img_len = len - sizeof(Header);

  // labels
  float* lab = out_label + static_cast<size_t>(i) * label_width;
  for (int k = 0; k < label_width; ++k) lab[k] = 0.f;
  if (hdr.flag > 0) {
    const size_t nlab = hdr.flag;
    if (img_len < nlab * 4) return false;
    for (int k = 0; k < label_width && k < static_cast<int>(nlab); ++k)
      std::memcpy(&lab[k], img + 4 * k, 4);
    img += nlab * 4;
    img_len -= nlab * 4;
  } else {
    lab[0] = hdr.label;
  }

  // pixels
  std::vector<uint8_t> rgb;
  int ih = 0, iw = 0;
  if (img_len >= 5 && std::memcmp(img, "RAWN", 4) == 0) {
    int ndim = img[4];
    if (ndim < 2 || ndim > 3) return false;
    uint32_t shp[3] = {0, 0, 1};
    if (img_len < 5 + 4u * ndim) return false;
    std::memcpy(shp, img + 5, 4 * ndim);
    ih = shp[0];
    iw = shp[1];
    const int ch = ndim == 3 ? shp[2] : 1;
    const uint8_t* px = img + 5 + 4 * ndim;
    if (img_len < 5 + 4u * ndim + static_cast<size_t>(ih) * iw * ch)
      return false;
    rgb.resize(static_cast<size_t>(ih) * iw * 3);
    for (size_t p = 0; p < static_cast<size_t>(ih) * iw; ++p)
      for (int cc = 0; cc < 3; ++cc)
        rgb[p * 3 + cc] = px[p * ch + (cc < ch ? cc : ch - 1)];
  } else {
    if (!DecodeJpeg(img, img_len, &rgb, &ih, &iw, decode_hint))
      return false;
  }

  // resize shorter side
  std::vector<uint8_t> tmp;
  auto resize_shorter = [&](int size) {
    int nh, nw;
    if (ih < iw) {
      nh = size;
      nw = std::max(1, static_cast<int>(std::lround(
          static_cast<double>(iw) * size / ih)));
    } else {
      nw = size;
      nh = std::max(1, static_cast<int>(std::lround(
          static_cast<double>(ih) * size / iw)));
    }
    ResizeBilinear(rgb.data(), ih, iw, &tmp, nh, nw);
    rgb.swap(tmp);
    ih = nh;
    iw = nw;
  };
  if (resize > 0) resize_shorter(resize);
  if (ih < h || iw < w) resize_shorter(std::max(h, w));

  // crop
  std::mt19937_64 rng(sample_seed);
  int top, left;
  if (rand_crop) {
    top = static_cast<int>(rng() % static_cast<uint64_t>(ih - h + 1));
    left = static_cast<int>(rng() % static_cast<uint64_t>(iw - w + 1));
  } else {
    top = (ih - h) / 2;
    left = (iw - w) / 2;
  }
  const bool mirror = rand_mirror && (rng() & 1);

  // fused crop+mirror+normalize into float32 CHW
  float* dst = out_data + static_cast<size_t>(i) * c * h * w;
  for (int cc = 0; cc < c; ++cc) {
    const float m = mean[cc], s = stdv[cc];
    float* plane = dst + static_cast<size_t>(cc) * h * w;
    for (int y = 0; y < h; ++y) {
      const uint8_t* row =
          rgb.data() + (static_cast<size_t>(top + y) * iw + left) * 3 + cc;
      float* drow = plane + static_cast<size_t>(y) * w;
      if (mirror) {
        for (int x = 0; x < w; ++x)
          drow[x] = (static_cast<float>(row[(w - 1 - x) * 3]) - m) / s;
      } else {
        for (int x = 0; x < w; ++x)
          drow[x] = (static_cast<float>(row[x * 3]) - m) / s;
      }
    }
  }
  return true;
}

int Iter::Next(float* data, float* label, std::vector<uint32_t>* sel_buf) {
  if (cursor >= n_batches) return -1;
  const size_t n = order.size();
  const size_t lo = cursor * batch;
  size_t hi = lo + batch;
  int pad = 0;
  sel_buf->clear();
  if (hi > n) {
    pad = static_cast<int>(hi - n);
    hi = n;
  }
  for (size_t k = lo; k < hi; ++k) sel_buf->push_back(order[k]);
  for (int k = 0; k < pad; ++k)
    sel_buf->push_back(order[k % n]);   // round_batch: wrap to the front

  {
    std::lock_guard<std::mutex> lk(mu);
    sel = sel_buf->data();
    out_data = data;
    out_label = label;
    n_samples_job = batch;
    next_sample.store(0);
    done_count.store(0);
    job_failed.store(false);
    ++job_gen;
  }
  cv_work.notify_all();
  {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return done_count.load() >= n_samples_job; });
  }
  ++cursor;
  if (job_failed.load()) {
    last_error = "corrupt record or undecodable image in batch";
    return -2;
  }
  return pad;
}

void Iter::Reset() {
  ++epoch;
  cursor = 0;
  if (shuffle) {
    std::mt19937_64 rng(seed + epoch * 0x9e3779b97f4a7c15ULL);
    for (size_t k = order.size(); k > 1; --k) {
      size_t j = rng() % k;
      std::swap(order[k - 1], order[j]);
    }
  }
}

}  // namespace

extern "C" {

void* MXTPUIOCreate(const char* rec_path, const char* idx_path,
                    int batch, int c, int h, int w, int resize,
                    int rand_crop, int rand_mirror, int shuffle,
                    int round_batch, uint64_t seed,
                    const float* mean, const float* stdv, int label_width,
                    int part_index, int num_parts, int nthreads,
                    int decode_hint, char* err, int err_len) {
  auto fail = [&](const std::string& msg) -> void* {
    std::snprintf(err, err_len, "%s", msg.c_str());
    return nullptr;
  };
  auto it = std::unique_ptr<Iter>(new Iter());
  it->batch = batch;
  it->c = c;
  it->h = h;
  it->w = w;
  it->resize = resize;
  it->label_width = label_width;
  it->rand_crop = rand_crop;
  it->rand_mirror = rand_mirror;
  it->shuffle = shuffle;
  it->round_batch = round_batch;
  it->seed = seed;
  it->decode_hint = decode_hint;
  for (int k = 0; k < 3; ++k) {
    it->mean[k] = mean ? mean[k] : 0.f;
    it->stdv[k] = stdv ? stdv[k] : 1.f;
  }
  if (c < 1 || c > 3) return fail("c must be 1..3");

  it->fd = open(rec_path, O_RDONLY);
  if (it->fd < 0) return fail(std::string("cannot open ") + rec_path);
  struct stat st;
  if (fstat(it->fd, &st) != 0 || st.st_size == 0)
    return fail("empty or unstatable rec file");
  it->file_len = st.st_size;
  void* m = mmap(nullptr, it->file_len, PROT_READ, MAP_PRIVATE, it->fd, 0);
  if (m == MAP_FAILED) return fail("mmap failed");
  it->base = static_cast<const uint8_t*>(m);

  // offsets: from the .idx sidecar when given, else a linear scan
  if (idx_path && idx_path[0]) {
    FILE* f = fopen(idx_path, "r");
    if (!f) return fail(std::string("cannot open ") + idx_path);
    char line[256];
    while (fgets(line, sizeof line, f)) {
      const char* tab = strchr(line, '\t');
      if (tab) it->offsets.push_back(strtoull(tab + 1, nullptr, 10));
    }
    fclose(f);
  } else {
    size_t pos = 0;
    while (pos + 8 <= it->file_len) {
      uint32_t magic, lrec;
      std::memcpy(&magic, it->base + pos, 4);
      std::memcpy(&lrec, it->base + pos + 4, 4);
      if (magic != kMagic) return fail("bad record magic during scan");
      size_t len = lrec & ((1u << 29) - 1);
      it->offsets.push_back(pos);
      pos += 8 + len + (4 - len % 4) % 4;
    }
  }
  if (it->offsets.empty()) return fail("no records in file");

  // distributed shard (reference: part_index/num_parts)
  const size_t nrec = it->offsets.size();
  const size_t shard = nrec / num_parts;
  const size_t lo = static_cast<size_t>(part_index) * shard;
  const size_t hi = part_index == num_parts - 1 ? nrec : lo + shard;
  it->offsets.assign(it->offsets.begin() + lo, it->offsets.begin() + hi);

  it->order.resize(it->offsets.size());
  std::iota(it->order.begin(), it->order.end(), 0);
  it->n_batches = it->order.size() / batch;
  if (it->round_batch && it->order.size() % batch) ++it->n_batches;
  it->epoch = static_cast<uint64_t>(-1);   // Reset() bumps to 0
  it->Reset();

  const int nt = nthreads > 0 ? nthreads : 4;
  it->nthreads = nt;
  for (int t = 0; t < nt; ++t)
    it->pool.emplace_back(&Iter::WorkerLoop, it.get());
  return it.release();
}

int64_t MXTPUIONumSamples(void* h) {
  return static_cast<Iter*>(h)->order.size();
}

int64_t MXTPUIONumBatches(void* h) {
  return static_cast<Iter*>(h)->n_batches;
}

// Fill one batch.  Returns pad count (>=0), -1 at epoch end, -2 on error.
int MXTPUIONext(void* h, float* data, float* label) {
  thread_local std::vector<uint32_t> sel_buf;
  return static_cast<Iter*>(h)->Next(data, label, &sel_buf);
}

const char* MXTPUIOLastError(void* h) {
  return static_cast<Iter*>(h)->last_error.c_str();
}

void MXTPUIOReset(void* h) { static_cast<Iter*>(h)->Reset(); }

void MXTPUIODestroy(void* h) { delete static_cast<Iter*>(h); }

}  // extern "C"
