"""Native (C++) runtime components, built on demand with the system
toolchain and loaded through ctypes — the L9 discipline of the reference
(flat C ABI, opaque handles; SURVEY.md §2.1): no Python dependency inside
the native code, no binding generator.

Components:
- io_core.cc — RecordIO + JPEG decode + augment batch pipeline
  (reference: src/io/iter_image_recordio_2.cc).
- predict_core.cc — the MXPred* C predict ABI for embedding
  (reference: src/c_api/c_predict_api.cc).

``load_io()`` / ``load_predict()`` return the ctypes library (building it
the first time) or raise MXNetError with the toolchain failure; callers
degrade gracefully to the pure-Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ..base import MXNetError

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB = None
_LOAD_ERR = None


def _build(src: str, so: str, extra: list) -> None:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", so, src] + extra
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        raise MXNetError(
            f"native build failed: {' '.join(cmd)}\n{r.stderr[-2000:]}")


def _stale(src: str, so: str) -> bool:
    return (not os.path.isfile(so)
            or os.path.getmtime(so) < os.path.getmtime(src))


def load_io():
    """Build (if needed) + load the io core; cached process-wide."""
    global _LIB, _LOAD_ERR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_ERR is not None:
            raise _LOAD_ERR
        src = os.path.join(_DIR, "io_core.cc")
        so = os.path.join(_DIR, "libmxtpu_io.so")
        try:
            if _stale(src, so):
                _build(src, so, ["-ljpeg", "-lpthread"])
            lib = ctypes.CDLL(so)
        except (MXNetError, OSError, subprocess.SubprocessError) as e:
            _LOAD_ERR = e if isinstance(e, MXNetError) else \
                MXNetError(f"cannot load native io core: {e}")
            raise _LOAD_ERR
        c_float_p = ctypes.POINTER(ctypes.c_float)
        lib.MXTPUIOCreate.restype = ctypes.c_void_p
        lib.MXTPUIOCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, c_float_p, c_float_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.MXTPUIONext.restype = ctypes.c_int
        lib.MXTPUIONext.argtypes = [ctypes.c_void_p, c_float_p, c_float_p]
        lib.MXTPUIONumSamples.restype = ctypes.c_int64
        lib.MXTPUIONumSamples.argtypes = [ctypes.c_void_p]
        lib.MXTPUIONumBatches.restype = ctypes.c_int64
        lib.MXTPUIONumBatches.argtypes = [ctypes.c_void_p]
        lib.MXTPUIOLastError.restype = ctypes.c_char_p
        lib.MXTPUIOLastError.argtypes = [ctypes.c_void_p]
        lib.MXTPUIOReset.argtypes = [ctypes.c_void_p]
        lib.MXTPUIODestroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def io_available() -> bool:
    try:
        load_io()
        return True
    except MXNetError:
        return False


_PRED = {"lib": None, "err": None}


def load_predict():
    """Build (if needed) + load the predict C ABI; cached process-wide."""
    import sysconfig
    with _LOCK:
        if _PRED["lib"] is not None:
            return _PRED["lib"]
        if _PRED["err"] is not None:
            raise _PRED["err"]
        src = os.path.join(_DIR, "predict_core.cc")
        so = os.path.join(_DIR, "libmxtpu_predict.so")
        try:
            if _stale(src, so):
                inc = sysconfig.get_paths()["include"]
                libdir = sysconfig.get_config_var("LIBDIR") or "/usr/lib"
                ver = sysconfig.get_config_var("LDVERSION") or \
                    sysconfig.get_config_var("VERSION")
                _build(src, so, [f"-I{inc}", f"-L{libdir}",
                                 f"-lpython{ver}", "-ldl"])
            lib = ctypes.CDLL(so, mode=ctypes.RTLD_GLOBAL)
        except (MXNetError, OSError, subprocess.SubprocessError) as e:
            _PRED["err"] = e if isinstance(e, MXNetError) else \
                MXNetError(f"cannot load predict core: {e}")
            raise _PRED["err"]
        u32 = ctypes.c_uint32
        lib.MXPredCreate.restype = ctypes.c_int
        lib.MXPredCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, u32, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u32), ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXPredSetInput.restype = ctypes.c_int
        lib.MXPredSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float), u32]
        lib.MXPredForward.restype = ctypes.c_int
        lib.MXPredForward.argtypes = [ctypes.c_void_p]
        lib.MXPredGetOutputShape.restype = ctypes.c_int
        lib.MXPredGetOutputShape.argtypes = [
            ctypes.c_void_p, u32, ctypes.POINTER(ctypes.POINTER(u32)),
            ctypes.POINTER(u32)]
        lib.MXPredGetOutput.restype = ctypes.c_int
        lib.MXPredGetOutput.argtypes = [
            ctypes.c_void_p, u32, ctypes.POINTER(ctypes.c_float), u32]
        lib.MXPredFree.restype = ctypes.c_int
        lib.MXPredFree.argtypes = [ctypes.c_void_p]
        lib.MXGetLastError.restype = ctypes.c_char_p
        _PRED["lib"] = lib
        return lib
