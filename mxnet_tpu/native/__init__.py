"""Native (C++) runtime components, built on demand with the system
toolchain and loaded through ctypes — the L9 discipline of the reference
(flat C ABI, opaque handles; SURVEY.md §2.1): no Python dependency inside
the native code, no binding generator.

Components:
- io_core.cc — RecordIO + JPEG decode + augment batch pipeline
  (reference: src/io/iter_image_recordio_2.cc).
- predict_core.cc — the MXPred* C predict ABI for embedding
  (reference: src/c_api/c_predict_api.cc).
- ndarray_core.cc — the MXNDArray*/MXImperativeInvoke imperative C ABI,
  the slice the reference's six language bindings are built on
  (reference: src/c_api/c_api.cc + c_api_ndarray.cc).
- symbol_core.cc — the MXSymbol* graph-construction C ABI
  (variable/atomic/compose/JSON/list/InferShape; reference:
  src/c_api/c_api_symbolic.cc).

``load_io()`` / ``load_predict()`` / ``load_ndarray()`` / ``load_symbol()``
return the ctypes library (building it the first time) or raise MXNetError
with the toolchain failure; callers degrade gracefully to the pure-Python
path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ..base import MXNetError

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB = None
_LOAD_ERR = None


def _build(src: str, so: str, extra: list) -> None:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", so, src] + extra
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        raise MXNetError(
            f"native build failed: {' '.join(cmd)}\n{r.stderr[-2000:]}")


def _stale(src: str, so: str) -> bool:
    return (not os.path.isfile(so)
            or os.path.getmtime(so) < os.path.getmtime(src))


def load_io():
    """Build (if needed) + load the io core; cached process-wide."""
    global _LIB, _LOAD_ERR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_ERR is not None:
            raise _LOAD_ERR
        src = os.path.join(_DIR, "io_core.cc")
        so = os.path.join(_DIR, "libmxtpu_io.so")
        try:
            if _stale(src, so):
                # portable flags only: the .so is cached by mtime next to
                # the source, so a host-tuned build (-march=native) could
                # be loaded on a different microarchitecture and SIGILL
                # (and measured no win here anyway - libjpeg-turbo's SIMD
                # dominates the runtime)
                _build(src, so, ["-ljpeg", "-lpthread"])
            lib = ctypes.CDLL(so)
        except (MXNetError, OSError, subprocess.SubprocessError) as e:
            _LOAD_ERR = e if isinstance(e, MXNetError) else \
                MXNetError(f"cannot load native io core: {e}")
            raise _LOAD_ERR
        c_float_p = ctypes.POINTER(ctypes.c_float)
        lib.MXTPUIOCreate.restype = ctypes.c_void_p
        lib.MXTPUIOCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, c_float_p, c_float_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.MXTPUIONext.restype = ctypes.c_int
        lib.MXTPUIONext.argtypes = [ctypes.c_void_p, c_float_p, c_float_p]
        lib.MXTPUIONumSamples.restype = ctypes.c_int64
        lib.MXTPUIONumSamples.argtypes = [ctypes.c_void_p]
        lib.MXTPUIONumBatches.restype = ctypes.c_int64
        lib.MXTPUIONumBatches.argtypes = [ctypes.c_void_p]
        lib.MXTPUIOLastError.restype = ctypes.c_char_p
        lib.MXTPUIOLastError.argtypes = [ctypes.c_void_p]
        lib.MXTPUIOReset.argtypes = [ctypes.c_void_p]
        lib.MXTPUIODestroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def io_available() -> bool:
    try:
        load_io()
        return True
    except MXNetError:
        return False


def _load_embedded(cache: dict, src_name: str, so_name: str,
                   what: str):
    """Shared build+load+cache protocol for the embedded-CPython ABIs
    (predict_core / ndarray_core): one place owns the link flags and the
    error-caching discipline.  Caller must hold _LOCK."""
    import sysconfig
    if cache["lib"] is not None:
        return cache["lib"]
    if cache["err"] is not None:
        raise cache["err"]
    src = os.path.join(_DIR, src_name)
    so = os.path.join(_DIR, so_name)
    try:
        if _stale(src, so):
            inc = sysconfig.get_paths()["include"]
            libdir = sysconfig.get_config_var("LIBDIR") or "/usr/lib"
            ver = sysconfig.get_config_var("LDVERSION") or \
                sysconfig.get_config_var("VERSION")
            _build(src, so, [f"-I{inc}", f"-L{libdir}",
                             f"-lpython{ver}", "-ldl"])
        return ctypes.CDLL(so, mode=ctypes.RTLD_GLOBAL)
    except (MXNetError, OSError, subprocess.SubprocessError) as e:
        cache["err"] = e if isinstance(e, MXNetError) else \
            MXNetError(f"cannot load {what}: {e}")
        raise cache["err"]


_PRED = {"lib": None, "err": None}


def load_predict():
    """Build (if needed) + load the predict C ABI; cached process-wide."""
    with _LOCK:
        if _PRED["lib"] is not None:
            return _PRED["lib"]
        lib = _load_embedded(_PRED, "predict_core.cc",
                             "libmxtpu_predict.so", "predict core")
        u32 = ctypes.c_uint32
        lib.MXPredCreate.restype = ctypes.c_int
        lib.MXPredCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, u32, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u32), ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXPredSetInput.restype = ctypes.c_int
        lib.MXPredSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float), u32]
        lib.MXPredForward.restype = ctypes.c_int
        lib.MXPredForward.argtypes = [ctypes.c_void_p]
        lib.MXPredGetOutputShape.restype = ctypes.c_int
        lib.MXPredGetOutputShape.argtypes = [
            ctypes.c_void_p, u32, ctypes.POINTER(ctypes.POINTER(u32)),
            ctypes.POINTER(u32)]
        lib.MXPredGetOutput.restype = ctypes.c_int
        lib.MXPredGetOutput.argtypes = [
            ctypes.c_void_p, u32, ctypes.POINTER(ctypes.c_float), u32]
        lib.MXPredFree.restype = ctypes.c_int
        lib.MXPredFree.argtypes = [ctypes.c_void_p]
        lib.MXGetLastError.restype = ctypes.c_char_p
        _PRED["lib"] = lib
        return lib


_SYMC = {"lib": None, "err": None}


def load_symbol():
    """Build (if needed) + load the symbol C ABI; cached process-wide."""
    with _LOCK:
        if _SYMC["lib"] is not None:
            return _SYMC["lib"]
        lib = _load_embedded(_SYMC, "symbol_core.cc",
                             "libmxtpu_symbol.so", "symbol core")
        u32 = ctypes.c_uint32
        vp = ctypes.c_void_p
        pu32 = ctypes.POINTER(u32)
        ppu32 = ctypes.POINTER(pu32)
        pppu32 = ctypes.POINTER(ppu32)
        strs = ctypes.POINTER(ctypes.c_char_p)
        lib.MXSymbolCreateVariable.restype = ctypes.c_int
        lib.MXSymbolCreateVariable.argtypes = [ctypes.c_char_p,
                                               ctypes.POINTER(vp)]
        lib.MXSymbolCreateFromJSON.restype = ctypes.c_int
        lib.MXSymbolCreateFromJSON.argtypes = [ctypes.c_char_p,
                                               ctypes.POINTER(vp)]
        lib.MXSymbolSaveToJSON.restype = ctypes.c_int
        lib.MXSymbolSaveToJSON.argtypes = [
            vp, ctypes.POINTER(ctypes.c_char_p)]
        lib.MXSymbolCreateAtomicSymbol.restype = ctypes.c_int
        lib.MXSymbolCreateAtomicSymbol.argtypes = [
            ctypes.c_char_p, u32, strs, strs, ctypes.POINTER(vp)]
        lib.MXSymbolCompose.restype = ctypes.c_int
        lib.MXSymbolCompose.argtypes = [vp, ctypes.c_char_p, u32, strs,
                                        ctypes.POINTER(vp)]
        for fname in ("MXSymbolListArguments", "MXSymbolListOutputs",
                      "MXSymbolListAuxiliaryStates"):
            f = getattr(lib, fname)
            f.restype = ctypes.c_int
            f.argtypes = [vp, pu32, ctypes.POINTER(strs)]
        lib.MXSymbolInferShape.restype = ctypes.c_int
        lib.MXSymbolInferShape.argtypes = [
            vp, u32, strs, pu32, pu32,
            pu32, ppu32, pppu32,
            pu32, ppu32, pppu32,
            pu32, ppu32, pppu32,
            ctypes.POINTER(ctypes.c_int)]
        lib.MXSymbolFree.restype = ctypes.c_int
        lib.MXSymbolFree.argtypes = [vp]
        lib.MXSymGetLastError.restype = ctypes.c_char_p
        _register_symbol_introspection(lib)
        _SYMC["lib"] = lib
        return lib


_NDC = {"lib": None, "err": None}


def load_ndarray():
    """Build (if needed) + load the imperative C ABI; cached process-wide."""
    with _LOCK:
        if _NDC["lib"] is not None:
            return _NDC["lib"]
        lib = _load_embedded(_NDC, "ndarray_core.cc",
                             "libmxtpu_ndarray.so", "ndarray core")
        u32 = ctypes.c_uint32
        vp = ctypes.c_void_p
        lib.MXNDArrayCreate.restype = ctypes.c_int
        lib.MXNDArrayCreate.argtypes = [
            ctypes.POINTER(u32), u32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(vp)]
        lib.MXNDArrayCreateEx.restype = ctypes.c_int
        lib.MXNDArrayCreateEx.argtypes = [
            ctypes.POINTER(u32), u32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(vp)]
        lib.MXNDArrayFree.restype = ctypes.c_int
        lib.MXNDArrayFree.argtypes = [vp]
        lib.MXNDArrayGetShape.restype = ctypes.c_int
        lib.MXNDArrayGetShape.argtypes = [
            vp, ctypes.POINTER(u32), ctypes.POINTER(ctypes.POINTER(u32))]
        lib.MXNDArrayGetDType.restype = ctypes.c_int
        lib.MXNDArrayGetDType.argtypes = [vp, ctypes.POINTER(ctypes.c_int)]
        lib.MXNDArraySyncCopyFromCPU.restype = ctypes.c_int
        lib.MXNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
        lib.MXNDArraySyncCopyToCPU.restype = ctypes.c_int
        lib.MXNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
        lib.MXNDArrayWaitAll.restype = ctypes.c_int
        lib.MXListAllOpNames.restype = ctypes.c_int
        lib.MXListAllOpNames.argtypes = [
            ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
        lib.NNGetOpHandle.restype = ctypes.c_int
        lib.NNGetOpHandle.argtypes = [ctypes.c_char_p, ctypes.POINTER(vp)]
        lib.MXImperativeInvoke.restype = ctypes.c_int
        lib.MXImperativeInvoke.argtypes = [
            vp, ctypes.c_int, ctypes.POINTER(vp), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.POINTER(vp)), ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p)]
        lib.MXNDGetLastError.restype = ctypes.c_char_p
        # kvstore slice (same .so — handles are shared with MXNDArray*)
        pint = ctypes.POINTER(ctypes.c_int)
        lib.MXKVStoreCreate.restype = ctypes.c_int
        lib.MXKVStoreCreate.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(vp)]
        lib.MXKVStoreFree.restype = ctypes.c_int
        lib.MXKVStoreFree.argtypes = [vp]
        for fname in ("MXKVStoreInit", "MXKVStorePush", "MXKVStorePull"):
            f = getattr(lib, fname)
            f.restype = ctypes.c_int
            f.argtypes = [vp, u32, pint, ctypes.POINTER(vp)] + \
                ([] if fname == "MXKVStoreInit" else [ctypes.c_int])
        lib.MXKVStoreGetType.restype = ctypes.c_int
        lib.MXKVStoreGetType.argtypes = [
            vp, ctypes.POINTER(ctypes.c_char_p)]
        lib.MXKVStoreGetRank.restype = ctypes.c_int
        lib.MXKVStoreGetRank.argtypes = [vp, pint]
        lib.MXKVStoreGetGroupSize.restype = ctypes.c_int
        lib.MXKVStoreGetGroupSize.argtypes = [vp, pint]
        lib.MXKVStoreBarrier.restype = ctypes.c_int
        lib.MXKVStoreBarrier.argtypes = [vp]
        # training slice: autograd + CachedOp (same .so — handles shared)
        lib.MXAutogradSetIsRecording.restype = ctypes.c_int
        lib.MXAutogradSetIsRecording.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.MXAutogradSetIsTraining.restype = ctypes.c_int
        lib.MXAutogradSetIsTraining.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.MXAutogradMarkVariables.restype = ctypes.c_int
        lib.MXAutogradMarkVariables.argtypes = [
            u32, ctypes.POINTER(vp), ctypes.POINTER(u32),
            ctypes.POINTER(vp)]
        lib.MXAutogradBackward.restype = ctypes.c_int
        lib.MXAutogradBackward.argtypes = [
            u32, ctypes.POINTER(vp), ctypes.POINTER(vp), ctypes.c_int]
        lib.MXCreateCachedOp.restype = ctypes.c_int
        lib.MXCreateCachedOp.argtypes = [vp, ctypes.POINTER(vp)]
        lib.MXCreateCachedOpFromJSON.restype = ctypes.c_int
        lib.MXCreateCachedOpFromJSON.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(vp)]
        lib.MXFreeCachedOp.restype = ctypes.c_int
        lib.MXFreeCachedOp.argtypes = [vp]
        lib.MXInvokeCachedOp.restype = ctypes.c_int
        lib.MXInvokeCachedOp.argtypes = [
            vp, ctypes.c_int, ctypes.POINTER(vp),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.POINTER(vp))]
        # data-iterator slice (same .so — GetData/GetLabel mint shared
        # NDArray handles owned by the iterator)
        lib.MXListDataIters.restype = ctypes.c_int
        lib.MXListDataIters.argtypes = [
            ctypes.POINTER(u32), ctypes.POINTER(ctypes.POINTER(vp))]
        lib.MXDataIterCreateIter.restype = ctypes.c_int
        lib.MXDataIterCreateIter.argtypes = [
            vp, u32, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(vp)]
        for fname in ("MXDataIterBeforeFirst", "MXDataIterFree"):
            f = getattr(lib, fname)
            f.restype = ctypes.c_int
            f.argtypes = [vp]
        lib.MXDataIterNext.restype = ctypes.c_int
        lib.MXDataIterNext.argtypes = [vp, ctypes.POINTER(ctypes.c_int)]
        for fname in ("MXDataIterGetData", "MXDataIterGetLabel"):
            f = getattr(lib, fname)
            f.restype = ctypes.c_int
            f.argtypes = [vp, ctypes.POINTER(vp)]
        lib.MXDataIterGetPadNum.restype = ctypes.c_int
        lib.MXDataIterGetPadNum.argtypes = [vp,
                                            ctypes.POINTER(ctypes.c_int)]
        # misc runtime slice
        lib.MXGetVersion.restype = ctypes.c_int
        lib.MXGetVersion.argtypes = [ctypes.POINTER(ctypes.c_int)]
        lib.MXRandomSeed.restype = ctypes.c_int
        lib.MXRandomSeed.argtypes = [ctypes.c_int]
        lib.MXNDArrayAt.restype = ctypes.c_int
        lib.MXNDArrayAt.argtypes = [vp, u32, ctypes.POINTER(vp)]
        lib.MXNDArraySlice.restype = ctypes.c_int
        lib.MXNDArraySlice.argtypes = [vp, u32, u32, ctypes.POINTER(vp)]
        lib.MXNDArrayReshape.restype = ctypes.c_int
        lib.MXNDArrayReshape.argtypes = [
            vp, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(vp)]
        lib.MXNDArraySave.restype = ctypes.c_int
        lib.MXNDArraySave.argtypes = [
            ctypes.c_char_p, u32, ctypes.POINTER(vp),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.MXNDArrayLoad.restype = ctypes.c_int
        lib.MXNDArrayLoad.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.POINTER(vp)), ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
        _NDC["lib"] = lib
        return lib


def _register_symbol_introspection(lib):
    import ctypes as ct
    u32, vp = ct.c_uint32, ct.c_void_p
    strs = ct.POINTER(ct.c_char_p)
    lib.MXSymbolListAtomicSymbolCreators.restype = ct.c_int
    lib.MXSymbolListAtomicSymbolCreators.argtypes = [
        ct.POINTER(u32), ct.POINTER(ct.POINTER(vp))]
    lib.MXSymbolGetAtomicSymbolName.restype = ct.c_int
    lib.MXSymbolGetAtomicSymbolName.argtypes = [vp,
                                                ct.POINTER(ct.c_char_p)]
    lib.MXSymbolGetAtomicSymbolInfo.restype = ct.c_int
    lib.MXSymbolGetAtomicSymbolInfo.argtypes = [
        vp, ct.POINTER(ct.c_char_p), ct.POINTER(ct.c_char_p),
        ct.POINTER(u32), ct.POINTER(strs), ct.POINTER(strs),
        ct.POINTER(strs), ct.POINTER(ct.c_char_p)]
