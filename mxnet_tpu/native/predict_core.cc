// C predict ABI: the embedding seam for serving from C/C++ hosts.
//
// Reference parity: src/c_api/c_predict_api.cc + include/mxnet/
// c_predict_api.h (SURVEY.md §2.1 L9, §3.5) — same function names and
// call contract: MXPredCreate(json, params, dev, shapes) →
// MXPredSetInput → MXPredForward → MXPredGetOutputShape/MXPredGetOutput,
// errors via MXGetLastError.
//
// TPU-native design: the reference backs this ABI with its own C++
// executor; here the executor IS the XLA-compiled graph, reached by
// embedding CPython (libpython is the runtime the XLA client lives in)
// — the ABI boundary stays pure C (opaque handles, POD types), so a
// C host needs no Python headers, only this .so.  When loaded INSIDE a
// Python process (ctypes), the embedded interpreter is the host's own.

#include <Python.h>
#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_err(const std::string& m) { g_last_error = m; }

// format + clear the live Python exception into g_last_error
void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_err(msg);
}

struct Predictor {
  PyObject* obj = nullptr;                  // _Predictor instance
  std::vector<std::vector<uint32_t>> out_shape_cache;
};

const char kBootstrap[] = R"PY(
import io as _io
import sys as _sys
if _MXTPU_ROOT not in _sys.path:
    _sys.path.insert(0, _MXTPU_ROOT)
import numpy as _np
import mxnet_tpu as _mx
from mxnet_tpu.ndarray import utils as _mxu


class _Predictor:
    def __init__(self, sym_json, param_bytes, dev_type, dev_id, shapes):
        from mxnet_tpu.symbol import load_json
        sym = load_json(sym_json)
        params = _mxu.load_buffer(param_bytes) if param_bytes else {}
        arg, aux = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                arg[k[4:]] = v
            elif k.startswith("aux:"):
                aux[k[4:]] = v
            else:
                arg[k] = v
        ctx = _mx.cpu(dev_id) if dev_type == 1 else _mx.tpu(dev_id)
        self._shapes = dict(shapes)
        self._exe = sym.simple_bind(ctx=ctx, grad_req="null",
                                    **self._shapes)
        for k, v in {**arg, **aux}.items():
            if k in self._exe.arg_dict:
                v.copyto(self._exe.arg_dict[k])
            elif k in self._exe.aux_dict:
                v.copyto(self._exe.aux_dict[k])
        self._outs = None

    def set_input(self, key, raw):
        if key not in self._shapes:
            raise KeyError(f"unknown input {key!r}")
        arr = _np.frombuffer(raw, _np.float32).reshape(self._shapes[key])
        self._exe.arg_dict[key]._set_data(
            _mx.nd.array(arr, ctx=self._exe.arg_dict[key].context)._read())

    def forward(self):
        self._outs = self._exe.forward(is_train=False)

    def num_outputs(self):
        return len(self._outs) if self._outs is not None else 0

    def output_shape(self, i):
        return tuple(self._outs[i].shape)

    def output_bytes(self, i):
        return self._outs[i].asnumpy().astype(_np.float32).tobytes()
)PY";

PyObject* g_predictor_cls = nullptr;

bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // embedding host: release the GIL we now hold so PyGILState_Ensure
    // works uniformly below
    PyEval_SaveThread();
  }
  return true;
}

bool ensure_bootstrap() {
  if (g_predictor_cls) return true;
  // locate repo root: this .so lives at <root>/mxnet_tpu/native/
  Dl_info info;
  std::string root = ".";
  if (dladdr(reinterpret_cast<void*>(&ensure_bootstrap), &info) &&
      info.dli_fname) {
    std::string p = info.dli_fname;
    for (int up = 0; up < 3; ++up) {
      auto pos = p.find_last_of('/');
      if (pos == std::string::npos) break;
      p = p.substr(0, pos);
    }
    if (!p.empty()) root = p;
  }
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* rootstr = PyUnicode_FromString(root.c_str());
  PyDict_SetItemString(globals, "_MXTPU_ROOT", rootstr);
  Py_DECREF(rootstr);
  PyObject* res = PyRun_String(kBootstrap, Py_file_input, globals, globals);
  if (!res) {
    set_err_from_python();
    Py_DECREF(globals);
    return false;
  }
  Py_DECREF(res);
  g_predictor_cls = PyDict_GetItemString(globals, "_Predictor");
  Py_XINCREF(g_predictor_cls);
  Py_DECREF(globals);
  if (!g_predictor_cls) {
    set_err("bootstrap did not define _Predictor");
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

// Reference signature (c_predict_api.h): shapes arrive CSR-style —
// input_shape_indptr[i]..indptr[i+1] indexes into input_shape_data.
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, void** out) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject *shapes = nullptr, *params = nullptr, *obj = nullptr;
  do {
    if (!ensure_bootstrap()) break;
    shapes = PyDict_New();
    for (uint32_t i = 0; i < num_input_nodes; ++i) {
      uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
      PyObject* tup = PyTuple_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j)
        PyTuple_SET_ITEM(tup, j - lo,
                         PyLong_FromUnsignedLong(input_shape_data[j]));
      PyDict_SetItemString(shapes, input_keys[i], tup);
      Py_DECREF(tup);
    }
    params = PyBytes_FromStringAndSize(
        static_cast<const char*>(param_bytes), param_size);
    obj = PyObject_CallFunction(g_predictor_cls, "sOiiO",
                                symbol_json_str, params, dev_type,
                                dev_id, shapes);
    if (!obj) {
      set_err_from_python();
      break;
    }
    auto* p = new Predictor();
    p->obj = obj;
    obj = nullptr;
    *out = p;
    rc = 0;
  } while (false);
  Py_XDECREF(shapes);
  Py_XDECREF(params);
  Py_XDECREF(obj);
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(void* handle, const char* key, const float* data,
                   uint32_t size) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* raw = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* r = PyObject_CallMethod(p->obj, "set_input", "sO", key, raw);
  Py_DECREF(raw);
  int rc = 0;
  if (!r) {
    set_err_from_python();
    rc = -1;
  }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(p->obj, "forward", nullptr);
  int rc = 0;
  if (!r) {
    set_err_from_python();
    rc = -1;
  }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(void* handle, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "output_shape", "I", index);
  if (r) {
    Py_ssize_t n = PyTuple_Size(r);
    if (p->out_shape_cache.size() <= index)
      p->out_shape_cache.resize(index + 1);
    auto& v = p->out_shape_cache[index];
    v.resize(n);
    for (Py_ssize_t i = 0; i < n; ++i)
      v[i] = static_cast<uint32_t>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, i)));
    *shape_data = v.data();
    *shape_ndim = static_cast<uint32_t>(n);
    Py_DECREF(r);
    rc = 0;
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(void* handle, uint32_t index, float* data,
                    uint32_t size) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "output_bytes", "I", index);
  if (r) {
    char* buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &buf, &n) == 0 &&
        n == static_cast<Py_ssize_t>(size * sizeof(float))) {
      std::memcpy(data, buf, n);
      rc = 0;
    } else {
      set_err("output size mismatch");
    }
    Py_DECREF(r);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"
