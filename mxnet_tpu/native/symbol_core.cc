// C Symbol ABI: graph construction / serialization / inference from C.
//
// Reference parity: src/c_api/c_api_symbolic.cc (SURVEY.md §2.1 L9) — the
// slice the reference language bindings use to BUILD graphs (the Scala/R/
// Julia model constructors are all Compose loops over this surface):
//   MXSymbolCreateVariable / MXSymbolCreateAtomicSymbol / MXSymbolCompose /
//   MXSymbolCreateFromJSON / MXSymbolSaveToJSON / MXSymbolListArguments /
//   MXSymbolListOutputs / MXSymbolListAuxiliaryStates / MXSymbolInferShape /
//   MXSymbolFree, errors via MXSymGetLastError.
// Reference contracts kept: opaque handles; attrs as STRINGS; Compose
// mutates the atomic handle in place; list results and inferred shapes
// live in per-handle scratch valid until the next call on that handle
// (the reference's MXAPIThreadLocalEntry discipline, narrowed per-handle);
// InferShape takes CSR-packed input shapes keyed by argument name.
//
// TPU-native design: a handle holds a Python mxnet_tpu Symbol reached
// through embedded CPython — graph nodes compose through the SAME registry
// the Python frontend uses, and InferShape IS jax.eval_shape, so the C
// surface cannot drift from the Python one.

#include <Python.h>
#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_sym_last_error;

void sym_set_err(const std::string& m) { g_sym_last_error = m; }

void sym_set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* u = PyUnicode_AsUTF8(s);
      if (u) msg = u;
      Py_DECREF(s);
    }
  }
  PyErr_Clear();
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  sym_set_err(msg);
}

struct SymHandle {
  PyObject* obj = nullptr;     // mxnet_tpu Symbol OR pending-atomic dict
  // scratch caches (valid until the next call on this handle)
  std::string json_cache;
  std::vector<std::string> str_store;
  std::vector<const char*> str_ptrs;
  // InferShape scratch: three CSR groups (arg / out / aux)
  std::vector<uint32_t> shape_ndim[3];
  std::vector<std::vector<uint32_t>> shape_rows[3];
  std::vector<const uint32_t*> shape_ptrs[3];
};

const char kSymBootstrap[] = R"PY(
import ast as _ast
import sys as _sys
if _MXTPU_ROOT not in _sys.path:
    _sys.path.insert(0, _MXTPU_ROOT)
import mxnet_tpu as _mx
from mxnet_tpu.symbol.register import apply_op as _apply_op


class _SymCore:
    @staticmethod
    def variable(name):
        return _mx.sym.Variable(name)

    # ---- operator introspection (reference c_api_symbolic.cc
    # MXSymbolListAtomicSymbolCreators / MXSymbolGetAtomicSymbolInfo):
    # the surface the reference's language bindings read at build time
    # to GENERATE their typed wrappers ---------------------------------
    @staticmethod
    def list_atomic():
        from mxnet_tpu.ndarray.register import list_ops
        return list_ops()

    # variadic ops whose leading inputs are counted by a parameter —
    # the reference's key_var_num_args contract (nnvm op attr)
    _KEY_VAR_NUM_ARGS = {
        "Concat": "num_args", "concat": "num_args",
        "add_n": "num_args", "ElementWiseSum": "num_args",
        "stack": "num_args",
        "multi_sgd_update": "num_weights",
        "multi_sgd_mom_update": "num_weights",
        "multi_mp_sgd_mom_update": "num_weights",
        "multi_all_finite": "num_arrays",
    }

    @staticmethod
    def atomic_info(name):
        import inspect
        from mxnet_tpu.ndarray.register import get_op
        from mxnet_tpu.symbol.register import _OP_INPUTS
        op = get_op(name)
        names, types = [], []
        # tensor inputs first (reference arguments list leads with
        # them).  Structured ops come from the symbol-side input table;
        # for the rest the REAL arity is read off the maker's returned
        # fn signature (this registry's single source of truth) — never
        # fabricated.  Ops whose maker needs required params yield no
        # input metadata rather than a guess; *args fns report the
        # variadic marker.
        inputs = _OP_INPUTS.get(op.name)
        if inputs is None:
            try:
                fn = op.maker()
                fps = list(inspect.signature(fn).parameters.values())
                if any(p.kind == p.VAR_POSITIONAL for p in fps):
                    inputs = ("*data",)
                else:
                    inputs = tuple(
                        p.name for p in fps
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD))
            except Exception:
                inputs = ()
        for in_name in inputs:
            names.append(in_name)
            types.append("NDArray-or-Symbol")
        try:
            sig = inspect.signature(op.maker)
        except (TypeError, ValueError):
            sig = None
        if sig is not None:
            for p in sig.parameters.values():
                if p.kind not in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY):
                    continue
                names.append(p.name)
                if p.default is p.empty:
                    types.append("any, required")
                else:
                    types.append(
                        f"{type(p.default).__name__}, optional, "
                        f"default={p.default!r}")
        kv = _SymCore._KEY_VAR_NUM_ARGS.get(op.name, "")
        return op.name, (op.doc or ""), names, types, kv

    @staticmethod
    def from_json(js):
        return _mx.sym.load_json(js)

    @staticmethod
    def to_json(s):
        return s.tojson()

    @staticmethod
    def atomic(op, keys, vals):
        # reference two-phase protocol: CreateAtomicSymbol holds op+attrs,
        # Compose later binds inputs.  The pending node is a plain dict.
        kwargs = {}
        for k, v in zip(keys, vals):
            try:
                kwargs[k] = _ast.literal_eval(v)
            except (ValueError, SyntaxError):
                kwargs[k] = v
        return {"__pending_op__": op, "kwargs": kwargs}

    @staticmethod
    def compose(pending, name, keys, args):
        if not (isinstance(pending, dict) and "__pending_op__" in pending):
            raise ValueError("MXSymbolCompose: handle is not an atomic "
                             "symbol (already composed?)")
        op = pending["__pending_op__"]
        kw = dict(pending["kwargs"])
        if keys:
            kw.update(zip(keys, args))
            return _apply_op(op, [], kw, name=name or None)
        return _apply_op(op, list(args), kw, name=name or None)

    @staticmethod
    def list_arguments(s):
        return list(s.list_arguments())

    @staticmethod
    def list_outputs(s):
        return list(s.list_outputs())

    @staticmethod
    def list_aux(s):
        return list(s.list_auxiliary_states())

    @staticmethod
    def infer_shape(s, names, shapes):
        # reference contract: under-specified inputs are NOT an error —
        # rc=0 with *complete=0 (partial inference); only malformed
        # graphs raise
        kw = {n: tuple(int(d) for d in sh)
              for n, sh in zip(names, shapes)}
        arg, out, aux = s.infer_shape_partial(**kw)
        if arg is None:
            return None
        conv = lambda rows: [tuple(int(d) for d in r) for r in rows]
        return conv(arg), conv(out), conv(aux)
)PY";

PyObject* g_symcore_cls = nullptr;

std::once_flag g_py_init_once;

bool sym_ensure_python() {
  std::call_once(g_py_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
  return true;
}

bool sym_ensure_bootstrap() {
  if (g_symcore_cls) return true;
  Dl_info info;
  std::string root = ".";
  if (dladdr(reinterpret_cast<void*>(&sym_ensure_bootstrap), &info) &&
      info.dli_fname) {
    std::string p = info.dli_fname;
    for (int up = 0; up < 3; ++up) {
      auto pos = p.find_last_of('/');
      if (pos == std::string::npos) break;
      p = p.substr(0, pos);
    }
    if (!p.empty()) root = p;
  }
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* rootstr = PyUnicode_FromString(root.c_str());
  PyDict_SetItemString(globals, "_MXTPU_ROOT", rootstr);
  Py_DECREF(rootstr);
  PyObject* res =
      PyRun_String(kSymBootstrap, Py_file_input, globals, globals);
  if (!res) {
    sym_set_err_from_python();
    Py_DECREF(globals);
    return false;
  }
  Py_DECREF(res);
  g_symcore_cls = PyDict_GetItemString(globals, "_SymCore");
  Py_XINCREF(g_symcore_cls);
  Py_DECREF(globals);
  if (!g_symcore_cls) {
    sym_set_err("bootstrap did not define _SymCore");
    return false;
  }
  return true;
}

PyObject* str_list(uint32_t n, const char** items) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(items[i] ? items[i] : ""));
  return lst;
}

// shared body of the three MXSymbolList* calls
int list_strings(void* handle, const char* method, uint32_t* out_size,
                 const char*** out_array) {
  auto* h = static_cast<SymHandle*>(handle);
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* r =
        PyObject_CallMethod(g_symcore_cls, method, "O", h->obj);
    if (!r) {
      sym_set_err_from_python();
      break;
    }
    Py_ssize_t n = PyList_Size(r);
    h->str_store.clear();
    h->str_store.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char* u = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
      h->str_store.emplace_back(u ? u : "");
    }
    Py_DECREF(r);
    h->str_ptrs.clear();
    for (const auto& s : h->str_store) h->str_ptrs.push_back(s.c_str());
    *out_size = static_cast<uint32_t>(h->str_store.size());
    *out_array = h->str_ptrs.data();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

// unpack one python list-of-shape-tuples into the handle's CSR scratch.
// EVERY false return clears any pending CPython exception — leaking one
// across the ABI poisons the host's next CPython call (the
// PyLong_AsUnsignedLong path documents the same rule)
bool fill_shapes(SymHandle* h, int group, PyObject* rows) {
  if (!rows) {
    PyErr_Clear();
    return false;
  }
  Py_ssize_t n = PySequence_Size(rows);
  if (n < 0) {
    PyErr_Clear();
    return false;
  }
  h->shape_ndim[group].resize(n);
  h->shape_rows[group].assign(n, {});
  h->shape_ptrs[group].resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* row = PySequence_GetItem(rows, i);
    if (!row) {
      PyErr_Clear();
      return false;
    }
    Py_ssize_t nd = PySequence_Size(row);
    if (nd < 0) {
      PyErr_Clear();
      Py_DECREF(row);
      return false;
    }
    auto& dst = h->shape_rows[group][i];
    dst.resize(nd);
    for (Py_ssize_t d = 0; d < nd; ++d) {
      PyObject* it = PySequence_GetItem(row, d);
      unsigned long v = it ? PyLong_AsUnsignedLong(it) : 0;
      Py_XDECREF(it);
      if (PyErr_Occurred()) {
        // never report success with garbage dims or leak a pending
        // CPython exception past the ABI boundary
        PyErr_Clear();
        Py_DECREF(row);
        return false;
      }
      dst[d] = static_cast<uint32_t>(v);
    }
    Py_DECREF(row);
    h->shape_ndim[group][i] = static_cast<uint32_t>(nd);
    h->shape_ptrs[group][i] = dst.data();
  }
  return true;
}

}  // namespace

extern "C" {

const char* MXSymGetLastError() { return g_sym_last_error.c_str(); }

int MXSymbolCreateVariable(const char* name, void** out) {
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* obj =
        PyObject_CallMethod(g_symcore_cls, "variable", "s", name);
    if (!obj) {
      sym_set_err_from_python();
      break;
    }
    auto* h = new SymHandle();
    h->obj = obj;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateFromJSON(const char* json, void** out) {
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* obj =
        PyObject_CallMethod(g_symcore_cls, "from_json", "s", json);
    if (!obj) {
      sym_set_err_from_python();
      break;
    }
    auto* h = new SymHandle();
    h->obj = obj;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolSaveToJSON(void* handle, const char** out_json) {
  auto* h = static_cast<SymHandle*>(handle);
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(g_symcore_cls, "to_json", "O", h->obj);
  if (r) {
    const char* u = PyUnicode_AsUTF8(r);
    h->json_cache = u ? u : "";
    *out_json = h->json_cache.c_str();
    Py_DECREF(r);
    rc = 0;
  } else {
    sym_set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateAtomicSymbol(const char* op_name, uint32_t num_param,
                               const char** keys, const char** vals,
                               void** out) {
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* k = str_list(num_param, keys);
    PyObject* v = str_list(num_param, vals);
    PyObject* obj = PyObject_CallMethod(g_symcore_cls, "atomic", "sOO",
                                        op_name, k, v);
    Py_DECREF(k);
    Py_DECREF(v);
    if (!obj) {
      sym_set_err_from_python();
      break;
    }
    auto* h = new SymHandle();
    h->obj = obj;
    *out = h;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCompose(void* handle, const char* name, uint32_t num_args,
                    const char** keys, void** args) {
  auto* h = static_cast<SymHandle*>(handle);
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* klist;
    if (keys) {
      klist = str_list(num_args, keys);
    } else {
      klist = PyList_New(0);
    }
    PyObject* alist = PyList_New(num_args);
    bool bad = false;
    for (uint32_t i = 0; i < num_args; ++i) {
      auto* ah = static_cast<SymHandle*>(args[i]);
      if (!ah || !ah->obj) {
        bad = true;
        break;
      }
      Py_INCREF(ah->obj);
      PyList_SET_ITEM(alist, i, ah->obj);
    }
    if (bad) {
      Py_DECREF(klist);
      Py_DECREF(alist);
      sym_set_err("MXSymbolCompose: null argument handle");
      break;
    }
    PyObject* obj = PyObject_CallMethod(
        g_symcore_cls, "compose", "OsOO", h->obj, name ? name : "", klist,
        alist);
    Py_DECREF(klist);
    Py_DECREF(alist);
    if (!obj) {
      sym_set_err_from_python();
      break;
    }
    // reference semantics: Compose mutates the atomic handle in place
    Py_XDECREF(h->obj);
    h->obj = obj;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolListArguments(void* handle, uint32_t* out_size,
                          const char*** out_array) {
  return list_strings(handle, "list_arguments", out_size, out_array);
}

int MXSymbolListOutputs(void* handle, uint32_t* out_size,
                        const char*** out_array) {
  return list_strings(handle, "list_outputs", out_size, out_array);
}

int MXSymbolListAuxiliaryStates(void* handle, uint32_t* out_size,
                                const char*** out_array) {
  return list_strings(handle, "list_aux", out_size, out_array);
}

int MXSymbolInferShape(void* handle, uint32_t num_args, const char** keys,
                       const uint32_t* arg_ind_ptr,
                       const uint32_t* arg_shape_data,
                       uint32_t* in_shape_size,
                       const uint32_t** in_shape_ndim,
                       const uint32_t*** in_shape_data,
                       uint32_t* out_shape_size,
                       const uint32_t** out_shape_ndim,
                       const uint32_t*** out_shape_data,
                       uint32_t* aux_shape_size,
                       const uint32_t** aux_shape_ndim,
                       const uint32_t*** aux_shape_data, int* complete) {
  auto* h = static_cast<SymHandle*>(handle);
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* names = str_list(num_args, keys);
    PyObject* shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyObject* row = PyTuple_New(hi - lo);
      for (uint32_t d = lo; d < hi; ++d)
        PyTuple_SET_ITEM(row, d - lo,
                         PyLong_FromUnsignedLong(arg_shape_data[d]));
      PyList_SET_ITEM(shapes, i, row);
    }
    PyObject* r = PyObject_CallMethod(g_symcore_cls, "infer_shape", "OOO",
                                      h->obj, names, shapes);
    Py_DECREF(names);
    Py_DECREF(shapes);
    if (!r) {
      sym_set_err_from_python();
      break;
    }
    if (r == Py_None) {
      // partial inference: success with *complete = 0 and empty groups
      // (reference c_api_symbolic.cc contract)
      Py_DECREF(r);
      *in_shape_size = *out_shape_size = *aux_shape_size = 0;
      *in_shape_ndim = *out_shape_ndim = *aux_shape_ndim = nullptr;
      *in_shape_data = *out_shape_data = *aux_shape_data = nullptr;
      *complete = 0;
      rc = 0;
      break;
    }
    bool ok = true;
    PyObject* groups[3] = {PyTuple_GetItem(r, 0), PyTuple_GetItem(r, 1),
                           PyTuple_GetItem(r, 2)};
    for (int g = 0; g < 3 && ok; ++g) ok = fill_shapes(h, g, groups[g]);
    Py_DECREF(r);
    if (!ok) {
      sym_set_err("MXSymbolInferShape: malformed python result");
      break;
    }
    *in_shape_size = static_cast<uint32_t>(h->shape_ndim[0].size());
    *in_shape_ndim = h->shape_ndim[0].data();
    *in_shape_data = h->shape_ptrs[0].data();
    *out_shape_size = static_cast<uint32_t>(h->shape_ndim[1].size());
    *out_shape_ndim = h->shape_ndim[1].data();
    *out_shape_data = h->shape_ptrs[1].data();
    *aux_shape_size = static_cast<uint32_t>(h->shape_ndim[2].size());
    *aux_shape_ndim = h->shape_ndim[2].data();
    *aux_shape_data = h->shape_ptrs[2].data();
    *complete = 1;
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolFree(void* handle) {
  auto* h = static_cast<SymHandle*>(handle);
  if (!h) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->obj);
  PyGILState_Release(gil);
  delete h;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Operator introspection (reference: c_api_symbolic.cc
// MXSymbolListAtomicSymbolCreators / GetAtomicSymbolName /
// GetAtomicSymbolInfo) — the build-time surface language bindings read
// to generate typed wrappers.  Creator handles are interned name
// pointers (the op-handle discipline of the ndarray library); the info
// call's string storage is thread-local, valid until the thread's next
// info call.
// ---------------------------------------------------------------------------

namespace {

std::vector<std::string>* g_atomic_names = nullptr;
std::vector<const char*>* g_atomic_ptrs = nullptr;

struct AtomicInfoScratch {
  std::string name, desc, key_var;
  std::vector<std::string> arg_names, arg_types, arg_descs;
  std::vector<const char*> argn_ptrs, argt_ptrs, argd_ptrs;
};
thread_local AtomicInfoScratch g_atomic_info;

}  // namespace

extern "C" {

int MXSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                     void*** out_array) {
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    if (!g_atomic_names) {
      PyObject* r = PyObject_CallMethod(g_symcore_cls, "list_atomic",
                                        nullptr);
      if (!r) {
        sym_set_err_from_python();
        break;
      }
      g_atomic_names = new std::vector<std::string>();
      g_atomic_ptrs = new std::vector<const char*>();
      for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
        const char* u = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
        if (u) g_atomic_names->emplace_back(u);
        else PyErr_Clear();
      }
      for (auto& s : *g_atomic_names)
        g_atomic_ptrs->push_back(s.c_str());
      Py_DECREF(r);
    }
    *out_size = static_cast<uint32_t>(g_atomic_ptrs->size());
    *out_array = reinterpret_cast<void**>(
        const_cast<char**>(g_atomic_ptrs->data()));
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolGetAtomicSymbolName(void* creator, const char** name) {
  if (!creator) {
    sym_set_err("null creator handle");
    return -1;
  }
  *name = static_cast<const char*>(creator);
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(
    void* creator, const char** name, const char** description,
    uint32_t* num_args, const char*** arg_names, const char*** arg_types,
    const char*** arg_descriptions, const char** key_var_num_args) {
  sym_ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    if (!sym_ensure_bootstrap()) break;
    PyObject* r = PyObject_CallMethod(
        g_symcore_cls, "atomic_info", "s",
        static_cast<const char*>(creator));
    if (!r) {
      sym_set_err_from_python();
      break;
    }
    auto& sc = g_atomic_info;
    sc.arg_names.clear();
    sc.arg_types.clear();
    sc.arg_descs.clear();
    const char* u = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 0));
    sc.name = u ? u : "";
    u = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 1));
    sc.desc = u ? u : "";
    if (PyErr_Occurred()) PyErr_Clear();
    const char* kvs = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 4));
    sc.key_var = kvs ? kvs : "";
    if (PyErr_Occurred()) PyErr_Clear();
    PyObject* ns = PyTuple_GET_ITEM(r, 2);
    PyObject* ts = PyTuple_GET_ITEM(r, 3);
    for (Py_ssize_t i = 0; i < PyList_Size(ns); ++i) {
      const char* a = PyUnicode_AsUTF8(PyList_GET_ITEM(ns, i));
      const char* t = PyUnicode_AsUTF8(PyList_GET_ITEM(ts, i));
      if (PyErr_Occurred()) {
        PyErr_Clear();
        continue;
      }
      sc.arg_names.emplace_back(a ? a : "");
      sc.arg_types.emplace_back(t ? t : "");
      sc.arg_descs.emplace_back("");
    }
    Py_DECREF(r);
    sc.argn_ptrs.clear();
    sc.argt_ptrs.clear();
    sc.argd_ptrs.clear();
    for (auto& s : sc.arg_names) sc.argn_ptrs.push_back(s.c_str());
    for (auto& s : sc.arg_types) sc.argt_ptrs.push_back(s.c_str());
    for (auto& s : sc.arg_descs) sc.argd_ptrs.push_back(s.c_str());
    if (name) *name = sc.name.c_str();
    if (description) *description = sc.desc.c_str();
    if (num_args)
      *num_args = static_cast<uint32_t>(sc.arg_names.size());
    if (arg_names) *arg_names = sc.argn_ptrs.data();
    if (arg_types) *arg_types = sc.argt_ptrs.data();
    if (arg_descriptions) *arg_descriptions = sc.argd_ptrs.data();
    if (key_var_num_args) *key_var_num_args = sc.key_var.c_str();
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
