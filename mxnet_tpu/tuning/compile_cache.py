"""Persistent compilation cache: compiled executables that survive the
process.

PERF.md documents multi-minute XLA compiles inside 2-minute chip
windows: every restart — a preemption auto-resume, a ModelServer cold
start, a bench subprocess — re-pays the full compile for graphs the
previous process already built.  The in-memory caches this repo already
keys carefully (``_segment_cache`` in ndarray/register.py, the
per-signature ``HybridBlock._cached_graph``) die with the process; this
module gives those same keys a disk tier.

Design:

- **Keyed on the existing signature keys + a backend fingerprint.**
  A cache entry's name is ``sha256(kind + canonical-key + fingerprint)``
  where the fingerprint covers jax/jaxlib versions, the backend
  platform, and the device kind — a cache written by one toolchain or
  chip generation can never be replayed onto another (the stale entry
  simply never matches and ages out).
- **Written atomically** (tmp + ``os.replace``), so a crash mid-write
  leaves no torn entry and concurrent processes can share one
  directory — last writer wins, both wrote the same bytes.
- **Loaded lazily on first miss.**  Nothing is read at import or
  construction; a lookup happens only where the in-memory cache already
  missed, i.e. on the cold compile path — the steady-state hot path
  never touches this module (the mxlint ``hot-path-purity`` reachability
  proof holds because the wiring seams are installed hooks, not direct
  calls).

Two payload formats, matching the two compile paths in the repo:

- **pjrt** — exact-mode bulk segments compile through the raw PJRT
  client (``device.client.compile``); ``client.serialize_executable``
  round-trips those directly.
- **jit** — cached-graph executables are ``jax.jit`` artifacts; the AOT
  ``jax.experimental.serialize_executable`` pickle (payload + in/out
  trees) round-trips a ``lowered.compile()`` result.  Entries are
  trusted local state (same trust level as jax's own persistent cache,
  which uses the same mechanism).

Metrics (process-global registry): ``tuning.compile_cache_hits`` /
``_misses`` / ``_stores`` / ``_errors``, and ``tuning.compiles`` — the
count of actual backend compiles performed at cache-wired sites.  A
warm-started process replaying only previously-seen signatures holds
``tuning.compiles`` at ~0; the subprocess test asserts exactly that.

Enabled by ``MXTPU_COMPILE_CACHE_DIR``; with ``MXTPU_COMPILE_CACHE_JAX``
(default on) the same directory also hosts jax's own persistent
compilation cache (``<dir>/jax``), so plain ``jax.jit`` paths — per-op
fns, training vjp graphs — reuse compiles across processes too.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import warnings
from typing import Optional

from ..base import get_env
from ..observability.registry import registry as _metrics_registry

__all__ = ["CompileCache", "active", "configure", "install",
           "CACHE_DIR_ENV", "CACHE_JAX_ENV"]

CACHE_DIR_ENV = "MXTPU_COMPILE_CACHE_DIR"
CACHE_JAX_ENV = "MXTPU_COMPILE_CACHE_JAX"


def _fingerprint() -> str:
    """Toolchain + backend identity baked into every key: an entry
    compiled by a different jax/jaxlib or for a different chip must
    never deserialize into this process."""
    import jax
    import jaxlib
    try:
        dev = jax.devices()[0]
        backend = f"{dev.platform}/{dev.device_kind}"
    except Exception:   # noqa: BLE001 — no backend yet: fingerprint
        backend = "unknown"        # conservatively mismatches later runs
    return f"jax={jax.__version__};jaxlib={jaxlib.__version__};" \
           f"backend={backend}"


class CompileCache:
    """One directory of serialized executables (see module docstring).

    All I/O failures degrade to a miss (and count in
    ``tuning.compile_cache_errors``): a broken cache dir must never take
    down the compile it was supposed to skip.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._fp: Optional[str] = None
        self._lock = threading.Lock()
        reg = _metrics_registry()
        self._c_hits = reg.counter(
            "tuning.compile_cache_hits",
            help="persistent compile-cache entries deserialized instead "
                 "of compiled")
        self._c_misses = reg.counter(
            "tuning.compile_cache_misses",
            help="persistent compile-cache lookups that found no entry")
        self._c_stores = reg.counter(
            "tuning.compile_cache_stores",
            help="executables serialized into the persistent cache")
        self._c_errors = reg.counter(
            "tuning.compile_cache_errors",
            help="cache I/O or (de)serialization failures, each "
                 "degraded to a miss")
        self._c_compiles = reg.counter(
            "tuning.compiles",
            help="actual backend compiles at persistent-cache-wired "
                 "sites — ~0 on a warm start replaying known "
                 "signatures")

    # -- keys / paths --------------------------------------------------------
    def _fingerprint(self) -> str:
        fp = self._fp
        if fp is None:
            fp = self._fp = _fingerprint()
        return fp

    def entry_key(self, kind: str, canonical: str) -> str:
        h = hashlib.sha256()
        h.update(kind.encode("utf-8"))
        h.update(b"\0")
        h.update(self._fingerprint().encode("utf-8"))
        h.update(b"\0")
        h.update(canonical.encode("utf-8"))
        return f"{kind}-{h.hexdigest()}"

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.bin")

    def __len__(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.path)
                       if n.endswith(".bin"))
        except OSError:
            return 0

    # -- raw byte tier -------------------------------------------------------
    def load_bytes(self, key: str) -> Optional[bytes]:
        try:
            with open(self._entry_path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None
        except OSError:
            self._c_errors.inc()
            return None

    def store_bytes(self, key: str, data: bytes) -> bool:
        """Atomic write: tmp + rename, pid-suffixed so concurrent
        processes never clobber each other's tmp files."""
        path = self._entry_path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.path, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
            self._c_stores.inc()
            return True
        except OSError:
            self._c_errors.inc()
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    # -- pjrt tier: exact-mode bulk segments ---------------------------------
    def load_pjrt(self, key: str, client, options):
        """Deserialize a raw PJRT executable, or None on miss.  The
        caller supplies the same CompileOptions it would compile with —
        PJRT needs them to rebuild the device assignment."""
        data = self.load_bytes(key)
        if data is None:
            self._c_misses.inc()
            return None
        try:
            exe = client.deserialize_executable(data, options)
        except Exception:   # noqa: BLE001 — stale/foreign entry: a miss,
            self._c_errors.inc()       # never a crash on the compile path
            return None
        self._c_hits.inc()
        return exe

    def store_pjrt(self, key: str, client, exe) -> None:
        self._c_compiles.inc()         # a store follows a real compile
        try:
            data = client.serialize_executable(exe)
        except Exception:   # noqa: BLE001 — backend without executable
            self._c_errors.inc()       # serialization: run-only, no disk
            return
        self.store_bytes(key, bytes(data))

    # -- jit tier: AOT-compiled jax.jit executables --------------------------
    def load_jit(self, key: str):
        """Deserialize an AOT ``Compiled`` callable, or None on miss."""
        data = self.load_bytes(key)
        if data is None:
            self._c_misses.inc()
            return None
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = pickle.loads(data)
            compiled = _se.deserialize_and_load(payload, in_tree,
                                                out_tree)
        except Exception:   # noqa: BLE001 — toolchain drift or torn
            self._c_errors.inc()       # entry reads as a plain miss
            return None
        self._c_hits.inc()
        return compiled

    def store_jit(self, key: str, compiled) -> None:
        self._c_compiles.inc()
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            data = pickle.dumps((payload, in_tree, out_tree))
        except Exception:   # noqa: BLE001 — same degradation as pjrt
            self._c_errors.inc()
            return
        self.store_bytes(key, data)


# -- process-global instance + wiring ---------------------------------------

_active_lock = threading.Lock()
_active: Optional[CompileCache] = None
_configured_for: Optional[str] = None
_jax_cache_warned = False


def active() -> Optional[CompileCache]:
    """THE process-global cache, or None when ``MXTPU_COMPILE_CACHE_DIR``
    is unset.  Resolved live so a test (or a late-exported env) can
    enable it after import; the instance is rebuilt if the dir changes."""
    global _active, _configured_for
    path = (get_env(CACHE_DIR_ENV) or "").strip()
    if not path:
        return None
    inst = _active
    if inst is not None and _configured_for == path:
        return inst
    with _active_lock:
        if _active is None or _configured_for != path:
            _active = CompileCache(path)
            _configured_for = path
            _wire(_active)
    return _active


def configure(path: Optional[str] = None) -> Optional[CompileCache]:
    """Explicit enable: point the cache at ``path`` (exported to the
    env so child processes inherit it) and wire every seam.  With no
    argument, just resolves from the env like :func:`active`."""
    if path:
        os.environ[CACHE_DIR_ENV] = os.path.abspath(path)
    return active()


# back-compat alias: install() == configure-from-env
install = configure


def _wire(cache: CompileCache) -> None:
    """Install the lazy-load seams.  Hook indirection keeps the cache
    OFF the dispatch hot path in mxlint's reachability proof and keeps
    the frontend layers free of a tuning import."""
    from ..ndarray import register as _register
    _register._install_persist_hooks(_segment_lookup, _segment_store)
    _maybe_configure_jax_cache(cache)


def _maybe_configure_jax_cache(cache: CompileCache) -> None:
    """Point jax's own persistent compilation cache at ``<dir>/jax`` so
    the plain ``jax.jit`` paths (per-op fns, training vjp graphs) also
    survive restarts.  Best-effort: refused config updates (backend
    already live on some versions) only cost the jit tier."""
    global _jax_cache_warned
    if not get_env(CACHE_JAX_ENV):
        return
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(cache.path, "jax"))
        # default thresholds skip sub-second compiles and tiny
        # executables — this repo's segment graphs are exactly those
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          0)
    except Exception as e:   # noqa: BLE001 — version drift in config
        if not _jax_cache_warned:  # names must not disable OUR tiers
            _jax_cache_warned = True
            warnings.warn(
                f"persistent compile cache: could not configure jax's "
                f"own compilation cache ({e}); segment/cached-graph "
                f"tiers remain active", RuntimeWarning, stacklevel=2)


# -- the segment seam (installed into ndarray.register) ---------------------

def _segment_lookup(canonical: str, device, options):
    """Hook: exact-mode segment cache miss → try the disk tier."""
    cache = active()
    if cache is None:
        return None
    key = cache.entry_key("seg", canonical)
    return cache.load_pjrt(key, device.client, options)


def _segment_store(canonical: str, device, exe) -> None:
    """Hook: a segment executable was compiled → persist it."""
    cache = active()
    if cache is None:
        return
    key = cache.entry_key("seg", canonical)
    cache.store_pjrt(key, device.client, exe)


# -- the cached-graph seam (called from gluon.block) ------------------------

def aot_compile(lowered, kind: str = "graph"):
    """Compile a ``jax.jit(...).lower(...)`` artifact through the
    persistent cache: the lowered StableHLO text (plus the backend
    fingerprint) is the key, so identical traces in a fresh process
    deserialize instead of compiling.  Returns the AOT ``Compiled``
    callable, or None when the cache is disabled (callers then keep
    their plain jit path)."""
    cache = active()
    if cache is None:
        return None
    try:
        canonical = lowered.as_text()
    except Exception:   # noqa: BLE001 — no text form: nothing to key on
        cache._c_errors.inc()
        return None
    key = cache.entry_key(kind, canonical)
    compiled = cache.load_jit(key)
    if compiled is not None:
        return compiled
    compiled = lowered.compile()
    cache.store_jit(key, compiled)
    return compiled
