"""Self-tuning runtime: feedback controllers + a persistent compile
cache.

The two halves of ROADMAP direction #4, closing the loops the
observability spine already measures:

- :mod:`.controllers` — a :class:`Controller` base (guard rails,
  hysteresis, dry-run, every decision recorded as ``tuning.*`` metrics
  and a flight-recorder tuning record) and four concrete controllers:
  :class:`~.controllers.BulkSizeController` (``MXNET_ENGINE_BULK_SIZE``
  from ``engine.flush_us``), :class:`~.controllers.PrefetchController`
  (loader prefetch depth from its queue gauge),
  :class:`~.controllers.BatchWindowController`
  (``MXTPU_SERVING_BATCH_WINDOW_US`` from the serving queue gauge +
  request p99), :class:`~.controllers.FleetGatherController`
  (timer-thread fleet metric gather over the barrier-free KV
  transport), :class:`~.controllers.DevicePrefetchController` (the
  loader's device double-buffer depth vs HBM from the
  ``loader.device_put_us`` jitter) and — constructed per live
  instance, not stock — :class:`~.controllers.CommBucketController`
  (``MXTPU_COMM_BUCKET_MB`` hill-climb on ``resilience.step_us``) and
  :class:`~.controllers.DecodeSlotController` (a GenerationServer's
  decode-slot width hill-climbed on interval tokens/s, with the same
  bracketing stop — every move is a recompile) and
  :class:`~.controllers.SloController` (per-model p99 SLO defense over
  the PR-18 frontend registry: shed lowest-priority-first, scale the
  violator's dispatch workers);
- :mod:`.compile_cache` — compiled executables (exact-mode bulk
  segments, HybridBlock cached graphs) serialized to
  ``MXTPU_COMPILE_CACHE_DIR`` and reloaded by later processes, so
  auto-resume and server cold starts skip the XLA compile.

All controllers share ONE daemon timer thread
(:class:`TuningRuntime`), ticking every ``MXTPU_TUNE_INTERVAL``
seconds.  Controllers are tick-driven and wall-clock-free inside, so
tests (and the bench convergence loop) call ``controller.tick()`` /
``runtime().tick_all()`` directly against synthetic metric streams.

Quick start::

    from mxnet_tpu import tuning
    tuning.start()               # standard controllers, knob-gated
    ...                          # train / serve; knobs now self-tune
    tuning.stop()

Knobs: ``MXTPU_TUNE_INTERVAL``, ``MXTPU_TUNE_DRY_RUN``,
``MXTPU_TUNE_BULK`` / ``_PREFETCH`` / ``_BATCH_WINDOW`` /
``_FLEET_GATHER`` / ``_DECODE_SLOTS``, ``MXTPU_COMPILE_CACHE_DIR``,
``MXTPU_COMPILE_CACHE_JAX`` (see the README knob table).
"""
from __future__ import annotations

import threading
import warnings
from typing import List, Optional

from ..base import get_env
from ..observability.registry import registry as _metrics_registry
from . import compile_cache
from .controllers import (BatchWindowController, BulkSizeController,
                          CommBucketController, Controller, CounterDelta,
                          DecodeSlotController, DevicePrefetchController,
                          FleetGatherController, HistogramDelta,
                          PrefetchController, SloController)

__all__ = ["TuningRuntime", "runtime", "standard_controllers", "start",
           "stop", "Controller", "BulkSizeController",
           "PrefetchController", "BatchWindowController",
           "FleetGatherController", "CommBucketController",
           "DecodeSlotController", "DevicePrefetchController",
           "SloController", "HistogramDelta", "CounterDelta",
           "compile_cache"]

INTERVAL_ENV = "MXTPU_TUNE_INTERVAL"


class TuningRuntime:
    """The shared controller timer: one daemon thread ticking every
    registered controller each ``MXTPU_TUNE_INTERVAL`` seconds (read
    live per lap, so the cadence can be retuned on a running process).

    A controller whose ``tick()`` raises is counted
    (``tuning.errors``), warned about once, and *kept* — one misbehaving
    loop must not silence the other three.  ``tick_all()`` is the
    synchronous entry tests and the bench convergence loop drive
    directly."""

    def __init__(self):
        self._controllers: List[Controller] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._warned: set = set()
        self._c_errors = _metrics_registry().counter(
            "tuning.errors",
            help="controller tick() exceptions (each warned once, "
                 "controller kept)")
        self._c_ticks = _metrics_registry().counter(
            "tuning.ticks", help="runtime timer-thread tick sweeps")

    # -- membership ----------------------------------------------------------
    def add(self, controller: Controller) -> Controller:
        with self._lock:
            self._controllers.append(controller)
        return controller

    def remove(self, controller: Controller) -> None:
        with self._lock:
            if controller in self._controllers:
                self._controllers.remove(controller)

    @property
    def controllers(self) -> List[Controller]:
        with self._lock:
            return list(self._controllers)

    # -- ticking -------------------------------------------------------------
    def tick_all(self) -> List[dict]:
        """One synchronous sweep over every controller; returns the
        non-None decision records (the timer thread discards them —
        they already landed in metrics + the flight ring)."""
        self._c_ticks.inc()
        out = []
        for c in self.controllers:
            try:
                d = c.tick()
            except Exception as e:   # noqa: BLE001 — one bad controller
                self._c_errors.inc()       # must not kill the sweep
                if c.name not in self._warned:
                    self._warned.add(c.name)
                    warnings.warn(
                        f"tuning controller {c.name!r} raised "
                        f"{type(e).__name__}: {e} (counted in "
                        f"tuning.errors; controller kept)",
                        RuntimeWarning, stacklevel=2)
                continue
            if d is not None:
                out.append(d)
        return out

    def _run(self) -> None:
        while True:
            interval = max(0.05, float(get_env(INTERVAL_ENV)))
            if self._stop.wait(interval):
                return
            self.tick_all()

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "TuningRuntime":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="mxtpu-tuning", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout)


_runtime_lock = threading.Lock()
_runtime_inst: Optional[TuningRuntime] = None


def runtime() -> TuningRuntime:
    """THE process-global tuning runtime (analog of ``Engine.get()``)."""
    global _runtime_inst
    inst = _runtime_inst
    if inst is not None:
        return inst
    with _runtime_lock:
        if _runtime_inst is None:
            _runtime_inst = TuningRuntime()
        return _runtime_inst


def standard_controllers(**overrides) -> List[Controller]:
    """The stock controllers, each gated by its own
    ``MXTPU_TUNE_*`` enable knob (evaluated live at every tick, so a
    controller can be switched off on a running process).  Keyword
    overrides are forwarded per controller:
    ``standard_controllers(bulk_size={"vmax": 32})``."""
    return [
        BulkSizeController(**overrides.get("bulk_size", {})),
        PrefetchController(**overrides.get("prefetch", {})),
        BatchWindowController(**overrides.get("batch_window", {})),
        FleetGatherController(**overrides.get("fleet_gather", {})),
        DevicePrefetchController(**overrides.get("device_prefetch", {})),
        # CommBucketController is NOT stock: it needs a live
        # ShardedTrainer reference (apply rebuilds that trainer's jit)
        # — construct it with the trainer and runtime().add() it
    ]


def start(controllers: Optional[List[Controller]] = None,
          **overrides) -> TuningRuntime:
    """Convenience: register ``controllers`` (default: the stock set)
    on the global runtime and start its timer thread.  Also resolves
    the persistent compile cache from the env (``configure``), so one
    call arms both halves of the self-tuning runtime."""
    rt = runtime()
    if controllers is None:
        if not rt.controllers:
            controllers = standard_controllers(**overrides)
        elif overrides:
            # silently dropping caller-specified guard rails would
            # leave the OLD rails in force while the operator believes
            # the new ones are — say so
            warnings.warn(
                "tuning.start(): the runtime already has controllers "
                "registered; the given overrides were NOT applied — "
                "remove the existing controllers (runtime().remove) or "
                "pass controllers= explicitly", RuntimeWarning,
                stacklevel=2)
    for c in controllers or ():
        rt.add(c)
    compile_cache.active()        # wire the disk tier if the env asks
    return rt.start()


def stop(timeout: Optional[float] = 5.0) -> None:
    runtime().stop(timeout)
