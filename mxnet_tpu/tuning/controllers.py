"""Feedback controllers: close the loops the metrics spine measures.

Rounds 3–7 built an observability layer that measures everything and
controls nothing — ``engine.flush_us`` was recorded explicitly as "the
signal the MXNET_ENGINE_BULK_SIZE auto-tune follow-up needs", the loader
and serving layers export queue-depth gauges nobody read.  Each
controller here reads those exact signals and closes its loop:

==========================  ===============================================
:class:`BulkSizeController`  hill-climbs the live ``MXNET_ENGINE_BULK_SIZE``
                             cap from ``engine.flush_us`` interval deltas
:class:`PrefetchController`  adapts the DataLoader prefetch-depth target
                             from the ``loader.prefetch_depth`` gauge
:class:`BatchWindowController`  adapts ``MXTPU_SERVING_BATCH_WINDOW_US``
                             from ``serving.queue_depth`` +
                             ``serving.request_us`` p99 (PR-7 follow-up)
:class:`FleetGatherController`  streams the multi-host metric gather over
                             the barrier-free KV transport on the timer
                             thread instead of checkpoint boundaries
                             (PR-4 follow-up)
==========================  ===============================================

Shared discipline (the :class:`Controller` base):

- **guard rails** — every proposal clamps to ``[vmin, vmax]`` before it
  can touch anything (clamps are counted: a controller pinned to a rail
  is a controller whose model of the system is wrong);
- **hysteresis** — a change applies only after ``hysteresis`` consecutive
  ticks proposed a move in the same direction, so a single noisy
  interval cannot flap a knob;
- **dry run** — ``MXTPU_TUNE_DRY_RUN`` (or the per-instance flag)
  computes and records every decision but applies nothing: the
  observe-before-trust mode for new deployments;
- **auditable decisions** — every decision lands in the ``tuning.*``
  metrics (``tuning.<name>.value`` gauge, ``.decisions``/``.applied``/
  ``.clamped`` counters) AND as a flight-recorder tuning record, so a
  bad controller decision is visible in the crash post-mortem ring.

Controllers are deliberately *pull-based and tick-driven*: ``tick()``
reads registry metric deltas accumulated since the previous tick — no
wall-clock inside, so tests drive them with synthetic metric streams and
zero sleeps.  The shared timer thread lives in
:class:`mxnet_tpu.tuning.TuningRuntime`.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from ..base import get_env
from ..observability.flight import recorder as _flight_recorder
from ..observability.registry import (_percentile_from, registry,
                                      state_bounds)

__all__ = ["Controller", "BulkSizeController", "PrefetchController",
           "BatchWindowController", "FleetGatherController",
           "CommBucketController", "DecodeSlotController",
           "DevicePrefetchController", "SloController",
           "HistogramDelta", "CounterDelta", "exemplar_ids"]

DRY_RUN_ENV = "MXTPU_TUNE_DRY_RUN"


def exemplar_ids(hist, k: int = 3) -> str:
    """Comma-joined trace_ids from ``hist``'s highest (slowest)
    exemplar-carrying buckets, newest first — the concrete traces
    behind the tail a controller is steering on.  Empty when causal
    tracing is off (exemplars only exist while tracing records)."""
    ex = hist.exemplars()
    if not ex:
        return ""
    ids: List[str] = []
    for bound in sorted(ex, reverse=True):
        for tid, _v, _ts in reversed(ex[bound]):
            if tid not in ids:
                ids.append(tid)
            if len(ids) >= k:
                return ",".join(ids)
    return ",".join(ids)


class HistogramDelta:
    """Interval view over a registry Histogram: ``take()`` returns the
    aggregate of observations since the previous ``take()`` (count,
    total, p50/p99 from the bucket-count delta) — the per-tick signal a
    controller steers on, immune to the lifetime average's inertia."""

    def __init__(self, hist):
        self._h = hist
        self._last: Optional[dict] = None

    @property
    def hist(self):
        """The underlying registry Histogram (exemplar access)."""
        return self._h

    def take(self) -> Optional[dict]:
        st = self._h.state()
        last, self._last = self._last, st
        if last is None:
            return None
        counts = [a - b for a, b in zip(st["counts"], last["counts"])]
        n = st["count"] - last["count"]
        total = st["total"] - last["total"]
        if n <= 0:
            return {"count": 0, "total": 0.0, "p50": 0.0, "p99": 0.0,
                    "mean": 0.0}
        bounds = state_bounds(st)
        # lifetime min/max clamp the edge buckets — close enough for a
        # steering signal, and strictly conservative
        p50 = _percentile_from(bounds, counts, n, st["min"], st["max"],
                               50)
        p99 = _percentile_from(bounds, counts, n, st["min"], st["max"],
                               99)
        return {"count": n, "total": total, "p50": p50, "p99": p99,
                "mean": total / n}


class CounterDelta:
    """Interval view over a registry Counter (see HistogramDelta)."""

    def __init__(self, counter):
        self._c = counter
        self._last: Optional[int] = None

    def take(self) -> int:
        n = self._c.n
        last, self._last = self._last, n
        return 0 if last is None else max(0, n - last)


class Controller:
    """Base feedback controller (see module docstring for the shared
    discipline).  Subclasses implement:

    - ``current()`` — the live value of the controlled quantity;
    - ``decide()`` — ``(proposal, reason)`` from this tick's metric
      deltas, or None to hold;
    - ``apply(value)`` — actually mutate the knob/target.

    ``tick()`` runs the template: enable gate → decide → clamp to the
    guard rails → hysteresis → (dry-run-gated) apply → record the
    decision as ``tuning.*`` metrics + a flight-recorder tuning record.
    """

    #: metric namespace component (``tuning.<name>.*``) — snake_case
    name = "controller"
    #: the env knob this controller owns (documentation + decision
    #: records); None for non-knob controllers
    knob: Optional[str] = None
    #: per-controller enable knob (``MXTPU_TUNE_*``); None = always on
    enable_env: Optional[str] = None

    def __init__(self, *, vmin: float, vmax: float, hysteresis: int = 1,
                 enabled: Optional[bool] = None,
                 dry_run: Optional[bool] = None, flight=None):
        self.vmin = vmin
        self.vmax = vmax
        self.hysteresis = max(1, int(hysteresis))
        self._enabled = enabled
        self._dry_run = dry_run
        self._pending_dir = 0
        self._pending_n = 0
        self._flight = _flight_recorder() if flight is None else flight
        reg = registry()
        self._g_value = reg.gauge(
            f"tuning.{self.name}.value",
            help=f"live value of the {self.name} controller's target")
        self._c_decisions = reg.counter(
            f"tuning.{self.name}.decisions",
            help="decisions recorded (applied, held by hysteresis, or "
                 "dry-run)")
        self._c_applied = reg.counter(
            f"tuning.{self.name}.applied",
            help="decisions actually applied to the live knob/target")
        self._c_clamped = reg.counter(
            f"tuning.{self.name}.clamped",
            help="proposals clamped by the min/max guard rails — "
                 "sustained clamping means the rails disagree with the "
                 "controller's model")

    # -- knobs ---------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        if self._enabled is not None:
            return self._enabled
        if self.enable_env is not None:
            return bool(get_env(self.enable_env))
        return True

    @property
    def dry_run(self) -> bool:
        if self._dry_run is not None:
            return self._dry_run
        return bool(get_env(DRY_RUN_ENV))

    # -- subclass surface ----------------------------------------------------
    def current(self) -> float:
        raise NotImplementedError

    def decide(self) -> Optional[Tuple[float, str]]:
        raise NotImplementedError

    def apply(self, value) -> None:
        raise NotImplementedError

    def on_applied(self, value) -> None:
        """Post-apply hook for search-state baselines (hill climbers
        reset their comparison score here — NOT called in dry-run, so a
        dry-run controller never believes a move it didn't make)."""

    # -- the template --------------------------------------------------------
    def tick(self) -> Optional[dict]:
        """One control decision; returns the decision record (also sent
        to metrics + flight ring) or None when holding."""
        if not self.enabled:
            return None
        out = self.decide()
        cur = self.current()
        self._g_value.set(cur)
        if out is None:
            return None
        proposal, reason = out
        clamped = min(max(proposal, self.vmin), self.vmax)
        if clamped != proposal:
            self._c_clamped.inc()
            reason += f" [clamped {proposal:g} -> {clamped:g}]"
        if clamped == cur:
            self._pending_dir = 0
            self._pending_n = 0
            return None
        direction = 1 if clamped > cur else -1
        if direction == self._pending_dir:
            self._pending_n += 1
        else:
            self._pending_dir = direction
            self._pending_n = 1
        applied = False
        held = self._pending_n < self.hysteresis
        if not held:
            self._pending_dir = 0
            self._pending_n = 0
            if not self.dry_run:
                self.apply(clamped)
                self.on_applied(clamped)
                applied = True
                self._g_value.set(clamped)
        decision = {
            "controller": self.name,
            "knob": self.knob,
            "from": cur,
            "to": clamped,
            "applied": applied,
            "held": held,
            "dry_run": self.dry_run,
            "reason": reason,
        }
        # causal audit: controllers that steer on an exemplar-carrying
        # histogram stash the tail's trace_ids in decide() — the
        # decision record then names the actual traces that drove it
        ex = getattr(self, "_tick_exemplars", "")
        if ex:
            decision["exemplars"] = ex
            self._tick_exemplars = ""
        self._c_decisions.inc()
        if applied:
            self._c_applied.inc()
        self._flight.record_tuning(**decision)
        return decision


# ---------------------------------------------------------------------------
# BulkSizeController — the PR-2/PR-3 staged follow-up
# ---------------------------------------------------------------------------

class BulkSizeController(Controller):
    """Hill-climb the live ``MXNET_ENGINE_BULK_SIZE`` cap to minimize
    host-side dispatch cost per bulked op.

    Signal: the interval delta of ``engine.flush_us`` (per-segment flush
    latency — recorded unconditionally since PR 3 precisely for this
    loop) over the interval delta of ``engine.bulked_ops_flushed``:
    ``us-per-op = Δflush_total / Δops``.  Larger segments amortize the
    fixed dispatch overhead until compile variety / cache pressure turns
    the curve back up; the climb follows the measured gradient:

    - first move probes upward (the default cap of 15 was chosen for a
      1-core CI host; real hosts usually profit from more);
    - an interval that improved us-per-op by > ``tol`` keeps the
      direction; one that regressed by > ``tol`` reverses it; a plateau
      holds (that IS convergence — the controller then sits still until
      the workload shifts);
    - a p99 guard (``p99_budget_us``) forces downward pressure when tail
      flushes blow the budget regardless of the mean trend.

    Steps are multiplicative (``factor``), so the sweep covers the
    useful range (2..64) in a handful of decisions.
    """

    name = "bulk_size"
    knob = "MXNET_ENGINE_BULK_SIZE"
    enable_env = "MXTPU_TUNE_BULK"

    def __init__(self, *, vmin: int = 2, vmax: int = 64,
                 factor: float = 1.5, min_segments: int = 20,
                 tol: float = 0.03, settle_intervals: int = 1,
                 p99_budget_us: Optional[float] = None, **kw):
        super().__init__(vmin=vmin, vmax=vmax, **kw)
        self.factor = float(factor)
        self.min_segments = int(min_segments)
        self.tol = float(tol)
        self.settle_intervals = int(settle_intervals)
        self.p99_budget_us = p99_budget_us
        reg = registry()
        self._flush = HistogramDelta(reg.histogram("engine.flush_us"))
        self._ops = CounterDelta(reg.counter(
            "engine.bulked_ops_flushed"))
        self._dir = 1
        self._settle = 0
        self._last_score: Optional[float] = None

    def current(self) -> float:
        return int(get_env("MXNET_ENGINE_BULK_SIZE"))

    def on_applied(self, value) -> None:
        # the first interval(s) after a cap change are contaminated by
        # the new segment signatures' COMPILES (orders of magnitude
        # above a steady-state flush) — judging the move on them reads
        # every move as a regression and the climb degenerates into
        # oscillation (measured).  Discard them; judge the move on the
        # first clean interval.
        self._settle = self.settle_intervals

    def decide(self):
        d = self._flush.take()
        ops = self._ops.take()
        if d is None or d["count"] < self.min_segments or ops <= 0:
            return None
        if self._settle > 0:
            # the settle credit must be spent on an interval that
            # actually CARRIES flushes at the new cap (the compile
            # spikes) — an empty lull interval must not consume it, or
            # the contamination lands on the next judged interval and
            # the oscillation returns
            self._settle -= 1
            return None
        self._tick_exemplars = exemplar_ids(self._flush.hist)
        score = d["total"] / ops          # host us per bulked op
        cur = int(self.current())
        if self.p99_budget_us is not None and \
                d["p99"] > self.p99_budget_us:
            self._dir = -1
            self._last_score = score
        elif self._last_score is not None:
            if score > self._last_score * (1 + self.tol):
                self._dir = -self._dir    # regressed: turn around
                self._last_score = score
            elif score < self._last_score * (1 - self.tol):
                self._last_score = score  # improved: keep climbing
            else:
                # plateau: converged — hold here until the curve moves
                self._last_score = score
                return None
        else:
            self._last_score = score      # first full interval: probe up
        nxt = cur * self.factor if self._dir > 0 else cur / self.factor
        proposal = max(1, int(round(nxt)))
        if proposal == cur:               # factor rounding stuck
            proposal = cur + self._dir
        return proposal, (f"flush us/op={score:.2f} "
                          f"p50={d['p50']:.1f} p99={d['p99']:.1f} "
                          f"segments={d['count']} dir={self._dir:+d}")

    def apply(self, value) -> None:
        from ..engine import engine
        engine().set_bulk_size(int(value))


# ---------------------------------------------------------------------------
# PrefetchController
# ---------------------------------------------------------------------------

class PrefetchController(Controller):
    """Adapt the DataLoader prefetch-depth target from the
    ``loader.prefetch_depth`` gauge (sampled at every batch handoff).

    The gauge's own help text is the policy: *near-capacity means
    workers keep ahead of the device; near-zero means the pipeline is
    starving the step*.  A starving queue gets a deeper in-flight
    window (more batches in parallel absorb worker jitter); a queue
    pinned at capacity for ``hysteresis`` consecutive ticks gets a
    shallower one (each slot is a materialized host batch — memory).
    The applied target takes effect on the next ``__iter__`` (epoch
    boundary) via :func:`mxnet_tpu.gluon.data.dataloader.
    set_prefetch_override`.

    Two guards keep the model honest:

    - an interval with fewer than ``min_batches`` loader batches holds
      — an idle (or serving-only) process's zero gauge must not read
      as starvation and ratchet the override to the rail;
    - a depth EMA *above* the target means some loader was constructed
      deeper than the controller's model — the observed depth is
      adopted as the new baseline instead of being fought down, and
      the shrink branch only ever fires once the override (this
      controller's own sizing) is live.
    """

    name = "prefetch"
    enable_env = "MXTPU_TUNE_PREFETCH"

    def __init__(self, *, vmin: int = 1, vmax: int = 64,
                 initial: int = 4, low_frac: float = 0.25,
                 high_frac: float = 0.9, ema: float = 0.5,
                 min_batches: int = 8, hysteresis: int = 2, **kw):
        super().__init__(vmin=vmin, vmax=vmax, hysteresis=hysteresis,
                         **kw)
        self.low_frac = float(low_frac)
        self.high_frac = float(high_frac)
        self.ema = float(ema)
        self.min_batches = int(min_batches)
        self._target = int(initial)
        self._depth_ema: Optional[float] = None
        reg = registry()
        self._g_depth = reg.gauge("loader.prefetch_depth")
        self._g_capacity = reg.gauge("loader.prefetch_capacity")
        self._batches = CounterDelta(reg.counter("loader.batches"))

    def current(self) -> float:
        return self._target

    def _clamp(self, v: float) -> int:
        return max(int(self.vmin), min(int(v), int(self.vmax)))

    def decide(self):
        produced = self._batches.take()
        if produced < self.min_batches:
            return None                   # idle pipeline: no evidence
        depth = self._g_depth.value
        if self._depth_ema is None:
            self._depth_ema = depth
        else:
            self._depth_ema = (self.ema * depth
                               + (1 - self.ema) * self._depth_ema)
        t = self._target
        capacity = self._g_capacity.value   # what the gauge CAN reach
        if self._depth_ema > t:
            # a loader sized deeper than our model (constructor
            # prefetch > target, override not yet applied): adopt the
            # observed depth as the baseline rather than throttling a
            # correctly-sized pipeline.  Clamped to the guard rails —
            # an unclamped adopt above vmax would later make a clamped
            # "grow" proposal read as a shrink
            self._target = self._clamp(self._depth_ema)
            return None
        if self._depth_ema <= self.low_frac * t:
            if 0 < capacity < t:
                # an applied target only takes effect at the next
                # __iter__; until the live capacity reaches it, "deep
                # starvation" is just the old small queue still in use
                # — growing again here ratchets straight to the rail
                return None
            return t * 2, (f"queue starving (depth ema "
                           f"{self._depth_ema:.1f} <= {self.low_frac} "
                           f"x {t})")
        from ..gluon.data import dataloader as _dl
        if self._depth_ema >= self.high_frac * t and t > self.vmin \
                and _dl.prefetch_override() is not None:
            return max(self.vmin, t // 2), (
                f"queue pinned at capacity (depth ema "
                f"{self._depth_ema:.1f} >= {self.high_frac} x {t})")
        return None

    def apply(self, value) -> None:
        from ..gluon.data import dataloader as _dl
        self._target = int(value)
        _dl.set_prefetch_override(self._target)


# ---------------------------------------------------------------------------
# BatchWindowController — the PR-7 named follow-up
# ---------------------------------------------------------------------------

class BatchWindowController(Controller):
    """Adapt ``MXTPU_SERVING_BATCH_WINDOW_US`` — how long the serving
    batcher waits for a shape bucket to fill — from the live
    ``serving.queue_depth`` gauge and ``serving.request_us`` p99.

    The window only matters in the middle of the load curve: under
    light load the queue never backs up and every microsecond of window
    is pure added latency — shrink it; under sustained queueing a wider
    window packs fuller batches (higher goodput per dispatch) — widen
    it, but hill-climb on the measured request p99 so a widen that
    made the tail WORSE (depth was batch-starved, not arrival-limited)
    reverses instead of compounding.  The knob is read live per batch
    by the Batcher, so an applied decision reaches a running server on
    its next assembly.
    """

    name = "batch_window"
    knob = "MXTPU_SERVING_BATCH_WINDOW_US"
    enable_env = "MXTPU_TUNE_BATCH_WINDOW"

    def __init__(self, *, vmin: float = 200.0, vmax: float = 20000.0,
                 factor: float = 2.0, min_requests: int = 20,
                 tol: float = 0.05, depth_low: float = 1.0,
                 depth_high: float = 4.0, ema: float = 0.5, **kw):
        super().__init__(vmin=vmin, vmax=vmax, **kw)
        self.factor = float(factor)
        self.min_requests = int(min_requests)
        self.tol = float(tol)
        self.depth_low = float(depth_low)
        self.depth_high = float(depth_high)
        self.ema = float(ema)
        reg = registry()
        self._req = HistogramDelta(reg.histogram("serving.request_us"))
        self._g_depth = reg.gauge("serving.queue_depth")
        self._depth_ema: Optional[float] = None
        self._last_p99: Optional[float] = None
        self._last_dir = 0

    def current(self) -> float:
        return float(get_env("MXTPU_SERVING_BATCH_WINDOW_US"))

    def decide(self):
        depth = self._g_depth.value
        if self._depth_ema is None:
            self._depth_ema = depth
        else:
            self._depth_ema = (self.ema * depth
                               + (1 - self.ema) * self._depth_ema)
        d = self._req.take()
        if d is None or d["count"] < self.min_requests:
            return None
        self._tick_exemplars = exemplar_ids(self._req.hist)
        cur = self.current()
        p99, last_p99 = d["p99"], self._last_p99
        self._last_p99 = p99
        if self._depth_ema < self.depth_low:
            self._last_dir = -1
            return cur / self.factor, (
                f"light load (depth ema {self._depth_ema:.2f} < "
                f"{self.depth_low}): shed window latency")
        if self._depth_ema >= self.depth_high:
            direction = 1
            if self._last_dir > 0 and last_p99 is not None and \
                    p99 > last_p99 * (1 + self.tol):
                direction = -1            # the widen hurt the tail
            self._last_dir = direction
            nxt = cur * self.factor if direction > 0 else \
                cur / self.factor
            return nxt, (f"queued (depth ema {self._depth_ema:.2f} >= "
                         f"{self.depth_high}) p99={p99:.0f}us "
                         f"dir={direction:+d}")
        return None

    def apply(self, value) -> None:
        # a declared-knob write is the sanctioned mutation path (the
        # env-knob lint rejects writes of UNdeclared names); the Batcher
        # reads this knob live per assembled batch
        os.environ["MXTPU_SERVING_BATCH_WINDOW_US"] = repr(float(value))


# ---------------------------------------------------------------------------
# CommBucketController — the overlap tradeoff with a real optimum
# ---------------------------------------------------------------------------

class CommBucketController(Controller):
    """Hill-climb a :class:`~mxnet_tpu.parallel.ShardedTrainer`'s
    ``MXTPU_COMM_BUCKET_MB`` — the gradient reduce-scatter bucket cap —
    on the measured ``resilience.step_us`` interval mean.

    The tradeoff is real in both directions: buckets too LARGE expose
    the collective after backward (no overlap — the serialized phase
    this knob exists to hide); too SMALL and per-collective launch
    overhead dominates and the barrier chain over-constrains the
    scheduler.  The optimum is model- and fabric-dependent, so it is
    searched, not configured: probe upward first (more MB = fewer
    collectives), follow the measured gradient, hold on a plateau.

    Needs a live trainer (``set_comm_bucket_mb`` is an instance
    surface — a cap change rebuilds the jitted step), so it is NOT in
    the stock :func:`~mxnet_tpu.tuning.standard_controllers` set; the
    intervals right after an applied move are discarded
    (``settle_intervals``) because they carry the rebuild's compile,
    which would read as a regression and degenerate the climb into
    oscillation (the BulkSizeController lesson).  Unlike that
    controller (whose apply is a cheap env write), every move here is
    a RECOMPILE — so the climb also carries a bracketing stop: two
    direction reversals mean both neighboring caps measured worse
    than the current one, and the controller parks there instead of
    cycling optimum→neighbor→optimum forever (the plateau hold alone
    cannot catch that cycle: its comparison baseline is always the
    just-regressed neighbor).  It re-arms only when the interval mean
    drifts ``rearm`` above the best score seen — the workload
    actually changed.  Holds while the trainer has bucketing OFF
    (cap 0) — overlap-off is an operator choice the controller must
    not silently reverse."""

    name = "comm_bucket"
    knob = "MXTPU_COMM_BUCKET_MB"
    enable_env = "MXTPU_TUNE_COMM_BUCKET"

    def __init__(self, trainer, *, vmin: float = 0.25, vmax: float = 256.0,
                 factor: float = 2.0, min_steps: int = 8,
                 tol: float = 0.03, settle_intervals: int = 1,
                 rearm: float = 1.25, **kw):
        super().__init__(vmin=vmin, vmax=vmax, **kw)
        self._trainer = trainer
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        self.tol = float(tol)
        self.settle_intervals = int(settle_intervals)
        self.rearm = float(rearm)
        self._step_us = HistogramDelta(
            registry().histogram("resilience.step_us"))
        self._dir = 1
        self._settle = 0
        self._flips = 0      # reversals since the last NEW best score
        self._best: Optional[float] = None
        self._best_cap: float = 0.0
        self._last_score: Optional[float] = None

    def current(self) -> float:
        return float(self._trainer.comm_bucket_mb)

    def on_applied(self, value) -> None:
        self._settle = self.settle_intervals

    def decide(self):
        d = self._step_us.take()
        if d is None or d["count"] < self.min_steps:
            return None
        self._tick_exemplars = exemplar_ids(self._step_us.hist)
        cur = self.current()
        if cur <= 0:
            return None                  # bucketing off: hold (see doc)
        if self._settle > 0:
            # spend the settle credit only on an interval that carried
            # steps at the new cap (the jit-rebuild compile spike)
            self._settle -= 1
            return None
        score = d["mean"]                # step us, interval mean
        new_best = self._best is None or \
            score < self._best * (1 - self.tol)
        if self._best is None or score < self._best:
            self._best = score
            self._best_cap = cur
        if self._flips < 2:
            if self._last_score is None:
                self._last_score = score  # first full interval: probe up
            elif score > self._last_score * (1 + self.tol):
                self._dir = -self._dir   # regressed: turn around
                # an improvement that merely RETURNS to the best does
                # not reset the flip count — only a NEW best does, so
                # an optimum->neighbor->optimum cycle reaches 2 flips
                self._flips += 1
                self._last_score = score
            elif score < self._last_score * (1 - self.tol):
                self._last_score = score  # improved: keep climbing
                if new_best:
                    self._flips = 0       # genuine progress re-arms
            else:
                self._last_score = score  # plateau: converged — hold
                return None
        if self._flips >= 2:
            # bracketed: both neighbors of the best cap measured
            # worse — one final move back to the best, then park
            # there (each move is a recompile) until the workload
            # shifts, read as the mean drifting well above the best
            if score > self._best * self.rearm:
                self._flips = 0
                self._best = score
                self._best_cap = cur
                self._last_score = score
                return None
            if cur != self._best_cap:
                return self._best_cap, (
                    f"bracketed (2 reversals): parking at the best "
                    f"measured cap {self._best_cap:g}MB")
            return None
        nxt = cur * self.factor if self._dir > 0 else cur / self.factor
        return nxt, (f"step mean={score:.0f}us p99={d['p99']:.0f}us "
                     f"steps={d['count']} dir={self._dir:+d}")

    def apply(self, value) -> None:
        self._trainer.set_comm_bucket_mb(float(value))


# ---------------------------------------------------------------------------
# DecodeSlotController — running-batch width for the decode scheduler
# ---------------------------------------------------------------------------

class DecodeSlotController(Controller):
    """Hill-climb a :class:`~mxnet_tpu.serving.GenerationServer`'s
    decode-slot count (the running-batch width of the iteration-level
    scheduler) on measured interval **tokens per second of decode
    time**.

    The tradeoff is real in both directions: too FEW slots and the chip
    decodes a narrow batch while admissible prompts queue (throughput
    left on the floor); too MANY and each step's gather spans a wider
    KV working set, per-step latency grows, and — with slots the
    offered load can't fill — padded rows dilute every step.  The
    optimum depends on model size, KV pool, and traffic, so it is
    searched, not configured.

    Signal: ``Δserving.tokens_generated / Δserving.decode_step_us``
    (counter delta over histogram-total delta) — tokens per second of
    decode-step wall time, wall-clock-free like every controller here,
    and immune to idle gaps between bursts (an interval with fewer
    than ``min_steps`` decode steps holds).

    Every move is a RECOMPILE — a new slot count is a new compiled
    decode signature — so this controller carries the full
    :class:`CommBucketController` discipline: settle intervals discard
    the post-move compile spike, and a **bracketing stop** parks at the
    best measured slot count after two direction reversals (both
    neighbors measured worse), re-arming only when interval tokens/s
    decays below ``1/rearm`` of the best — the traffic actually
    changed.  Needs a live server (``set_decode_slots`` is an instance
    surface), so it is NOT in the stock ``standard_controllers`` set —
    attach it explicitly, gated by ``MXTPU_TUNE_DECODE_SLOTS``."""

    name = "decode_slots"
    knob = "MXTPU_SERVING_DECODE_SLOTS"
    enable_env = "MXTPU_TUNE_DECODE_SLOTS"

    def __init__(self, server, *, vmin: int = 1, vmax: int = 64,
                 min_steps: int = 8, tol: float = 0.03,
                 settle_intervals: int = 1, rearm: float = 1.25, **kw):
        super().__init__(vmin=vmin, vmax=vmax, **kw)
        self._server = server
        self.min_steps = int(min_steps)
        self.tol = float(tol)
        self.settle_intervals = int(settle_intervals)
        self.rearm = float(rearm)
        reg = registry()
        self._step_us = HistogramDelta(
            reg.histogram("serving.decode_step_us"))
        self._tokens = CounterDelta(
            reg.counter("serving.tokens_generated"))
        self._dir = 1
        self._settle = 0
        self._flips = 0      # reversals since the last NEW best score
        self._best: Optional[float] = None
        self._best_slots: int = 0
        self._last_score: Optional[float] = None

    def current(self) -> float:
        return int(self._server.decode_slots)

    def on_applied(self, value) -> None:
        self._settle = self.settle_intervals

    def decide(self):
        d = self._step_us.take()
        tokens = self._tokens.take()
        if d is None or d["count"] < self.min_steps or tokens <= 0:
            return None
        if self._settle > 0:
            # spend the settle credit only on an interval that carried
            # steps at the new width (the compile spike)
            self._settle -= 1
            return None
        self._tick_exemplars = exemplar_ids(self._step_us.hist)
        cur = int(self.current())
        score = tokens / max(d["total"] / 1e6, 1e-9)   # tok/s decode time
        # hill-climb MAXIMIZES here (CommBucket minimizes step time):
        # "regressed" = fewer tokens/s than the last interval
        new_best = self._best is None or \
            score > self._best * (1 + self.tol)
        if self._best is None or score > self._best:
            self._best = score
            self._best_slots = cur
        if self._flips < 2:
            if self._last_score is None:
                self._last_score = score  # first full interval: probe up
            elif score < self._last_score * (1 - self.tol):
                self._dir = -self._dir   # regressed: turn around
                # a recovery that merely RETURNS to the best does not
                # reset the flip count — only a NEW best does, so an
                # optimum->neighbor->optimum cycle reaches 2 flips
                self._flips += 1
                self._last_score = score
            elif score > self._last_score * (1 + self.tol):
                self._last_score = score  # improved: keep climbing
                if new_best:
                    self._flips = 0       # genuine progress re-arms
            else:
                self._last_score = score  # plateau: converged — hold
                return None
        if self._flips >= 2:
            # bracketed: both neighbors of the best width measured
            # worse — park at the best (each move is a recompile)
            # until the traffic shifts, read as interval tokens/s
            # decaying well below the best
            if score < self._best / self.rearm:
                self._flips = 0
                self._best = score
                self._best_slots = cur
                self._last_score = score
                return None
            if cur != self._best_slots:
                return self._best_slots, (
                    f"bracketed (2 reversals): parking at the best "
                    f"measured width {self._best_slots} slots")
            return None
        nxt = cur * 2 if self._dir > 0 else max(1, cur // 2)
        if nxt == cur:
            nxt = cur + self._dir
        return nxt, (f"decode tok/s={score:.0f} "
                     f"step p99={d['p99']:.0f}us steps={d['count']} "
                     f"dir={self._dir:+d}")

    def apply(self, value) -> None:
        self._server.set_decode_slots(int(value))


# ---------------------------------------------------------------------------
# DevicePrefetchController — depth vs HBM
# ---------------------------------------------------------------------------

class DevicePrefetchController(Controller):
    """Adapt the DataLoader device-prefetch depth — how many batches
    stay resident on device beyond the one being consumed — from the
    ``loader.device_put_us`` transfer-dispatch distribution.

    Depth exists to absorb transfer JITTER: if every ``device_put``
    dispatches in uniform time, one buffered batch already hides the
    transfer and each extra slot is pure HBM (a full resident batch).
    A heavy dispatch tail (interval p99 ≫ p50 — host contention,
    sharding layout work, a synchronizing placement fn) means the
    consumer can catch up with the stage during a slow transfer, so
    deeper buffering earns its memory.  The applied target reaches
    every loader at its next ``__iter__`` via
    :func:`~mxnet_tpu.gluon.data.dataloader.set_device_prefetch_override`;
    the ``loader.device_buffer_depth`` gauge is the evidence a target
    is live.  An interval with fewer than ``min_batches`` transfers
    holds — an idle pipeline must not read as smooth and ratchet the
    depth to the floor.  At target 0 (the env knob off) a loader whose
    device stage is nonetheless LIVE — ``device_prefetch=`` passed to
    its constructor, visible as a nonzero buffer-depth gauge — is
    ADOPTED as the baseline (the PrefetchController idiom: observed
    reality beats the controller's model), so constructor-enabled
    pipelines get tuned too; with no live stage anywhere, 0 holds —
    off is an operator choice this controller never reverses.  Note
    the applied override wins over constructor depths at the next
    ``__iter__`` (the same process-wide semantics as the host-side
    prefetch override)."""

    name = "device_prefetch"
    knob = "MXTPU_DEVICE_PREFETCH"
    enable_env = "MXTPU_TUNE_DEVICE_PREFETCH"

    def __init__(self, *, vmin: int = 1, vmax: int = 8,
                 initial: Optional[int] = None,
                 jitter_high: float = 4.0, jitter_low: float = 1.5,
                 min_batches: int = 8, hysteresis: int = 2, **kw):
        super().__init__(vmin=vmin, vmax=vmax, hysteresis=hysteresis,
                         **kw)
        if initial is None:
            initial = int(get_env("MXTPU_DEVICE_PREFETCH"))
        self._target = max(0, int(initial))
        self.jitter_high = float(jitter_high)
        self.jitter_low = float(jitter_low)
        self.min_batches = int(min_batches)
        self._put = HistogramDelta(
            registry().histogram("loader.device_put_us"))
        self._g_depth = registry().gauge("loader.device_buffer_depth")

    def current(self) -> float:
        return self._target

    def decide(self):
        d = self._put.take()
        if d is None or d["count"] < self.min_batches:
            return None
        t = self._target
        if t <= 0:
            live = self._g_depth.value
            if live > 0:
                # a loader enabled via its CONSTRUCTOR is running a
                # device stage the env-seeded target never saw: adopt
                # the observed depth as the baseline so it gets tuned
                self._target = max(int(self.vmin),
                                   min(int(live), int(self.vmax)))
            return None                   # prefetch off (or adopting)
        jitter = d["p99"] / max(d["p50"], 1e-9)
        if jitter >= self.jitter_high:
            return t * 2, (f"transfer dispatch tail heavy (p99/p50 "
                           f"{jitter:.1f} >= {self.jitter_high}): "
                           f"deepen the double buffer")
        if jitter <= self.jitter_low and t > self.vmin:
            return t - 1, (f"transfer dispatch uniform (p99/p50 "
                           f"{jitter:.1f} <= {self.jitter_low}): "
                           f"reclaim a resident-batch slot")
        return None

    def apply(self, value) -> None:
        from ..gluon.data import dataloader as _dl
        self._target = int(value)
        _dl.set_device_prefetch_override(self._target)


# ---------------------------------------------------------------------------
# FleetGatherController — the PR-4 named follow-up
# ---------------------------------------------------------------------------

class FleetGatherController(Controller):
    """Stream the multi-host metric gather on the timer thread.

    PR 4's fleet view refreshes only at checkpoint boundaries because
    ``allgather_bytes`` is a collective — every host must reach it in
    lockstep, and a free-running timer cannot guarantee that.  This
    controller uses the **barrier-free KV-store transport** instead
    (:func:`mxnet_tpu.parallel.dist.kv_publish` / ``kv_collect``): each
    tick *publishes* this host's ``export_state()`` under a
    generation-stamped key and *collects* every peer's newest published
    state — no blocking get, no barrier, no lockstep requirement, so
    hosts may tick at different rates (a peer's view is at most one of
    its ticks stale, tracked by the ``tuning.fleet_gather.hosts``
    gauge).  Collected states feed the same memo the
    ``MXTPU_METRICS_AGGREGATE`` Prometheus endpoint serves, turning the
    fleet view from checkpoint-fresh into timer-fresh.

    Not a knob controller: ``tick()`` is overridden — the "decision" is
    the gather itself (recorded in metrics + the flight tuning ring);
    dry-run publishes and collects but does not install the collected
    view.
    """

    name = "fleet_gather"
    enable_env = "MXTPU_TUNE_FLEET_GATHER"
    _KV_PREFIX = "mxtpu/fleetgather"

    def __init__(self, **kw):
        kw.setdefault("vmin", 0)
        kw.setdefault("vmax", 0)
        super().__init__(**kw)
        self._last_hosts: Optional[Tuple[int, ...]] = None
        self._g_hosts = registry().gauge(
            "tuning.fleet_gather.hosts",
            help="hosts visible in the latest barrier-free fleet "
                 "gather (this host included)")
        self._c_gathers = registry().counter(
            "tuning.fleet_gather.gathers",
            help="timer-thread fleet gathers streamed (every tick; "
                 "`.decisions` counts only membership CHANGES)")

    def current(self) -> float:
        return 0.0

    def tick(self) -> Optional[dict]:
        if not self.enabled:
            return None
        from ..parallel import dist
        if not dist.is_initialized():
            return None
        from ..observability.registry import ingest_host_states
        reg = registry()
        local = reg.export_state()
        dist.kv_publish(self._KV_PREFIX,
                        json.dumps(local).encode("utf-8"))
        blobs = dist.kv_collect(self._KV_PREFIX)
        states: List[Tuple[int, dict]] = sorted(
            (r, json.loads(b.decode("utf-8")))
            for r, b in blobs.items())
        applied = False
        if not self.dry_run and states:
            ingest_host_states(states)
            applied = True
        self._g_hosts.set(len(states))
        self._c_gathers.inc()
        hosts = tuple(r for r, _ in states)
        if hosts == self._last_hosts:
            # steady state: the gather streamed (gauge + counter above)
            # but a per-tick flight record would flood the shared
            # fixed-capacity tuning ring and evict the rare
            # knob-decision records the crash post-mortem exists for —
            # only fleet-membership CHANGES are decisions worth a slot
            return None
        self._last_hosts = hosts
        self._c_decisions.inc()
        if applied:
            self._c_applied.inc()
        decision = {
            "controller": self.name,
            "knob": None,
            # compact string: the flight dump materializer keeps
            # None/bool/int/str and numbers, not lists
            "hosts": ",".join(str(r) for r in hosts),
            "applied": applied,
            "held": False,
            "dry_run": self.dry_run,
            "reason": f"fleet membership now {len(states)} host(s) in "
                      f"the KV-transport gather",
        }
        self._flight.record_tuning(**decision)
        return decision


# ---------------------------------------------------------------------------
# SloController — p99 SLO defense for the multi-model frontend
# ---------------------------------------------------------------------------

class SloController(Controller):
    """Defend per-model p99 latency SLOs on a multi-model host by
    shedding load lowest-priority-first and scaling the violating
    model's dispatch workers — the PR-8 p99-budget knob generalized
    into a closed loop over the PR-18 frontend.

    One registered model per tenant, each carrying a ``priority`` and a
    ``slo_ms`` (see :class:`~mxnet_tpu.serving.registry.ModelRegistry`).
    The controller watches every SLO-carrying model's
    ``serving.model.<name>.request_us`` interval p99 — the
    socket-to-socket latency the frontend observes, i.e. what the
    client experienced, queueing included.  When a model blows its
    budget:

    - **shed** — the controlled scalar is the registry's *shed level*:
      requests for models with priority below it 429 at the door.  The
      level sheds one priority class per tick, lowest first: it rises
      to the rung *above* the lowest not-yet-shed class, capped at the
      highest-priority violator's own priority (the protected model
      itself is never shed), and steps back down one rung after
      ``recover_intervals`` consecutive intervals with every watched
      p99 under ``recover`` × its SLO — but only once the shed
      classes' arrival rate (their 429 counters' interval delta) has
      fallen under ``quiesce`` × its peak.  Watched latency looks
      healthy *because* the shed is holding, so stepping down on
      latency alone just probes the surge back in and oscillates; the
      door counters are the explicit demand signal that says the surge
      actually ended;
    - **scale** — violating predict models get their dispatch-worker
      pool doubled (up to ``workers_max``) via
      :meth:`ModelServer.set_workers`; recovery halves back toward the
      pool size the model started with.  Worker moves are side effects
      reported in the decision reason (dry-run skips them like any
      apply).

    Interval-delta driven and wall-clock-free like every controller
    here: tests tick it against synthetic latency streams.  Per-host
    instance surface (needs the live registry), so NOT in
    ``standard_controllers`` — attach explicitly, gated by
    ``MXTPU_TUNE_SLO``."""

    name = "slo"
    knob = "MXTPU_FRONTEND_SLO_MS"
    enable_env = "MXTPU_TUNE_SLO"

    def __init__(self, model_registry, *, vmin: int = 0,
                 vmax: int = 1 << 20, min_requests: int = 4,
                 recover: float = 0.6, recover_intervals: int = 2,
                 quiesce: float = 0.5, workers_max: int = 8, **kw):
        super().__init__(vmin=vmin, vmax=vmax, **kw)
        self._registry = model_registry
        self.min_requests = int(min_requests)
        self.recover = float(recover)
        self.recover_intervals = int(recover_intervals)
        self.quiesce = float(quiesce)
        self.workers_max = int(workers_max)
        self._deltas: Dict[str, HistogramDelta] = {}
        self._base_workers: Dict[str, int] = {}
        self._good = 0
        self._shed_prev = 0          # registry-wide shed-counter sum
        self._shed_peak = 0          # per-interval peak while level > 0

    def current(self) -> float:
        return int(self._registry.shed_level)

    def _delta(self, entry) -> HistogramDelta:
        d = self._deltas.get(entry.name)
        if d is None:
            d = self._deltas[entry.name] = HistogramDelta(
                entry.h_request)
        return d

    def _scale(self, entry, target: int) -> Optional[str]:
        """Move one model's worker pool (dry-run gated side effect);
        returns a reason fragment when a move happened."""
        server = entry.server
        if entry.kind != "predict" or not hasattr(server,
                                                  "set_workers"):
            return None
        cur = int(server.workers)
        self._base_workers.setdefault(entry.name, cur)
        target = max(self._base_workers[entry.name],
                     min(self.workers_max, target))
        if target == cur:
            return None
        if not self.dry_run:
            server.set_workers(target)
        return f"{entry.name}.workers {cur}->{target}"

    def decide(self):
        # demand signal first: the registry's shed counters tick for
        # every 429'd arrival, so their per-interval delta measures how
        # hard the shed classes are still knocking on the door —
        # re-admitting while that rate is near its peak would only
        # re-violate (the blind-probe oscillation), so recovery waits
        # for it to quiesce
        shed_sum = sum(int(e.c_shed.n)
                       for e in self._registry.entries())
        shed_delta = max(0, shed_sum - self._shed_prev)
        self._shed_prev = shed_sum
        cur = int(self.current())
        if cur > 0:
            self._shed_peak = max(self._shed_peak, shed_delta)
        watched = []
        for e in self._registry.entries():
            d = self._delta(e).take()     # take() every tick: no stale
            if e.slo_ms > 0 and d is not None and \
                    d["count"] >= self.min_requests:
                watched.append((e, d))
        if not watched:
            return None
        ladder = self._registry.priorities()
        violators = [(e, d) for e, d in watched
                     if d["p99"] > e.slo_ms * 1000.0]
        if violators:
            self._good = 0
            worst_e, worst_d = max(
                violators,
                key=lambda t: t[1]["p99"] / (t[0].slo_ms * 1000.0))
            self._tick_exemplars = exemplar_ids(worst_e.h_request)
            # shed lowest-priority-first, one class per tick: find the
            # lowest resident class not yet shed (strictly below the
            # protected violator — it is never shed itself), then raise
            # the level to the NEXT rung so that class 429s
            prot = max(e.priority for e, _ in violators)
            q = next((p for p in ladder if cur <= p < prot), None)
            nxt = cur if q is None else \
                next((p for p in ladder if q < p <= prot), prot)
            moves = [m for m in (self._scale(
                e, int(getattr(e.server, "workers", 0)) * 2)
                for e, _ in violators) if m]
            reason = (f"{worst_e.name} p99={worst_d['p99'] / 1e3:.2f}ms "
                      f"> slo={worst_e.slo_ms:g}ms "
                      f"(n={worst_d['count']})")
            if moves:
                reason += " scaled " + ",".join(moves)
            if nxt != cur:
                return nxt, reason
            # shed level already at the cap: the worker moves above
            # are the whole response this tick
            return None
        if all(d["p99"] < e.slo_ms * 1000.0 * self.recover
               for e, d in watched):
            self._good += 1
            # latency alone is not enough to step the level down — it
            # only looks healthy BECAUSE the shed is holding.  The gate
            # is the demand signal: re-admit once the shed classes'
            # arrival rate has fallen under ``quiesce`` x its peak
            # (_good keeps accumulating while the gate holds, so the
            # step-down lands on the first quiesced tick)
            if self._good >= self.recover_intervals and \
                    (cur == 0 or
                     shed_delta <= self.quiesce * self._shed_peak):
                self._good = 0
                moves = [m for m in (self._scale(
                    e, max(self._base_workers.get(e.name, 1),
                           int(getattr(e.server, "workers", 1)) // 2))
                    for e, _ in watched) if m]
                nxt = max([p for p in ladder if p < cur], default=0) \
                    if cur > 0 else 0
                if nxt == 0:
                    self._shed_peak = 0
                reason = ("all watched p99 < "
                          f"{self.recover:g}x slo for "
                          f"{self.recover_intervals} intervals, shed "
                          f"demand quiesced ({shed_delta}/interval)")
                if moves:
                    reason += " scaled " + ",".join(moves)
                if nxt != cur:
                    return nxt, reason
            return None
        self._good = 0
        return None

    def apply(self, value) -> None:
        self._registry.set_shed_level(int(value))
