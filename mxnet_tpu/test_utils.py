"""Test utilities (reference parity: python/mxnet/test_utils.py, SURVEY.md §4).

The reference's "crown jewels" rebuilt on the TPU stack:
``check_numeric_gradient`` (finite differences vs autograd through the bound
Executor), ``check_symbolic_forward/backward`` (graph vs numpy expectation),
``check_consistency`` (same graph across context/dtype list — the harness
that validated GPU kernels against CPU, here validating TPU against CPU),
``assert_almost_equal`` with per-dtype tolerances, and ``rand_ndarray``.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError, get_env
from .context import Context, cpu, current_context
from .ndarray import NDArray, array as nd_array

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "random_arrays",
           "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency", "simple_forward",
           "default_rtols", "default_atols"]

_default_ctx: Optional[Context] = None

# per-dtype tolerances (reference: test_utils.default_tols)
default_rtols = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
                 np.dtype(np.float64): 1e-7, np.dtype(np.int32): 0,
                 np.dtype(np.int64): 0, np.dtype(np.uint8): 0}
default_atols = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-5,
                 np.dtype(np.float64): 1e-9, np.dtype(np.int32): 0,
                 np.dtype(np.int64): 0, np.dtype(np.uint8): 0}


def _tol(table, dt, fallback):
    """Tolerance lookup that treats bfloat16 like fp16 without importing
    jax at module load (this file is imported from mxnet_tpu/__init__)."""
    if getattr(dt, "name", "") == "bfloat16":
        return 1e-1
    return table.get(dt, fallback)


def default_context() -> Context:
    """Context tests run in; env-switchable like the reference's
    MXNET_TEST_DEFAULT_CTX → the import-and-rerun TPU suite sets tpu(0)."""
    if _default_ctx is not None:
        return _default_ctx
    name = get_env("MXNET_TEST_DEFAULT_CTX")
    if name:
        from . import context as ctx_mod
        dev, _, idx = name.partition("(")
        idx = int(idx.rstrip(")")) if idx else 0
        return getattr(ctx_mod, dev)(idx)
    return current_context()


def set_default_context(ctx: Context) -> None:
    global _default_ctx
    _default_ctx = ctx


def _as_numpy(x) -> np.ndarray:
    """THE host-export boundary of this module: every check here ends
    in a numpy comparison, and every device readback funnels through
    this one call so the sync is deliberate and greppable."""
    if isinstance(x, NDArray):
        return x.asnumpy()  # mxlint: disable=hidden-host-sync — test-utils comparisons are host-side by definition; this is the module's single readback funnel
    return np.asarray(x)


def _find_max_violation(a, b, rtol, atol):
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff - tol
    idx = np.unravel_index(np.argmax(violation), violation.shape)
    return tuple(int(i) for i in idx), diff[idx]


def same(a, b) -> bool:
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol = rtol if rtol is not None else _tol(default_rtols, a.dtype, 1e-5)
    atol = atol if atol is not None else _tol(default_atols, a.dtype, 1e-8)
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False) -> None:
    a, b = _as_numpy(a), _as_numpy(b)
    dt = a.dtype if a.dtype.kind == "f" else np.dtype(np.float32)
    rtol = rtol if rtol is not None else _tol(default_rtols, dt, 1e-5)
    atol = atol if atol is not None else _tol(default_atols, dt, 1e-8)
    if np.allclose(a.astype(np.float64, copy=False),
                   b.astype(np.float64, copy=False),
                   rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    idx, err = _find_max_violation(a.astype(np.float64),
                                   b.astype(np.float64), rtol, atol)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}: "
        f"max violation {err} at index {idx}; "
        f"{names[0]}[{idx}]={a[idx]}, {names[1]}[{idx}]={b[idx]}")


def rand_shape_2d(dim0: int = 10, dim1: int = 10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0: int = 10, dim1: int = 10, dim2: int = 10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(ndim: int, dim: int = 10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype: str = "default", density: float = 1.0,
                 dtype=np.float32, ctx: Optional[Context] = None,
                 scale: float = 1.0):
    """Random NDArray; stype in {'default', 'row_sparse', 'csr'}."""
    ctx = ctx or default_context()
    arr = np.random.uniform(-scale, scale, size=shape).astype(dtype)
    if stype == "default":
        return nd_array(arr, ctx=ctx)
    mask = np.random.uniform(size=shape) < density
    if stype == "row_sparse":
        row_mask = np.random.uniform(size=shape[0]) < density
        arr = arr * row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
        from .sparse import RowSparseNDArray
        return RowSparseNDArray.from_dense(nd_array(arr, ctx=ctx))
    if stype == "csr":
        arr = arr * mask
        from .sparse import CSRNDArray
        return CSRNDArray.from_dense(nd_array(arr, ctx=ctx))
    raise MXNetError(f"unknown stype {stype!r}")


def random_arrays(*shapes, dtype=np.float64) -> List[np.ndarray]:
    arrays = [np.array(np.random.randn(), dtype=dtype) if len(s) == 0
              else np.random.randn(*s).astype(dtype) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def simple_forward(sym, ctx=None, is_train: bool = False, **inputs):
    """Bind + forward a symbol with keyword numpy inputs; return numpy."""
    ctx = ctx or default_context()
    shapes = {k: v.shape for k, v in inputs.items()}
    exe = sym.simple_bind(ctx=ctx, **shapes)
    for k, v in inputs.items():
        exe.arg_dict[k]._set_data(np.asarray(v, dtype=np.float32))
    outs = [_as_numpy(o) for o in exe.forward(is_train=is_train)]
    return outs[0] if len(outs) == 1 else outs


def _parse_location(sym, location, ctx) -> Dict[str, np.ndarray]:
    if isinstance(location, dict):
        missing = set(location) - set(sym.list_arguments())
        if missing:
            raise MXNetError(f"location names {missing} not in arguments")
        return {k: _as_numpy(v) for k, v in location.items()}
    return {k: _as_numpy(v)
            for k, v in zip(sym.list_arguments(), location)}


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None,
                           equal_nan=False) -> None:
    """Forward the graph and compare each output to a numpy expectation."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    exe = sym.simple_bind(
        ctx=ctx, grad_req="null",
        **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        exe.arg_dict[k]._set_data(v.astype(exe.arg_dict[k].dtype))
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k]._set_data(_as_numpy(v))
    outputs = exe.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(_as_numpy(out), exp, rtol, atol,
                            ("forward", "expected"), equal_nan=equal_nan)


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, aux_states=None, grad_req="write",
                            ctx=None, equal_nan=False) -> None:
    """Backward the graph with given head gradients; compare input grads."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    exe = sym.simple_bind(
        ctx=ctx, grad_req=grad_req,
        **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        exe.arg_dict[k]._set_data(v.astype(np.float32))
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k]._set_data(_as_numpy(v))
    exe.forward(is_train=True)
    grads = [nd_array(_as_numpy(g), ctx=ctx) for g in out_grads] \
        if not isinstance(out_grads, dict) else \
        [nd_array(_as_numpy(out_grads[k]), ctx=ctx)
         for k in sym.list_outputs()]
    exe.backward(grads)
    if isinstance(expected, dict):
        expected = {k: _as_numpy(v) for k, v in expected.items()}
    else:
        expected = dict(zip(sym.list_arguments(),
                            [_as_numpy(v) for v in expected]))
    for name, exp in expected.items():
        got = exe.grad_dict[name]
        assert_almost_equal(_as_numpy(got), exp, rtol, atol,
                            (f"grad({name})", "expected"),
                            equal_nan=equal_nan)


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps: float = 1e-3, rtol: float = 1e-2,
                           atol: Optional[float] = None,
                           grad_nodes: Optional[Sequence[str]] = None,
                           ctx=None, dtype=np.float64) -> None:
    """Compare autograd gradients against central finite differences —
    the reference's single most load-bearing numerical check."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    location = {k: v.astype(np.float64) for k, v in location.items()}
    grad_nodes = list(grad_nodes) if grad_nodes else list(location.keys())

    exe = sym.simple_bind(
        ctx=ctx, grad_req="write",
        **{k: v.shape for k, v in location.items()})

    def run_forward(loc: Dict[str, np.ndarray]) -> float:
        for k, v in loc.items():
            exe.arg_dict[k]._set_data(v.astype(np.float32))
        if aux_states:
            for k, v in aux_states.items():
                exe.aux_dict[k]._set_data(_as_numpy(v))
        outs = exe.forward(is_train=True)
        # reduce all outputs with a fixed random projection so a scalar
        # objective exists (reference uses sum via a random head grad of 1s)
        return float(sum(_as_numpy(o).astype(np.float64).sum()
                         for o in outs))

    # analytic grads: forward + backward with all-ones head gradients
    for k, v in location.items():
        exe.arg_dict[k]._set_data(v.astype(np.float32))
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k]._set_data(_as_numpy(v))
    outs = exe.forward(is_train=True)
    exe.backward([nd_array(np.ones(o.shape, np.float32), ctx=ctx)
                  for o in outs])
    analytic = {k: _as_numpy(exe.grad_dict[k]).astype(np.float64)
                for k in grad_nodes}

    for name in grad_nodes:
        base = location[name]
        numeric = np.zeros_like(base, dtype=np.float64)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = run_forward(location)
            flat[i] = orig - numeric_eps
            fm = run_forward(location)
            flat[i] = orig
            num_flat[i] = (fp - fm) / (2 * numeric_eps)
        run_forward(location)  # restore
        assert_almost_equal(
            analytic[name], numeric, rtol, atol if atol is not None else 1e-4,
            (f"autograd({name})", f"finite_diff({name})"))


def check_consistency(sym, ctx_list, scale: float = 1.0,
                      grad_req: str = "write", arg_params=None,
                      rtol=None, atol=None) -> None:
    """Run the same symbol under every (ctx, type_dict) in ctx_list and
    assert outputs and gradients agree — the reference's backend-parity
    harness (GPU-vs-CPU there, TPU-vs-CPU here)."""
    if not ctx_list:
        return
    specs = []
    for spec in ctx_list:
        ctx = spec["ctx"]
        shapes = {k: v for k, v in spec.items()
                  if k not in ("ctx", "type_dict")}
        dtypes = spec.get("type_dict", {})
        specs.append((ctx, shapes, dtypes))

    arg_names = sym.list_arguments()
    _, shapes0, dtypes0 = specs[0]
    if arg_params is None:
        arg_params = {}
        for n in arg_names:
            if n in shapes0:
                arg_params[n] = np.random.normal(
                    size=shapes0[n], scale=scale).astype(np.float64)

    results = []
    for ctx, shapes, dtypes in specs:
        exe = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
        for n, v in arg_params.items():
            dt = dtypes.get(n, np.float32)
            exe.arg_dict[n]._set_data(v.astype(dt))
        outs = exe.forward(is_train=(grad_req != "null"))
        grads = None
        if grad_req != "null":
            exe.backward([nd_array(np.ones(o.shape, np.float32), ctx=ctx)
                          for o in outs])
            grads = {n: _as_numpy(exe.grad_dict[n]) for n in arg_params}
        results.append(([_as_numpy(o) for o in outs], grads,
                        list(dtypes.values()) or [np.float32]))

    ref_outs, ref_grads, _ = results[0]
    for (outs, grads, dts) in results[1:]:
        dt = np.dtype(dts[0]) if dts else np.dtype(np.float32)
        rt = rtol if rtol is not None else _tol(default_rtols, dt, 1e-4)
        at = atol if atol is not None else _tol(default_atols, dt, 1e-5)
        for o, r in zip(outs, ref_outs):
            assert_almost_equal(o.astype(np.float64), r.astype(np.float64),
                                rt, at, ("ctx_out", "ref_out"))
        if grads is not None and ref_grads is not None:
            for n in grads:
                assert_almost_equal(grads[n].astype(np.float64),
                                    ref_grads[n].astype(np.float64),
                                    rt, at, (f"ctx_grad({n})",
                                             f"ref_grad({n})"))
