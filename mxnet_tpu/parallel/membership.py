"""Elastic-fleet membership: heartbeat leases, host-loss detection, and
the automatic re-form protocol (ROADMAP direction #5).

The reference's scale story delegated liveness to ps-lite (the dmlc
tracker restarting dead workers, server-side replication — SURVEY.md
§2.3); the TPU-native stack has no parameter server, and before this
module a dead host simply wedged every survivor inside the next
collective or barrier until the DCN timeout — checkpoint/restart with an
operator watching.  This module is the difference between that and a
fleet that holds an SLO unattended:

- **Leases** — every host publishes a monotonically-advancing heartbeat
  sequence over the same coordination-service KV store the tiered
  collectives already ride (:func:`~mxnet_tpu.parallel.dist.kv_publish`
  gen-stamped keys).  Liveness is judged on the OBSERVER's monotonic
  clock (a lease is dead when its sequence has not advanced for
  ``MXTPU_ELASTIC_LEASE_TTL`` seconds) — no cross-host clock trust.
- **Reaper/watcher** — a daemon thread on every host scans the lease
  table at the heartbeat cadence, flags expired members, notices
  peer-initiated re-form rounds, and detects this host's own fencing.
- **Re-form** — survivors run a deterministic KV consensus round (no
  device collective — the group is broken): each publishes its view of
  the surviving set, the lowest surviving rank leads, computes the
  member intersection, publishes the plan, collects acks, and commits a
  bumped **fencing generation**.  Every survivor then installs the
  narrowed group (:func:`~mxnet_tpu.parallel.dist.set_active_members`:
  new world size, contiguous logical ranks), the leader purges the dead
  hosts' KV generations, and a rejoin barrier over the survivors closes
  the round.
- **Fencing** — the false-death/split-brain case: a host whose
  heartbeat publisher stalled (GC pause, swap storm, the
  ``heartbeat_stall`` fault) but which keeps stepping is excluded by
  the reaper like any dead host.  The committed epoch record carries
  the bumped fence generation and the member list; the stalled host's
  watcher discovers a fence that excludes it and raises
  :class:`HostFenced` — it must exit, not rejoin, because the survivors
  have already re-formed without it and its KV generations were purged.

The supervised-training integration lives in
:class:`~mxnet_tpu.parallel.resilience.ResilientTrainer`: its membership
watcher quiesces at the next step boundary, calls :meth:`reform`,
restores the last committed checkpoint, re-winds the (re-sharded) data
loader, and raises the *recoverable* :class:`FleetReformed` so the
training loop rebuilds its epoch iterator and continues — no operator
action.

Everything here is observable: ``dist.membership.*`` metrics (alive /
world / fence gauges, heartbeat / expired / reform / fenced counters,
re-form latency histogram) and a flight-recorder membership ring
carrying the detect → quiesce → reform → resume timeline into crash
dumps.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

from ..base import MXNetError, get_env
from ..faults import Deadline, DeadlineExceeded
from ..observability import tracing as _tracing
from ..observability.flight import recorder as _flight_recorder
from ..observability.registry import registry as _metrics_registry
from . import dist

__all__ = ["MembershipManager", "LeaseTracker", "ReformResult",
           "FleetReformed", "HostFenced", "FleetLost",
           "MEMBER_PREFIX", "LEASE_PREFIX", "EPOCH_KEY"]

MEMBER_PREFIX = "mxtpu/member"
LEASE_PREFIX = f"{MEMBER_PREFIX}/lease"
# the committed epoch record lives in its OWN directory so the watcher's
# per-tick existence probe dir-gets at most one entry instead of the
# whole member namespace (every lease generation + reform-round key)
EPOCH_DIR = f"{MEMBER_PREFIX}/epoch"
EPOCH_KEY = f"{EPOCH_DIR}/record"
#: per-rank KV namespaces the reaper purges for a dead host, beyond its
#: lease: the allgather generation keys and the fleet metric-gather
#: stream (kv_publish shape) — a dead host's frozen state must never be
#: served to a later collect
PURGE_PREFIXES = ("mxtpu/fleet", LEASE_PREFIX)


class FleetReformed(MXNetError):
    """Recoverable: the fleet lost host(s), the survivors re-formed at
    the new world size, and training state was restored from the last
    committed checkpoint.  Raised at a step boundary by
    ``ResilientTrainer``; catch it at the epoch loop, rebuild the data
    iterator (the shard assignment changed), and continue training."""

    def __init__(self, result: "ReformResult", message: str):
        super().__init__(message)
        self.result = result


class HostFenced(MXNetError):
    """THIS host was declared dead by the surviving fleet (its lease
    expired — real death's twin is a stalled heartbeat publisher on a
    live process) and the membership epoch has moved past it.  The only
    safe action is to exit: the survivors already re-formed without
    this host and purged its KV generations; continuing to step or
    publish would be split-brain."""


class FleetLost(MXNetError):
    """The fleet cannot re-form: the coordination service is gone
    (coordinator host loss is fate-sharing — the KV store dies with
    it), no survivors remain, or the consensus round timed out.
    Unattended recovery is impossible; restart the job and let
    auto-resume pick up the last committed checkpoint."""


class ReformResult(NamedTuple):
    """What one committed re-form round decided."""
    fence: int                      # the bumped fencing generation
    old_members: Tuple[int, ...]    # active set before the round
    members: Tuple[int, ...]        # surviving ORIGINAL process ids
    dead: Tuple[int, ...]           # ranks fenced out by this round
    new_rank: int                   # this host's new contiguous rank
    new_world: int                  # the new world size
    resumed_t: Optional[int] = None  # checkpoint step restored (set by
    #                                 the resilience layer)
    timeline: Tuple = ()            # ((phase, wall_ts), ...) for the
    #                                 flight recorder


class LeaseTracker:
    """Pure lease-expiry accounting on the observer's clock.

    ``observe(rank, seq, now)`` feeds one scan's view of a peer's
    heartbeat sequence; a lease is **expired** when its sequence has not
    advanced for ``ttl`` seconds since the observer last saw it change
    (a peer never seen at all ages from the moment tracking started —
    ``track(rank, now)`` — so a host that dies before its first
    heartbeat is still reaped).  No wall-clock, no cross-host time:
    callers pass ``time.monotonic()`` and tests pass synthetic clocks.
    """

    def __init__(self, ttl: float):
        if ttl <= 0:
            raise MXNetError(f"lease ttl must be > 0, got {ttl}")
        self.ttl = float(ttl)
        self._last: Dict[int, Tuple[Optional[int], float]] = {}

    def track(self, rank: int, now: float) -> None:
        """Start aging ``rank`` (no-op if already tracked)."""
        self._last.setdefault(int(rank), (None, float(now)))

    def forget(self, rank: int) -> None:
        self._last.pop(int(rank), None)

    def observe(self, rank: int, seq: int, now: float) -> bool:
        """Feed one scan's sequence for ``rank``; returns True when the
        lease ADVANCED (fresh heartbeat since the last scan)."""
        rank, seq = int(rank), int(seq)
        prev = self._last.get(rank)
        if prev is not None and prev[0] is not None and seq <= prev[0]:
            return False
        self._last[rank] = (seq, float(now))
        return True

    def age(self, rank: int, now: float) -> Optional[float]:
        """Seconds since ``rank``'s lease last advanced (None if not
        tracked)."""
        entry = self._last.get(int(rank))
        if entry is None:
            return None
        return float(now) - entry[1]

    def expired(self, now: float,
                ranks: Optional[Iterable[int]] = None) -> List[int]:
        """Tracked ranks whose lease has not advanced within ttl."""
        pool = self._last.keys() if ranks is None else \
            [r for r in ranks if r in self._last]
        return sorted(r for r in pool
                      if float(now) - self._last[r][1] > self.ttl)


class MembershipManager:
    """One host's view of fleet membership: heartbeat publisher, lease
    reaper, fence discovery, and the re-form consensus protocol.

    Requires an initialized process group.  ``start()`` publishes the
    first lease synchronously (peers must see this host before its
    first interval elapses) and launches the publisher + watcher
    daemons; ``stop()`` tears both down.  The training-loop surface is
    three calls, all step-boundary cheap:

    - :meth:`raise_if_fenced` — surface this host's own fencing;
    - :attr:`reform_needed` — True once the reaper holds suspects (or a
      peer opened a re-form round);
    - :meth:`reform` — run the consensus round; returns a
      :class:`ReformResult` once the re-formed group is installed.

    ``step_barrier`` is the per-step lockstep sync a dead host breaks
    *quickly*: bounded at ~2 lease TTLs, it raises ``DeadlineExceeded``
    long before ``MXTPU_DIST_TIMEOUT`` would, and the resilience layer
    routes that into a forced lease scan and the re-form arc.
    """

    #: poll cadence inside the re-form round's wait loops
    _POLL_S = 0.05

    def __init__(self, *, lease_ttl: Optional[float] = None,
                 heartbeat_interval: Optional[float] = None,
                 reform_timeout: Optional[float] = None):
        if not dist.is_initialized():
            raise MXNetError(
                "MembershipManager requires an initialized process group "
                "(init_process_group) — leases ride the coordination-"
                "service KV store")
        self.lease_ttl = float(lease_ttl if lease_ttl is not None
                               else get_env("MXTPU_ELASTIC_LEASE_TTL"))
        self.heartbeat_interval = float(
            heartbeat_interval if heartbeat_interval is not None
            else get_env("MXTPU_ELASTIC_HEARTBEAT"))
        self.reform_timeout = float(
            reform_timeout if reform_timeout is not None
            else get_env("MXTPU_ELASTIC_REFORM_TIMEOUT"))
        if self.lease_ttl <= self.heartbeat_interval:
            raise MXNetError(
                f"lease ttl ({self.lease_ttl}s) must exceed the "
                f"heartbeat interval ({self.heartbeat_interval}s) — one "
                f"on-time heartbeat must always keep a lease alive")
        self._phys = dist.phys_rank()
        self._lock = threading.Lock()
        self._members: Tuple[int, ...] = dist.active_members()
        self._fence = dist.fence_generation()
        self._tracker = LeaseTracker(self.lease_ttl)
        now = time.monotonic()
        for r in self._members:
            if r != self._phys:
                self._tracker.track(r, now)
        self._seq = 0
        self._suspects: set = set()
        self._peer_round = False     # a peer opened a re-form round
        self._reform_needed = False
        self._fenced: Optional[str] = None   # reason, once discovered
        self._detect_ts: Optional[float] = None   # wall ts of first suspect
        self._sbar = 0                      # per-fence step-barrier counter
        self._stop = threading.Event()
        self._stall_until: Optional[float] = None   # monotonic; inf=forever
        self._hb_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        reg = _metrics_registry()
        self._c_heartbeats = reg.counter(
            "dist.membership.heartbeats",
            help="lease heartbeats published by this host")
        self._c_expired = reg.counter(
            "dist.membership.expired",
            help="peer leases this host observed expiring")
        self._c_reforms = reg.counter(
            "dist.membership.reforms",
            help="fleet re-form rounds this host committed")
        self._c_fenced = reg.counter(
            "dist.membership.fenced",
            help="times this host discovered it was fenced out")
        self._g_alive = reg.gauge(
            "dist.membership.alive",
            help="peers with fresh leases (this host included)")
        self._g_world = reg.gauge(
            "dist.membership.world",
            help="active logical world size (after re-forms)")
        self._g_fence = reg.gauge(
            "dist.membership.fence",
            help="current membership fencing generation")
        self._h_reform = reg.histogram(
            "dist.membership.reform_us",
            help="wall time of one committed re-form round")
        self._g_dp = reg.gauge(
            "dist.membership.dp_size",
            help="post-re-form data-parallel world size (set when the "
                 "resilience layer re-builds the sharded step at the "
                 "new world)")
        self._h_reshard = reg.histogram(
            "dist.membership.reshard_us",
            help="wall time of the in-graph re-shard after a re-form "
                 "(sharding re-derivation + state re-placement + jit "
                 "rebuild)")
        self._g_alive.set(len(self._members))
        self._g_world.set(len(self._members))
        self._g_fence.set(self._fence)
        self._flight = _flight_recorder()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Publish the first lease and launch the heartbeat + watcher
        daemons (idempotent).  An atexit hook stops them on normal
        interpreter exit: a daemon mid-``kv_publish`` while the jax
        client is being destroyed at teardown is a C++ exception on a
        handlerless thread — ``terminate()``, SIGABRT."""
        if self._hb_thread is not None:
            return
        if not getattr(self, "_atexit_stop", False):
            self._atexit_stop = True
            import weakref
            ref = weakref.ref(self)

            def _stop_daemons():
                mgr = ref()
                if mgr is not None:
                    mgr.stop()

            atexit.register(_stop_daemons)
        self._stop.clear()
        self._publish_lease()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name=f"mxtpu-membership-hb-{self._phys}")
        self._watch_thread = threading.Thread(
            target=self._watch_loop, daemon=True,
            name=f"mxtpu-membership-watch-{self._phys}")
        self._hb_thread.start()
        self._watch_thread.start()

    def stop(self) -> None:
        self._stop.set()
        for t in (self._hb_thread, self._watch_thread):
            if t is not None:
                t.join(timeout=2 * self.heartbeat_interval + 1.0)
        self._hb_thread = None
        self._watch_thread = None

    # -- introspection ------------------------------------------------------
    @property
    def phys_rank(self) -> int:
        return self._phys

    @property
    def members(self) -> Tuple[int, ...]:
        with self._lock:
            return self._members

    @property
    def fence(self) -> int:
        with self._lock:
            return self._fence

    @property
    def reform_needed(self) -> bool:
        with self._lock:
            return self._reform_needed

    @property
    def suspects(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._suspects))

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced is not None

    def raise_if_fenced(self) -> None:
        with self._lock:
            reason = self._fenced
        if reason is not None:
            raise HostFenced(reason)

    def record_reshard(self, dp_size: int, duration_us: float) -> None:
        """Record the in-graph re-shard that followed a committed
        re-form: the resilience layer rebuilds the sharded step at the
        new world size and reports the post-re-form dp size + re-shard
        wall time here, so elastic re-form timelines (metrics AND the
        flight membership ring) show the re-shard step between restore
        and resume."""
        self._g_dp.set(int(dp_size))
        self._h_reshard.observe(float(duration_us))
        self._flight.record_membership(
            event="reshard", ts=round(time.time(), 3),
            dp_size=int(dp_size), reshard_us=round(float(duration_us), 1))

    def _set_fenced(self, reason: str) -> None:
        with self._lock:
            if self._fenced is not None:
                return
            self._fenced = reason
        self._c_fenced.inc()
        self._flight.record_membership(
            event="fenced", ts=round(time.time(), 3), reason=reason)
        # a fenced host's clean jax teardown would run the full-world
        # shutdown barrier and abort the process — detach dirty instead
        _install_dirty_exit()

    # -- fault hook (heartbeat_stall) ---------------------------------------
    def stall_heartbeats(self, seconds: Optional[float] = None) -> None:
        """Freeze the lease publisher (the ``heartbeat_stall`` fault
        site): the process keeps stepping but its lease stops advancing,
        so peers reap it — the false-death/split-brain case the fencing
        generation resolves.  ``seconds=None`` stalls forever."""
        with self._lock:
            self._stall_until = float("inf") if seconds is None \
                else time.monotonic() + float(seconds)

    # -- heartbeat publisher ------------------------------------------------
    def _publish_lease(self) -> None:
        with self._lock:
            self._seq += 1
            payload = {"seq": self._seq, "fence": self._fence}
        dist.kv_publish(LEASE_PREFIX, json.dumps(payload).encode("utf-8"))
        self._c_heartbeats.inc()

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            with self._lock:
                stall = self._stall_until
                if stall is not None and time.monotonic() >= stall:
                    stall = self._stall_until = None
            if stall is not None:
                continue   # fault-injected publisher freeze
            try:
                self._publish_lease()
            except Exception:   # noqa: BLE001 — a failed publish is one
                # missed heartbeat; the next interval retries and the
                # lease only dies after a full TTL of them
                continue

    # -- reaper / watcher ---------------------------------------------------
    def scan(self) -> List[int]:
        """One reaper pass: read every peer's lease, age them on this
        host's monotonic clock, flag expiries, notice peer-initiated
        re-form rounds, and check the epoch record for this host's own
        fencing.  Returns the currently-suspected dead ranks.  Called
        from the watcher daemon every heartbeat interval and forced
        synchronously by the resilience layer when a bounded collective
        times out."""
        now = time.monotonic()
        with self._lock:
            members, fence = self._members, self._fence
        try:
            leases = dist.kv_collect(LEASE_PREFIX)
        except Exception as exc:   # noqa: BLE001 — the store is gone:
            # coordinator death is fate-sharing, surface as FleetLost
            # from reform(); here just report nothing new
            leases = {}
            if not dist.is_initialized():
                raise FleetLost(
                    "membership scan: the process group is gone") from exc
        advanced = set()
        for r, blob in leases.items():
            if r == self._phys or r not in members:
                continue
            try:
                payload = json.loads(blob.decode("utf-8"))
                seq = int(payload["seq"])
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
            if self._tracker.observe(r, seq, now):
                advanced.add(r)
        peers = [r for r in members if r != self._phys]
        dead = self._tracker.expired(now, peers)
        self._check_epoch(members, fence)
        self._check_peer_reform(fence)
        with self._lock:
            # a suspect whose lease ADVANCES again un-suspects: a
            # transient stall shorter than everyone's reform trigger
            # self-heals instead of leaving this host's view diverged
            # from peers that never noticed (two hosts with different
            # monotone suspect sets could otherwise elect two leaders)
            healed = (self._suspects & advanced) - set(dead)
            if healed:
                self._suspects -= healed
                if not self._suspects and not self._peer_round:
                    self._reform_needed = False
                    self._detect_ts = None
            new = set(dead) - self._suspects
            if new:
                self._suspects |= new
                self._reform_needed = True
                if self._detect_ts is None:
                    self._detect_ts = time.time()
            alive = len(members) - len(self._suspects)
        if new:
            self._c_expired.inc(len(new))
            self._flight.record_membership(
                event="suspect", ts=round(time.time(), 3),
                dead=sorted(new), members=list(members), fence=fence)
        self._g_alive.set(alive)
        return sorted(dead)

    def _check_epoch(self, members, fence) -> None:
        """Fence discovery: a committed epoch record with a NEWER fence
        that excludes this host means the fleet re-formed without it."""
        record = _epoch_record()
        if record is None:
            return
        new_fence = int(record.get("fence", 0))
        new_members = [int(m) for m in record.get("members", [])]
        if new_fence <= fence:
            return
        if self._phys not in new_members:
            self._set_fenced(
                f"host (process id {self._phys}) was fenced out at "
                f"generation {new_fence}: the surviving fleet "
                f"{new_members} re-formed without it (its lease expired "
                f"— dead to them, even if this process is still "
                f"running); exit and restart, do not rejoin")

    def _check_peer_reform(self, fence) -> None:
        """A peer that opened a re-form round for the next fence has
        already posted its view — join promptly instead of waiting for
        this host's own reaper to age the dead lease out."""
        try:
            views = _dir_by_rank(f"{MEMBER_PREFIX}/reform/"
                                 f"{fence + 1}/view")
        except Exception:   # noqa: BLE001 — transient store hiccup:
            return          # the next scan retries
        if views:
            with self._lock:
                self._peer_round = True
                self._reform_needed = True
                if self._detect_ts is None:
                    self._detect_ts = time.time()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self.scan()
            except FleetLost:
                return   # nothing left to watch
            except Exception:   # noqa: BLE001 — one failed scan must
                continue        # not kill liveness detection

    # -- the per-step lockstep sync -----------------------------------------
    def step_barrier(self, timeout: Optional[float] = None) -> None:
        """Bounded barrier over the ACTIVE member set at a step
        boundary.  This is the blocking path a dead host breaks *fast*:
        the default timeout is ~2 lease TTLs (long enough that by the
        time it fires the dead host's lease has provably expired, short
        enough that survivors never sit out the full
        ``MXTPU_DIST_TIMEOUT``), and an absent peer raises the typed
        ``DeadlineExceeded`` the resilience layer converts into a
        forced scan + re-form."""
        from jax._src import distributed
        if timeout is None:
            timeout = max(2.0 * self.lease_ttl, 4 * self.heartbeat_interval)
        with self._lock:
            fence, members = self._fence, self._members
            n = self._sbar
            self._sbar += 1
        timeout_ms = max(100, int(timeout * 1000))
        dist._deadline_wait(
            f"membership step_barrier {n} (fence {fence}) over ranks "
            f"{list(members)}", timeout,
            distributed.global_state.client.wait_at_barrier,
            f"mxtpu_step_{fence}_{n}", timeout_ms, list(members))

    # -- the re-form protocol -----------------------------------------------
    def reform(self) -> ReformResult:
        """Run one re-form consensus round over the coordination-service
        KV store and install the surviving group.  EVERY survivor must
        call this (it is fleet-synchronized like a collective — the
        collective-safety lint rule checks reachability); the dead
        host(s) obviously don't, which is why no phase below uses a
        device collective or an all-ranks barrier.

        Round shape (all keys under ``mxtpu/member/reform/<fence+1>``):

        1. **view** — each survivor posts the member set it believes
           alive (own reaper verdict), then waits for a view from every
           rank in its own view, dropping ranks whose lease expires
           while waiting (cascaded death during the round).
        2. **plan** — the leader (lowest surviving rank) intersects the
           posted views (never includes a host any survivor can't see)
           and posts the member list + bumped fence.
        3. **ack/commit** — survivors in the plan ack; once every
           planned member acked, the leader writes the epoch record
           (the durable fence bump a stalled host discovers later) and
           the commit mark; everyone installs the narrowed group via
           ``dist.set_active_members`` and the leader purges the dead
           ranks' KV generations.
        4. **rejoin barrier** — over the NEW member set, so no survivor
           races ahead into a collective before its peers installed.

        Raises :class:`HostFenced` when the plan excludes this host,
        :class:`FleetLost` when the round cannot complete inside
        ``reform_timeout`` or the store is gone.
        """
        self.raise_if_fenced()
        t0 = time.monotonic()
        with self._lock:
            detect_ts = self._detect_ts
        timeline: List[Tuple[str, float]] = []
        if detect_ts is not None:
            timeline.append(("detect", round(detect_ts, 3)))
        timeline.append(("reform_start", round(time.time(), 3)))
        deadline = Deadline(self.reform_timeout)
        me = self._phys
        with self._lock:
            old_members, fence = self._members, self._fence
        fence_next = fence + 1
        base = f"{MEMBER_PREFIX}/reform/{fence_next}"
        # causal tracing: every survivor opens a (never-sampled-away)
        # re-form span and ships its traceparent on a SIDE key next to
        # its posted view (the consensus payloads stay byte-identical
        # to the pre-tracing protocol); once the views are in, everyone
        # re-parents onto the lowest-rank poster's context — a 2-proc
        # re-form stitches into ONE trace through the KV tier, no
        # matter who opened it
        trc = _tracing.tracer()
        tspan = None if not trc.enabled else trc.begin(
            "membership.reform",
            trace_id=_tracing.gen_trace_id(),
            args={"fence": fence_next, "rank": me})
        try:
            views, view_tps = self._exchange_views(base, deadline,
                                                   tspan)
            if tspan is not None and view_tps:
                low = min(view_tps)
                if low < me:
                    tspan.adopt(
                        _tracing.parse_traceparent(view_tps[low]))
            plan = self._plan_round(base, views, fence_next, deadline)
            members = tuple(sorted(int(m) for m in plan["members"]))
            timeline.append(("plan", round(time.time(), 3)))
            if me not in members:
                self._set_fenced(
                    f"host (process id {me}) was excluded by the "
                    f"re-form plan at generation {fence_next} (members "
                    f"{list(members)}): its lease expired from the "
                    f"survivors' view — exit and restart, do not rejoin")
                self.raise_if_fenced()
            self._commit_round(base, members, fence_next, deadline)
        except DeadlineExceeded as exc:
            if tspan is not None:
                tspan.annotate(error="DeadlineExceeded")
                tspan.finish()
            raise FleetLost(
                f"fleet re-form at generation {fence_next} did not "
                f"complete within {self.reform_timeout:.0f}s "
                f"(MXTPU_ELASTIC_REFORM_TIMEOUT): {exc}") from exc
        except Exception:
            if tspan is not None:
                tspan.annotate(error="reform-failed")
                tspan.finish()
            raise
        dead = tuple(sorted(set(old_members) - set(members)))
        # install: the narrowed group is live from here on this host
        dist.set_active_members(members, fence_next)
        with self._lock:
            self._members = members
            self._fence = fence_next
            self._suspects.clear()
            self._peer_round = False
            self._reform_needed = False
            self._detect_ts = None
            self._sbar = 0
        for r in dead:
            self._tracker.forget(r)
        if me == min(members):
            self._purge_dead(dead, fence)
        # rejoin barrier OVER THE NEW SET: every survivor has installed
        # before anyone's next collective
        from jax._src import distributed
        timeout = max(1.0, deadline.remaining())
        try:
            dist._deadline_wait(
                f"re-form rejoin barrier (fence {fence_next})", timeout,
                distributed.global_state.client.wait_at_barrier,
                f"mxtpu_reform_{fence_next}",
                max(1000, int(timeout * 1000)), list(members))
        except DeadlineExceeded as exc:
            if tspan is not None:
                tspan.annotate(error="rejoin-barrier-timeout")
                tspan.finish()
            raise FleetLost(
                f"a survivor never reached the rejoin barrier at "
                f"generation {fence_next}: {exc}") from exc
        timeline.append(("reformed", round(time.time(), 3)))
        if tspan is not None:
            tspan.annotate(members=",".join(str(m) for m in members),
                           dead=",".join(str(d) for d in dead))
            tspan.finish()
        # the original world's shutdown barrier can never complete again
        # — every survivor must detach dirty at exit (see _hard_exit)
        _install_dirty_exit()
        self._c_reforms.inc()
        self._g_world.set(len(members))
        self._g_fence.set(fence_next)
        self._g_alive.set(len(members))
        self._h_reform.observe((time.monotonic() - t0) * 1e6)
        self._flight.record_membership(
            event="reform", ts=round(time.time(), 3), fence=fence_next,
            members=list(members), dead=list(dead),
            new_rank=members.index(me), timeline=list(timeline))
        return ReformResult(
            fence=fence_next, old_members=old_members, members=members,
            dead=dead, new_rank=members.index(me),
            new_world=len(members), timeline=tuple(timeline))

    # -- round phases -------------------------------------------------------
    def _exchange_views(self, base: str, deadline: Deadline,
                        tspan=None):
        """Phase 1: post this host's view, gather every view it is
        waiting on, dropping ranks that die mid-round.

        The view payload stays the bare member list every fleet
        version parses; the causal-tracing traceparent rides a SIDE
        key (``{base}/viewtp/{rank}``) on the same KV tier, so tracing
        can never perturb the consensus and a tp-less (older or
        tracing-off) host simply stitches nothing.  Returns
        ``(views, view_tps)``."""
        me = self._phys
        self.scan()   # freshest possible verdict before voting
        with self._lock:
            view = sorted((set(self._members) - self._suspects) | {me})
        if tspan is not None:
            try:
                _kv_set(f"{base}/viewtp/{me}", tspan.traceparent)
            except Exception:   # noqa: BLE001 — tracing is
                pass            # best-effort; the round decides
        _kv_set(f"{base}/view/{me}", json.dumps(view))
        views: Dict[int, List[int]] = {}
        view_tps: Dict[int, str] = {}
        while True:
            deadline.check("re-form view exchange")
            try:
                posted = _dir_by_rank(f"{base}/view")
            except Exception as exc:   # noqa: BLE001 — store gone
                raise FleetLost(
                    "re-form view exchange: the coordination-service KV "
                    f"store is unreachable ({exc}) — coordinator loss "
                    "is fate-sharing") from exc
            for r, raw in posted.items():
                try:
                    views[r] = [int(x) for x in json.loads(raw)]
                except ValueError:
                    continue
            if all(r in views for r in view):
                if tspan is not None:
                    try:
                        view_tps = _dir_by_rank(f"{base}/viewtp")
                    except Exception:   # noqa: BLE001 — tracing is
                        view_tps = {}   # best-effort
                return ({r: v for r, v in views.items() if r in view},
                        view_tps)
            # a rank in our view may die while we wait: re-scan, shrink
            # the view, re-post so peers stop waiting on our old vote
            self.scan()
            with self._lock:
                shrunk = sorted(
                    (set(view) - self._suspects) | {me})
            if shrunk != view:
                view = shrunk
                _kv_set(f"{base}/view/{me}", json.dumps(view))
            time.sleep(self._POLL_S)

    def _plan_round(self, base: str, views: Dict[int, List[int]],
                    fence_next: int, deadline: Deadline) -> dict:
        """Phase 2: the leader intersects the views and posts the plan;
        everyone (leader included) reads it back from the store — one
        source of truth."""
        me = self._phys
        leader = min(views)
        if me == leader:
            agreed = set(views[leader])
            for v in views.values():
                agreed &= set(v)
            if me not in agreed:
                # every peer's view excludes this host: IT is the
                # false-dead one (a stalled publisher that joined a
                # peer-opened round and, having reaped nobody, elected
                # itself leader).  Authoring a plan here would re-admit
                # a host the fleet already reaped — the exact
                # split-brain fencing exists to prevent.  Fence, never
                # write the plan; the true survivors' leader (the
                # lowest rank every view agrees on) authors it, so the
                # committed plan content is the same no matter which
                # participant computes it.
                self._set_fenced(
                    f"host (process id {me}) is excluded from every "
                    f"peer's re-form view at generation {fence_next}: "
                    f"its lease expired from the survivors' side (a "
                    f"stalled heartbeat publisher reads as death) — "
                    f"exit and restart, do not rejoin")
                self.raise_if_fenced()
            with self._lock:
                old = self._members
            plan = {"fence": fence_next,
                    "members": sorted(agreed),
                    "dead": sorted(set(old) - agreed)}
            _kv_set(f"{base}/plan", json.dumps(plan))
        blob = _kv_await(f"{base}/plan", deadline, "re-form plan")
        return json.loads(blob)

    def _commit_round(self, base: str, members: Tuple[int, ...],
                      fence_next: int, deadline: Deadline) -> None:
        """Phase 3: ack, then (leader) epoch record + commit mark; wait
        for the commit."""
        me = self._phys
        _kv_set(f"{base}/ack/{me}", "1")
        if me == min(members):
            while True:
                deadline.check("re-form ack collection")
                try:
                    acked = set(_dir_by_rank(f"{base}/ack"))
                except Exception:   # noqa: BLE001 — transient read
                    acked = set()
                if all(r in acked for r in members):
                    break
                time.sleep(self._POLL_S)
            _kv_set(EPOCH_KEY, json.dumps(
                {"fence": fence_next, "members": list(members)}))
            _kv_set(f"{base}/commit", "1")
        _kv_await(f"{base}/commit", deadline, "re-form commit")

    def _purge_dead(self, dead: Tuple[int, ...], old_fence: int) -> None:
        """Leader-only, best-effort: delete the dead ranks' lease and
        published-state generations plus the PREVIOUS fence's allgather
        namespace (keys only the old full group could have written), so
        no later collect serves a dead host's frozen payload."""
        for r in dead:
            for prefix in PURGE_PREFIXES:
                try:
                    dist.kv_purge_rank(prefix, r)
                except Exception:   # noqa: BLE001 — purge best-effort
                    continue
            try:
                dist.kv_purge_rank(f"mxtpu/agb/{old_fence}", r)
            except Exception:   # noqa: BLE001 — same
                continue


# -- dirty detach ------------------------------------------------------------
#
# Once the fleet has re-formed (or this host is fenced), the ORIGINAL
# world is permanently degraded: the jax coordination client's normal
# teardown runs a Shutdown barrier over EVERY launcher task, the dead
# one included — the service then marks the barrier failed, propagates
# a fatal error to all remaining tasks, and jax's error-polling thread
# ABORTS each of their processes (SIGABRT) in response.  A survivor
# that trained through a host loss flawlessly would die at exit, and
# its abort would take the other survivors with it.  The only safe
# teardown is to never run that C++ shutdown: flush what matters
# (stdio, in-flight async checkpoint writes), then ``os._exit`` with
# the interpreter's intended status.  Installed automatically by every
# committed re-form and by fence discovery; ``sys.exit`` and unhandled
# exceptions keep their exit codes.

_dirty_exit_lock = threading.Lock()
_dirty_exit_installed = False
_dirty_exit_code = {"code": 0}   # recorded by the sys.exit patch


def _hard_exit(code: int) -> None:
    try:
        # the os._exit below skips threading._register_atexit hooks, so
        # run the resilience layer's checkpoint flush ourselves — a
        # survivor's last async write must still commit
        from .resilience import _exit_flush_trainers
        for tr in list(_exit_flush_trainers or ()):
            tr.wait_checkpoint()
    except Exception:   # noqa: BLE001 — an uncommitted write is
        pass            # skipped by resume's committed-only filter
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:   # noqa: BLE001 — exiting regardless
        pass
    try:
        from jax._src import distributed as _jdist
        if _jdist.global_state.service is not None:
            # this process HOSTS the coordination service: its death
            # severs every peer's fabric mid-RPC, and jax's
            # error-polling thread SIGABRTs a peer whose poll hits the
            # closed socket.  Linger so peers still wrapping up — or a
            # stalled host still discovering its fence — finish with
            # their own clean exit codes first.
            time.sleep(max(0.0, float(get_env(
                "MXTPU_ELASTIC_COORD_LINGER"))))
    except Exception:   # noqa: BLE001 — exiting regardless
        pass
    os._exit(code)


def _install_dirty_exit() -> None:
    global _dirty_exit_installed
    with _dirty_exit_lock:
        if _dirty_exit_installed:
            return
        _dirty_exit_installed = True

    def exit_now(code=0):
        # record the status for the atexit layer, then raise SystemExit
        # like the real sys.exit: the caller's finally blocks and
        # context managers UNWIND normally — only the very last step of
        # interpreter shutdown is replaced by the dirty os._exit
        if code is None:
            _dirty_exit_code["code"] = 0
        elif isinstance(code, int):
            _dirty_exit_code["code"] = code
        else:
            print(code, file=sys.stderr)
            _dirty_exit_code["code"] = 1
        raise SystemExit(code)

    sys.exit = exit_now
    prev_hook = sys.excepthook

    def hook(etype, value, tb):
        prev_hook(etype, value, tb)   # flight-recorder dump chain runs
        _hard_exit(1)

    sys.excepthook = hook
    # normal end-of-script (and the SystemExit path above): atexit
    # hooks run AFTER the threading._register_atexit checkpoint flush,
    # so state is safe by the time this fires (and os._exit skips jax's
    # own atexit hooks, which is the point).  Known caveat: a top-level
    # `raise SystemExit(n)` (instead of the idiomatic sys.exit(n),
    # which is patched above) reaches this hook with no way to read the
    # pending status — it exits 0.
    atexit.register(lambda: _hard_exit(_dirty_exit_code["code"]))


# -- module helpers ----------------------------------------------------------

def _client():
    from jax._src import distributed
    return distributed.global_state.client


def _kv_set(key: str, value: str) -> None:
    _client().key_value_set(key, value, allow_overwrite=True)


def _kv_await(key: str, deadline: Deadline, what: str) -> str:
    """Poll one key with short bounded reads until it appears or the
    round deadline expires (the round-level ``DeadlineExceeded`` is the
    caller's FleetLost signal)."""
    while True:
        deadline.check(what)
        wait_ms = max(50, min(500, int(deadline.remaining() * 1000)))
        try:
            return _client().blocking_key_value_get(key, wait_ms)
        except Exception as exc:   # noqa: BLE001 — DEADLINE_EXCEEDED on
            # this short poll is just 'not yet'; anything else is a
            # store failure worth surfacing
            if "DEADLINE_EXCEEDED" in str(exc):
                continue
            raise FleetLost(
                f"{what}: the coordination-service KV store is "
                f"unreachable ({exc})") from exc


def _dir_by_rank(prefix: str) -> Dict[int, str]:
    """Keys shaped ``{prefix}/{rank}`` → ``{rank: raw_value}`` (the
    re-form round's view/ack namespaces — written with plain overwrite
    sets, unlike the gen-stamped ``kv_publish`` lease shape)."""
    out: Dict[int, str] = {}
    for key, value in _client().key_value_dir_get(prefix):
        try:
            out[int(key.rsplit("/", 1)[1])] = value
        except (ValueError, IndexError):
            continue
    return out


def _epoch_record() -> Optional[dict]:
    """The committed membership epoch record, or None before the first
    re-form.  Non-blocking (a one-entry dir read, not a blocking get)."""
    try:
        for key, value in _client().key_value_dir_get(EPOCH_DIR):
            if key == EPOCH_KEY:
                return json.loads(value)
    except Exception:   # noqa: BLE001 — missing dir / transient store
        return None
    return None
