"""Ring attention: exact long-context attention over the 'sp' mesh axis.

Reference parity: none — the reference (2018-era) predates sequence
parallelism entirely; its long-sequence story is bucketing (SURVEY.md §5.7).
The build mandate makes long-context first-class, so this module provides
the TPU-native mechanism: keys/values are sharded along the sequence axis,
and each step of a `lax.fori_loop` computes one block of scores while
`lax.ppermute` rotates the K/V shards around the ICI ring — compute and
collective overlap, memory stays O(S_local²·heads) instead of O(S²).

Streaming-softmax accumulation (the flash-attention recurrence) keeps the
result exact, not approximate.  Causal masking uses global block offsets so
the rotated blocks mask correctly.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

__all__ = ["ring_attention"]


def _ring_block_attention(q, k, v, axis_name: str, ring_size: int,
                          causal: bool, scale: float):
    """Per-shard body under shard_map.

    q, k, v: (BH, S_local, D) — this device's shards.
    Returns (BH, S_local, D) attention output for the local queries over
    the GLOBAL key/value sequence.
    """
    import jax
    import jax.numpy as jnp

    n = ring_size                           # static ring size
    idx = jax.lax.axis_index(axis_name)     # my position on the ring
    s_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]   # rotate K/V right

    q_pos = idx * s_local + jnp.arange(s_local)           # global q rows
    m0 = jnp.full(q.shape[:-1], -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], dtype=jnp.float32)
    o0 = jnp.zeros(q.shape, dtype=jnp.float32)

    def accumulate(i, o, l, m, k_blk, v_blk):
        # after i rotations we hold the block originally on ring slot idx-i
        blk = (idx - i) % n
        s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = blk * s_local + jnp.arange(s_local)
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard the all-masked rows (exp(-inf - -inf))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bqk,bkd->bqd", p, v_blk.astype(jnp.float32))
        return o, l, m_new

    def body(i, carry):
        o, l, m, k_blk, v_blk = carry
        o, l, m = accumulate(i, o, l, m, k_blk, v_blk)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, l, m, k_blk, v_blk

    # n-1 rotate-and-accumulate rounds, then the final block without the
    # trailing (discarded) ppermute pair
    o, l, m, k, v = jax.lax.fori_loop(0, n - 1, body, (o0, l0, m0, k, v))
    o, l, m = accumulate(n - 1, o, l, m, k, v)
    l = jnp.where(l == 0.0, 1.0, l)         # fully-masked rows -> zeros
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axis: Optional[str] = "dp"):
    """Exact attention with sequence-sharded K/V rotation over ICI.

    q, k, v: (BH, S, D) jax arrays (global sequence length S); S must be
    divisible by the 'seq_axis' mesh size.  Batch stays sharded over
    `batch_axis` (set None if the batch dim is replicated).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map_compat

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(batch_axis, seq_axis, None)
    body = functools.partial(_ring_block_attention, axis_name=seq_axis,
                             ring_size=mesh.shape[seq_axis],
                             causal=causal, scale=scale)
    fn = shard_map_compat(body, mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
