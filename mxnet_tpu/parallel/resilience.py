"""ResilientTrainer: a fault-tolerant supervisor around ShardedTrainer.

Reference parity: the reference delegated fault tolerance to the parameter
server (ps-lite server replication + the dmlc tracker restarting dead
workers — SURVEY.md §2.3).  The TPU-native stack has no parameter server,
so resilience moves into the training loop itself, the way large SPMD jobs
actually survive preemptible TPU pods:

- a **jitted all-finite guard** over loss+grads that skips the optimizer
  update (params/momenta/aux pass through bit-identical) instead of
  corrupting the replicated state with NaN/Inf, optionally decaying a
  dynamic loss scale (trainer.py surgery — the guard lives inside the one
  XLA step so it costs no extra host sync);
- **bounded retry with backoff** on transient step failures;
- **periodic async checkpoints** every N steps with keep-last-K retention
  that only ever prunes *committed* checkpoints (§5.4 "async-writes
  internally");
- **auto-resume** from the newest committed checkpoint — torn dirs left by
  a crash mid-async-write are skipped;
- **SIGTERM/SIGINT preemption handling**: the handler only sets a flag;
  the next step boundary writes a checkpoint, flushes it, and raises
  :class:`TrainingPreempted` (an ``atexit`` hook additionally flushes any
  in-flight async write on interpreter exit);
- **counters** (``steps_skipped``, ``steps_retried``, ``steps_failed``,
  ``rollbacks``, ``checkpoints_written/pruned/failed``, ``resumes``)
  registered in the observability layer as ``resilience.*`` metrics —
  ``ResilientTrainer.counters`` is a back-compat per-instance view;
  step/checkpoint/resume wall-times record as ``resilience.*_us``
  histograms via trace spans.

- **elastic-fleet supervision** (with a
  :class:`~mxnet_tpu.parallel.membership.MembershipManager` attached): a
  membership watcher at every step boundary plus a bounded per-step
  fleet sync, so a lost HOST — not just a failed step — is detected
  within a lease TTL; the survivors then quiesce, run the KV-consensus
  re-form, restore the last committed checkpoint, re-wind the attached
  loader onto the new shard assignment, and raise the *recoverable*
  :class:`~mxnet_tpu.parallel.membership.FleetReformed` for the epoch
  loop to catch and continue — no operator action, no hung collective.

Every failure path is exercisable on CPU through the deterministic fault
plan in :mod:`mxnet_tpu.faults` (``MXTPU_FAULT_PLAN``) — including the
host-level kinds ``host_loss`` (self-SIGKILL at a step) and
``heartbeat_stall`` (silent lease, the false-death case).
"""
from __future__ import annotations

import atexit
import json
import os
import shutil
import signal
import threading
import time
from typing import Optional, Tuple, Type

from ..base import MXNetError, get_env, hot_path
from ..faults import (DeadlineExceeded, FaultPlan, TransientFault,
                      active_plan, retry_call)
from ..observability import tracing as _tracing
from ..observability.flight import recorder as _flight_recorder
from ..observability.registry import registry as _metrics_registry
from ..observability.trace import span as _span
from .membership import FleetReformed, HostFenced, MembershipManager
from .trainer import ShardedTrainer

#: critical-path stages of one supervised step interval, in the order
#: the `step.breakdown.bottleneck` gauge indexes them
BREAKDOWN_STAGES = ("compute", "loader", "device_prefetch", "collective",
                    "ckpt", "other")

# per-process nonce keying single-process step trace ids (a multi-proc
# group keys on the fleet-shared fencing generation instead, so every
# host's step-N spans land in ONE deterministic trace)
_RUN_NONCE = os.urandom(8).hex()

__all__ = ["ResilientTrainer", "TrainingPreempted", "FleetReformed",
           "HostFenced"]


class TrainingPreempted(MXNetError):
    """Raised at a step boundary after SIGTERM/SIGINT, once the
    preemption checkpoint has been written and flushed."""


_exit_flush_trainers = None   # WeakSet, created on first registration


def _register_exit_flush(trainer) -> None:
    """Flush in-flight async checkpoint writes at interpreter exit.

    Plain ``atexit`` is too late: since py3.9, ``concurrent.futures``
    executors are torn down by ``threading._register_atexit`` hooks which
    run BEFORE atexit callbacks — orbax's commit thread then cannot
    schedule its metadata write and the final checkpoint stays torn.
    ``threading._register_atexit`` runs hooks in REVERSE registration
    order, so ours must register AFTER concurrent.futures' — which is
    imported lazily (first orbax save), hence the explicit import below —
    to flush while the writer executors are still alive.  Fall back to
    atexit on interpreters without the private hook.

    ONE process-wide hook over a WeakSet: trainers stay collectable (no
    pinned closures), and repeated ResilientTrainer construction doesn't
    accumulate hooks."""
    global _exit_flush_trainers
    import concurrent.futures.thread   # noqa: F401 — ordering, see above
    import weakref
    if _exit_flush_trainers is None:
        _exit_flush_trainers = weakref.WeakSet()

        def _flush_all():
            for tr in list(_exit_flush_trainers):
                try:
                    tr.wait_checkpoint()
                except Exception:   # noqa: BLE001 — interpreter is
                    # tearing down; a failed flush just leaves an
                    # uncommitted dir, which the committed-checkpoint
                    # filter ignores on resume
                    pass

        try:
            threading._register_atexit(_flush_all)
        except (AttributeError, RuntimeError):
            atexit.register(_flush_all)
    _exit_flush_trainers.add(trainer)


def _poison_first_float(x):
    """Replace the first floating-point input with an all-NaN array of the
    same shape/dtype (the 'nan' fault: a poisoned batch makes loss and
    every gradient non-finite, exercising the skip path end to end)."""
    import numpy as np

    def to_np(v):
        if hasattr(v, "asnumpy"):
            # fault injection: runs only when a 'nan' fault is
            # armed for this exact step — never on the clean path
            # mxlint: disable=hidden-host-sync — fault-only path
            return v.asnumpy()
        # mxlint: disable=hidden-host-sync — same fault-only path
        return np.asarray(v)

    xs = list(x) if isinstance(x, (tuple, list)) else [x]
    for i, v in enumerate(xs):
        a = to_np(v)
        if np.issubdtype(a.dtype, np.floating):
            xs[i] = np.full(a.shape, np.nan, dtype=a.dtype)
            return tuple(xs) if isinstance(x, (tuple, list)) else xs[0]
    raise MXNetError("fault 'nan': no floating-point input to poison "
                     "(all inputs are integer typed)")


class _InstanceCounters:
    """Per-trainer tallies mirrored into the process-global registry.

    ``inc()`` bumps both this instance's own count and the
    ``resilience.<key>`` registry Counter; ``view()`` returns the
    instance's dict.  The double-write keeps the old
    ``ResilientTrainer.counters`` contract exact (strictly per-instance,
    immune to other trainers and to ``registry().reset()``) while the
    registry carries the process-wide totals for exporters."""

    __slots__ = ("_local", "_global")

    def __init__(self, reg, keys):
        self._local = dict.fromkeys(keys, 0)
        self._global = {k: reg.counter(f"resilience.{k}") for k in keys}

    def inc(self, key: str, n: int = 1) -> None:
        self._local[key] += n
        self._global[key].inc(n)

    def view(self) -> dict:
        return dict(self._local)


def _collect_vote_tps(prefix: str):
    """Peers' traceparents for one vote round, from the SIDE namespace
    (``<prefix>_tp``) — the vote payload itself stays the bare ascii
    int every fleet version parses, so tracing can never split the
    agreed flush step; a host that publishes no tp simply stitches
    nothing."""
    from . import dist
    out = {}
    try:
        for r, v in dist.kv_collect(f"{prefix}_tp").items():
            out[int(r)] = v.decode("ascii", "replace")
    except Exception:   # noqa: BLE001 — tracing is best-effort on a
        pass            # possibly-degrading fabric
    return out


def _run_vote_round(prefix: str, own_vote: int, members, timeout: float,
                    poll: float, on_votes=None, trace_parent=None) -> int:
    """THE coordinated-preemption vote protocol — one implementation
    shared by the blocking path (:meth:`ResilientTrainer.
    _coordinate_flush_step` calls it inline) and the async path
    (:class:`_AsyncVoteRound` calls it on a voter thread), so a
    protocol change can never split the agreed flush step between
    async and blocking hosts in a mixed-config fleet.

    Publish ``own_vote`` under ``prefix``, then poll the KV tier until
    every active member has voted; the agreed flush step is
    ``max(votes)``.  Degrades to ``own_vote`` — the unilateral
    pre-coordination flush — when the publish fails (severed KV store:
    exactly the degraded fabric a preemption often rides in on) or the
    ``timeout`` deadline passes with members missing.  ``on_votes``
    observes every successful collect (the async round's known_max
    feed).

    Causal tracing: this host's traceparent rides a SIDE key
    (``<prefix>_tp`` — the vote payload itself stays the bare ascii int
    every fleet version parses, so tracing can never perturb the
    protocol), and the fleet's rounds stitch — the round's span parents
    on ``trace_parent`` (the initiating step's trace; the async path
    captures it before hopping threads), or adopts the lowest-rank
    voter's traceparent when this host joined a PEER's round."""
    from . import dist
    tr = _tracing.tracer()
    sp = None
    t0 = _tracing.now()
    if tr.enabled:
        if trace_parent is None:
            trace_parent = _tracing.current()
        if trace_parent is not None:
            sp = tr.begin("resilience.vote_round", parent=trace_parent,
                          activate=False, t0=t0,
                          args={"vote": own_vote})

    def _finish(agreed: int) -> int:
        if sp is not None:
            sp.annotate(agreed=agreed)
            sp.finish()
        return agreed

    if sp is not None:
        try:
            dist.kv_publish(f"{prefix}_tp",
                            sp.traceparent.encode("ascii"))
        except Exception:   # noqa: BLE001 — tracing is best-effort;
            pass            # the vote below decides what matters
    try:
        dist.kv_publish(prefix, str(own_vote).encode("ascii"))
    except Exception:   # noqa: BLE001 — degrade, never lose the
        return _finish(own_vote)  # preemption checkpoint
    members = set(members)
    deadline = time.monotonic() + float(timeout)
    poll = max(0.005, float(poll))
    tp_probes = 3   # bounded: no peer publishing a tp (e.g. the step
    # was unsampled fleet-wide) must not cost an extra KV dir-get on
    # EVERY poll of a round riding an already-degrading fabric
    while True:
        votes = {}
        try:
            for r, v in dist.kv_collect(prefix).items():
                votes[int(r)] = int(v.decode("ascii"))
        except Exception:   # noqa: BLE001 — transient KV failure:
            votes = {}      # retry until the deadline
        if sp is None and tr.enabled and tp_probes > 0:
            # joined a peer-initiated round with no trace of our own:
            # adopt the lowest-rank voter's context so the whole
            # fleet's round lands in ONE trace
            tp_probes -= 1
            tps = _collect_vote_tps(prefix)
            if tps:
                ctx = _tracing.parse_traceparent(tps[min(tps)])
                if ctx is not None:
                    sp = tr.begin("resilience.vote_round", parent=ctx,
                                  activate=False, t0=t0,
                                  args={"vote": own_vote})
        if on_votes is not None and votes:
            on_votes(votes)
        if members <= set(votes):
            _metrics_registry().counter(
                "resilience.preempt_coordinated",
                help="preemption rounds that agreed a fleet-wide "
                     "flush step over the KV tier").inc()
            return _finish(max(votes[r] for r in members))
        if time.monotonic() > deadline:
            return _finish(own_vote)
        time.sleep(poll)


class _AsyncVoteRound:
    """Background runner for :func:`_run_vote_round`
    (``MXTPU_ASYNC_CKPT``): the same protocol, on its OWN thread, so
    the step path never blocks in the vote wait the way
    :meth:`ResilientTrainer._coordinate_flush_step` does.

    Consistency argument (why hosts may keep stepping while the round
    is open): a host only steps while its update counter is strictly
    below ``known_max`` — the highest vote it has OBSERVED so far.
    Since ``known_max`` never exceeds the final agreed step (the max
    over ALL votes), no host can overshoot the agreement; once every
    active member has voted, everyone steps up to exactly
    ``max(votes)`` and commits the SAME ``state-<t>`` — the PR-10
    invariant, minus the initiator parking while peers catch up."""

    def __init__(self, prefix: str, own_vote: int, members, timeout: float,
                 poll: float):
        self.own_vote = int(own_vote)
        self.known_max = int(own_vote)   # monotone int store (GIL-atomic)
        self.agreed: Optional[int] = None
        self.resolved = threading.Event()
        self._poll = max(0.005, float(poll))
        # the contextvar does not cross the voter-thread hop: capture
        # the initiating step's trace context HERE (construction runs
        # on the stepping thread) so the round's span joins its trace
        parent = _tracing.current()

        def run():
            self.agreed = _run_vote_round(
                prefix, self.own_vote, members, timeout, self._poll,
                on_votes=lambda votes: setattr(
                    self, "known_max",
                    max(self.known_max, max(votes.values()))),
                trace_parent=parent)
            self.resolved.set()

        self._thread = threading.Thread(
            target=run, name="mxtpu-preempt-vote", daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        """Reap the voter thread.  ``resolved.set()`` is its final
        statement, so after the event fires this returns ~immediately;
        the flush boundary calls it so the round never leaves a zombie
        racing interpreter teardown."""
        self._thread.join(timeout)


class ResilientTrainer:
    """Wrap a :class:`ShardedTrainer` with failure handling.

    Parameters
    ----------
    trainer : ShardedTrainer — must not be built yet if ``skip_nonfinite``
        needs to switch the guard on (the guard changes the jitted step).
    checkpoint_dir : str — where periodic/preemption checkpoints land.
    checkpoint_every : int — save every N supervisor steps (0 = only on
        preemption / explicit :meth:`checkpoint` calls).
    keep_last : int — retention: prune committed checkpoints beyond the
        newest K (clamped to >= 1; the newest committed one is never
        deleted).
    max_retries : int — bounded retries per step on ``retry_on`` failures.
    retry_on : tuple of exception types treated as transient.
    fault_plan : FaultPlan | str | None — deterministic fault injection;
        ``None`` uses the process-global plan (``MXTPU_FAULT_PLAN``).
    auto_resume : bool — on the first step, restore the newest committed
        checkpoint under ``checkpoint_dir`` if one exists.
    skip_nonfinite : bool — enable the in-graph all-finite guard.
    dynamic_loss_scale : bool — carry a loss scale in the step (decayed on
        skipped steps, grown after ``scale_growth_interval`` clean steps).
    membership : MembershipManager — an (already started) elastic-fleet
        membership layer; the supervisor then watches it at every step
        boundary, runs a per-step bounded fleet sync, and on host loss
        quiesces → re-forms → restores the last committed checkpoint →
        raises the recoverable :class:`FleetReformed`.
    elastic : bool — convenience: build and start a default
        ``MembershipManager`` (requires an initialized process group).
    loader : DataLoader — attach the data pipeline so its position
        cursor rides every checkpoint (sidecar ``loader-<t>.json``) and
        resume/re-form re-winds it on the current shard assignment.
    fleet_sync_every : int — run the bounded per-step fleet barrier
        every N supervised steps (default 1: full lockstep).  Each sync
        is a coordination-service round trip serialized on the slowest
        host; jobs with millisecond steps can raise N — host-loss
        detection only needs to beat the lease TTL (seconds), which the
        watcher provides regardless, so a larger N trades in-band
        detection latency for per-step overhead.
    """

    def __init__(self, trainer: ShardedTrainer, *,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0,
                 keep_last: int = 3,
                 max_retries: int = 3,
                 retry_base_delay: float = 0.05,
                 retry_max_delay: float = 2.0,
                 retry_on: Tuple[Type[BaseException], ...] =
                 (TransientFault,),
                 fault_plan=None,
                 auto_resume: bool = True,
                 skip_nonfinite: bool = True,
                 dynamic_loss_scale: bool = False,
                 init_loss_scale: float = 2.0 ** 15,
                 scale_growth_interval: int = 2000,
                 scale_backoff: float = 0.5,
                 membership: Optional[MembershipManager] = None,
                 elastic: bool = False,
                 loader=None,
                 fleet_sync_every: int = 1):
        if not isinstance(trainer, ShardedTrainer):
            raise MXNetError(
                f"ResilientTrainer wraps a ShardedTrainer, got "
                f"{type(trainer).__name__}")
        self._trainer = trainer
        self._ckpt_dir = os.path.abspath(checkpoint_dir) \
            if checkpoint_dir else None
        self._every = int(checkpoint_every)
        self._keep_last = max(1, int(keep_last))
        self._max_retries = int(max_retries)
        self._retry_base = float(retry_base_delay)
        self._retry_max = float(retry_max_delay)
        self._retry_on = tuple(retry_on)
        if isinstance(fault_plan, str):
            fault_plan = FaultPlan(fault_plan)
        self._plan = fault_plan if fault_plan is not None else active_plan()
        self._auto_resume = bool(auto_resume)
        if skip_nonfinite and not trainer.guard_enabled:
            trainer.enable_nonfinite_guard(
                dynamic_loss_scale=dynamic_loss_scale,
                init_loss_scale=init_loss_scale,
                scale_growth_interval=scale_growth_interval,
                scale_backoff=scale_backoff)
        # counters live in the process-global observability registry
        # under `resilience.*` (the PR-1 follow-up: one surface with the
        # engine's dispatch counters).  Each instance ALSO keeps its own
        # tallies: `counters` must stay genuinely per-instance (two
        # trainers in one process must not see each other's skips, and a
        # registry reset must not send a view negative), so every bump
        # writes both.
        self._metrics = _InstanceCounters(
            _metrics_registry(),
            ("steps_skipped", "steps_retried", "steps_failed",
             "rollbacks", "checkpoints_written", "checkpoints_pruned",
             "checkpoints_failed", "resumes", "fleet_reforms"))
        reg = _metrics_registry()
        self._g_loss_scale = reg.gauge(
            "resilience.loss_scale",
            help="current (dynamic) loss scale — refreshed at sync "
                 "points (skip-flag drains, checkpoints), not per step")
        self._g_loss_scale.set(trainer.loss_scale if trainer.built
                               else (init_loss_scale if dynamic_loss_scale
                                     else 1.0))
        # flight-recorder plumbing: per-step records ride the supervised
        # step; dumps fire from the preemption and retry-exhaustion
        # paths below (plus the process-wide excepthook installed here)
        self._flight = _flight_recorder()
        self._flight.install()
        self._h_flush = reg.histogram("engine.flush_us")
        self._c_skipped = reg.counter("resilience.steps_skipped")
        self._c_rollbacks = reg.counter("resilience.rollbacks")
        self._g_loader_depth = reg.gauge("loader.prefetch_depth")
        self._step_unsafe = False     # set once a failed attempt consumed
        # its donated buffers: every later step refuses fast
        self._pending_finite: list = []
        self._step_index = 0          # supervisor step counter (fault site)
        self._save_index = 0          # checkpoint-write counter (fault site)
        self._last_saved_t = None
        self._preempt_signum: Optional[int] = None
        self._preempt_flush_t: Optional[int] = None
        self._prev_handlers: dict = {}
        self._resume_checked = False
        self.resumed_t: Optional[int] = None
        # elastic fleet: the membership watcher consulted at every step
        # boundary (host loss -> quiesce/re-form/resume arc)
        if elastic and membership is None:
            membership = MembershipManager()
            membership.start()
        self._membership = membership
        self._fleet_sync_every = max(1, int(fleet_sync_every))
        self._loader = None
        self.attach_loader(loader)
        self._g_ckpt_inflight = reg.gauge("resilience.ckpt_inflight")
        self._vote_round: Optional[_AsyncVoteRound] = None
        # causal tracing + critical-path attribution: the step ROOT
        # span covers boundary-to-boundary wall time (previous step's
        # exit to this step's exit — the interval a training loop
        # actually experiences, loader wait included), decomposed into
        # the child-span segments below.  `resilience.step_us` keeps
        # its body-only semantics (the CommBucketController's signal).
        self._boundary_pc: Optional[float] = None
        self._h_step_wall = reg.histogram(
            "resilience.step_wall_us",
            help="boundary-to-boundary supervised-step wall time "
                 "(loader wait + step body + checkpoint/collective "
                 "work); carries trace-id exemplars when causal "
                 "tracing is on — the p99 bucket points at real step "
                 "traces")
        self._g_breakdown = {
            s: reg.gauge(
                f"step.breakdown.{s}_us",
                help=f"last step's '{s}' share of the "
                     f"boundary-to-boundary wall time (critical-path "
                     f"attribution)")
            for s in BREAKDOWN_STAGES}
        self._g_bottleneck = reg.gauge(
            "step.breakdown.bottleneck",
            help="dominant stage of the last step's wall time, as an "
                 "index into (compute, loader, device_prefetch, "
                 "collective, ckpt, other) — the one-number answer to "
                 "'why is this step slow'")
        # live introspection: heartbeat for the progress watchdog
        # (thresholded on step_wall's recent p99 — a stalled loader or
        # wedged collective goes silent between beats), sampler opt-in,
        # and the manual SIGQUIT stack-dump probe
        from ..observability.sampler import maybe_start_from_env as \
            _maybe_start_sampler
        from ..observability.watchdog import (install_stack_signal,
                                              touchpoint as _touchpoint)
        self._tp_step = _touchpoint("resilience.step",
                                    hist="resilience.step_wall_us")
        _maybe_start_sampler()
        install_stack_signal()
        # interpreter-exit fallback: an in-flight async write must commit
        # even if the loop never reaches another step boundary
        _register_exit_flush(trainer)

    # -- introspection -----------------------------------------------------
    @property
    def trainer(self) -> ShardedTrainer:
        return self._trainer

    @property
    def membership(self) -> Optional[MembershipManager]:
        return self._membership

    def attach_loader(self, loader) -> None:
        """Attach (or replace) the data pipeline whose position cursor
        rides the checkpoint payload.  Also wires the loader's
        device-prefetch stage (if it has one and no custom placement
        was set) to this trainer's sharding-aware ``place_batch``, so
        ``MXTPU_DEVICE_PREFETCH`` double-buffers batches directly onto
        the dp mesh instead of the default device."""
        self._loader = loader
        if loader is not None and \
                getattr(loader, "device_put_fn", True) is None and \
                hasattr(loader, "set_device_put_fn"):
            loader.set_device_put_fn(self._trainer.place_batch)

    @property
    def loss_scale(self) -> float:
        return self._trainer.loss_scale

    @property
    def preempted(self) -> bool:
        return self._preempt_signum is not None

    def _drain_finite(self) -> None:
        if not self._pending_finite:
            return
        import jax
        flags = jax.device_get(self._pending_finite)
        self._pending_finite = []
        skipped = sum(1 for f in flags if not bool(f))
        if skipped:
            self._metrics.inc("steps_skipped", skipped)
        # already syncing the device here — refresh the loss-scale gauge
        # on the same boundary so exporters/flight records see a value
        # at most one drain stale, without a per-step device_get
        if self._trainer.guard_enabled:
            self._g_loss_scale.set(self._trainer.loss_scale)

    @property
    def counters(self) -> dict:
        """Snapshot of THIS trainer's resilience counters (resolves any
        pending device-side skip flags — may sync).  Strictly
        per-instance, as before the observability subsystem; every bump
        is mirrored into the process-global `resilience.*` registry
        counters (``observability.registry().snapshot()`` has the
        totals)."""
        self._drain_finite()
        return self._metrics.view()

    # -- signals -----------------------------------------------------------
    def install_signal_handlers(
            self, signals=(signal.SIGTERM, signal.SIGINT)) -> None:
        """Route SIGTERM/SIGINT through checkpoint-and-raise at the next
        step boundary.  Main thread only (a CPython constraint)."""
        if threading.current_thread() is not threading.main_thread():
            raise MXNetError("signal handlers can only be installed from "
                             "the main thread")
        for s in signals:
            self._prev_handlers[s] = signal.signal(s, self._on_signal)

    def uninstall_signal_handlers(self) -> None:
        for s, h in self._prev_handlers.items():
            signal.signal(s, h)
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame) -> None:
        # async-signal-safe: only set a flag; all real work happens at the
        # next step boundary on the main thread
        self._preempt_signum = signum

    def _flush_and_raise(self) -> None:
        signum = self._preempt_signum
        cause = f"signal {signum}" if signum is not None else \
            "a peer's preemption (coordinated flush)"
        # the run is about to end: leave the postmortem dump next to the
        # preemption checkpoint BEFORE the (fallible) save below
        self._flight.dump(f"preempted by {cause}")
        save_err = None
        try:
            if self._ckpt_dir is not None and self._trainer.built and \
                    self._last_saved_t != self._trainer.num_update:
                self.checkpoint(wait=True)
        except Exception as exc:   # noqa: BLE001 — reported below; the
            # preemption signal must NEVER escape as a retryable fault
            save_err = exc
        try:
            self._trainer.wait_checkpoint()
        except Exception as exc:   # noqa: BLE001 — same: report, not mask
            save_err = save_err or exc
        where = f" (flushed to {self._ckpt_dir})" if self._ckpt_dir else ""
        if save_err is not None:
            raise TrainingPreempted(
                f"training preempted by {cause}; the preemption "
                f"checkpoint FAILED ({save_err!r}) — resume will use the "
                f"last committed checkpoint") from save_err
        raise TrainingPreempted(
            f"training preempted by {cause}{where}")

    # -- coordinated preemption (multi-process) -----------------------------
    #
    # A fleet whose hosts each flush "at the next step boundary" commits
    # DIFFERENT state-<t> dirs (SIGTERM lands at different wall times on
    # different hosts) — resume would then mix steps across hosts.  In a
    # multi-process group the preemption flush is therefore agreed over
    # the bounded coordination-service KV tier (no device collective —
    # the fabric may already be degrading when the preemption arrives):
    # every host that sees a preemption (its own SIGTERM, or a peer's
    # vote in the KV store) publishes its current update counter as a
    # VOTE and waits (bounded poll, no lockstep) until every active
    # member has voted; the agreed flush step is max(votes).  A host
    # already at the agreed step checkpoints and raises; a host behind
    # it keeps stepping until its counter reaches the agreed step, so
    # every host commits the SAME `state-<t>` (the oldest carried
    # follow-up, PR 1).  The vote wait is bounded by MXTPU_DIST_TIMEOUT:
    # an unreachable peer degrades to the old unilateral flush rather
    # than wedging the shutdown.

    def _preempt_prefix(self) -> str:
        from . import dist
        return f"mxtpu/preempt/{dist.fence_generation()}"

    def _preempt_coord_on(self) -> bool:
        if not bool(get_env("MXTPU_PREEMPT_COORD")):
            return False
        from . import dist
        return dist.is_initialized() and dist.num_workers() > 1

    def _peer_preempt_pending(self) -> bool:
        """A peer has opened a preemption round (its vote is in the KV
        store).  Barrier-free read; checked at every step boundary in a
        multi-process group."""
        if not self._preempt_coord_on():
            return False
        from . import dist
        try:
            # kv_collect is a coordination-service RPC (host<->
            # coordinator TCP), not a device readback — nothing here
            # touches the async engine
            # mxlint: disable=hidden-host-sync — KV RPC, no device sync
            return bool(dist.kv_collect(self._preempt_prefix()))
        except Exception:   # noqa: BLE001 — a degraded KV read must not
            return False    # fail the step; the local signal still flushes

    def _coordinate_flush_step(self) -> int:
        """Publish this host's vote (its current update counter) and
        wait — bounded, INLINE (this host does not step while the
        round is open; MXTPU_ASYNC_CKPT moves the same protocol onto
        a voter thread instead) — for every active member's; the
        agreed flush step is the max.  Falls back to this host's own
        counter (the unilateral pre-coordination behavior) when peers
        never arrive within MXTPU_DIST_TIMEOUT."""
        from . import dist
        return _run_vote_round(
            self._preempt_prefix(), self._trainer.num_update,
            dist.active_members(),
            float(get_env("MXTPU_DIST_TIMEOUT")),
            float(get_env("MXTPU_PREEMPT_POLL")))

    def _preempt_pending(self) -> bool:
        return (self.preempted or self._preempt_flush_t is not None or
                self._vote_round is not None or
                self._peer_preempt_pending())

    def _preempt_round_open(self) -> bool:
        """A coordinated flush is agreed or being agreed — the window
        in which the per-step fleet barrier is skipped (peers are in
        vote waits, not barriers)."""
        return (self._preempt_flush_t is not None or
                self._vote_round is not None)

    def _preempt_boundary(self) -> None:
        """The step-boundary preemption surface.  Single-process (or
        coordination off): checkpoint-and-raise immediately, exactly the
        pre-coordination behavior.  Multi-process: agree on one flush
        step, then flush only once this host's counter reaches it.

        With ``MXTPU_ASYNC_CKPT`` the vote wait moves to a background
        thread (:class:`_AsyncVoteRound`): this boundary RETURNS —
        keep stepping — while the round is unresolved and this host's
        counter is below the highest vote seen, so the initiator
        catches up toward the agreement instead of parking while its
        peers do (see the round's consistency argument)."""
        if self._preempt_flush_t is None:
            if not self._preempt_coord_on():
                self._flush_and_raise()
            if bool(get_env("MXTPU_ASYNC_CKPT")):
                from . import dist
                if self._vote_round is None:
                    self._vote_round = _AsyncVoteRound(
                        self._preempt_prefix(),
                        self._trainer.num_update,
                        dist.active_members(),
                        float(get_env("MXTPU_DIST_TIMEOUT")),
                        float(get_env("MXTPU_PREEMPT_POLL")))
                r = self._vote_round
                # check BEFORE waiting: a host behind the highest
                # known vote must step immediately, not after a poll
                # sleep (this boundary runs twice per step — a
                # leading sleep would throttle the very catch-up the
                # async round exists for and could blow peers' vote
                # deadlines); park only when caught up, and a new
                # higher vote arriving mid-park resumes stepping
                while not r.resolved.is_set():
                    if self._trainer.num_update < r.known_max:
                        return
                    r.resolved.wait(r._poll)
                self._preempt_flush_t = r.agreed
                r.join(timeout=r._poll)  # resolved ⇒ exiting; reap it
            else:
                self._preempt_flush_t = self._coordinate_flush_step()
        if self._trainer.num_update >= self._preempt_flush_t:
            self._flush_and_raise()

    # -- resume ------------------------------------------------------------
    def maybe_resume(self, x, y, batch_size: Optional[int] = None):
        """Restore the newest *committed* checkpoint under
        ``checkpoint_dir`` if one exists.  Returns the restored update
        counter, or None.  An unbuilt trainer is built first with one
        probe step on (x, y) — its effect is entirely overwritten by the
        restore (params, optimizer state, update counter, RNG stream)."""
        self._resume_checked = True
        if self._ckpt_dir is None:
            return None
        path = ShardedTrainer.latest_checkpoint(self._ckpt_dir)
        if path is None:
            return None
        with _span("resilience.resume_us"):
            if not self._trainer.built:
                self._trainer.step(x, y, batch_size)
            self._trainer.load_checkpoint(self._ckpt_dir)
        self.resumed_t = self._trainer.num_update
        self._last_saved_t = self.resumed_t
        self._restore_loader_cursor(self.resumed_t)
        self._metrics.inc("resumes")
        return self.resumed_t

    # -- the supervised step ----------------------------------------------
    @hot_path("step")
    def step(self, x, y, batch_size: Optional[int] = None):
        """One supervised train step: auto-resume (first call), fault
        injection, bounded retry, skip accounting, preemption handling,
        periodic checkpointing.  Returns the (device) mean loss —
        NaN on a skipped step, with params untouched."""
        if self.preempted or self._preempt_round_open():
            # local-state check only — the peer-vote KV probe runs ONCE
            # per step (at the end-of-step boundary below); a vote
            # landing mid-step is caught one boundary later, and the
            # hot path never pays two dir_get RPCs per step
            self._preempt_boundary()
        if self._auto_resume and not self._resume_checked:
            self.maybe_resume(x, y, batch_size)
        # watchdog heartbeat at step ENTRY: a loader stalled between
        # steps (the epoch loop blocked in next(loader)) keeps this
        # silent — exactly the hang the postmortem must catch
        self._tp_step.beat()
        self._step_index += 1
        i = self._step_index
        plan = self._plan
        if plan is not None:
            self._fire_host_faults(i, plan)
        if self._membership is not None:
            # the membership watcher's step-boundary surface: this
            # host's own fencing first, then any pending re-form
            self._membership.raise_if_fenced()
            if self._membership.reform_needed:
                self._reform_and_resume(i)
        # causal tracing + critical-path attribution: drain the
        # attached loader's pending consume-wait (it happened BETWEEN
        # steps, on the epoch loop) into the breakdown, then open the
        # step's deterministic trace root — every host in a lockstep
        # fleet derives the SAME trace id for step i, so cross-host
        # step traces stitch with zero communication
        seg = dict.fromkeys(BREAKDOWN_STAGES, 0.0)
        lw = None
        if self._loader is not None and \
                hasattr(self._loader, "consume_trace"):
            lw = self._loader.consume_trace()
            seg["device_prefetch"] = lw["device_put_us"]
            seg["loader"] = max(0.0, lw["wait_us"] - lw["device_put_us"])
        root = self._begin_step_trace(i)

        def one_attempt():
            if self._step_unsafe:
                # a previous attempt died AFTER its donated buffers were
                # consumed: params/opt state no longer exist on device —
                # retrying would crash on deleted arrays, so refuse with
                # the recovery path spelled out (ROADMAP 'Known gap').
                # A flag, not a per-attempt donation_consumed scan — the
                # happy path must not pay an O(n_params) check.
                raise MXNetError(
                    "ResilientTrainer: a failed step consumed its donated "
                    "parameter buffers — the live training state is gone "
                    "and the step cannot be retried; restore from the "
                    "newest committed checkpoint (auto_resume / "
                    "maybe_resume) instead")
            if plan is not None:
                plan.fire("step_error", i)
            xi = x
            if plan is not None and \
                    plan.scheduled("nan", i) is not None:
                xi = _poison_first_float(x)
            # ShardedTrainer.step is NOT idempotent: it advances `_t` and
            # the RNG stream before dispatch.  Snapshot both so a failure
            # from INSIDE the step rolls back and the retry replays the
            # attempt bit-for-bit instead of desyncing.
            snap = self._trainer.step_state()
            try:
                return self._trainer.step(xi, y, batch_size)
            except self._retry_on as exc:
                if self._trainer.donation_consumed:
                    self._step_unsafe = True
                    raise MXNetError(
                        "ResilientTrainer: a failed step consumed its "
                        "donated parameter buffers — the live training "
                        "state is gone and the step cannot be retried; "
                        "restore from the newest committed checkpoint "
                        "(auto_resume / maybe_resume) instead") from exc
                self._trainer.rollback_step(snap)
                self._metrics.inc("rollbacks")
                raise
            except Exception:
                # NON-retryable failure from inside the step: still roll
                # back `_t`/RNG (when the device state survived) so a
                # caller that catches and continues is not silently
                # desynced; never mask the original error
                if self._trainer.donation_consumed:
                    self._step_unsafe = True
                else:
                    self._trainer.rollback_step(snap)
                    self._metrics.inc("rollbacks")
                raise

        def on_retry(attempt, exc, delay):
            self._metrics.inc("steps_retried")

        try:
            try:
                # step/update ids ride to the chrome-trace timeline as
                # event args (the histogram never sees them — no label
                # explosion)
                with _span("resilience.step_us",
                           args={"step": i,
                                 "t": self._trainer.num_update}) as sp:
                    loss = retry_call(one_attempt,
                                      retries=self._max_retries,
                                      base_delay=self._retry_base,
                                      max_delay=self._retry_max,
                                      retry_on=self._retry_on,
                                      on_retry=on_retry)
            except self._retry_on:
                self._metrics.inc("steps_failed")
                seg["compute"] = sp.duration_us
                # retries exhausted: the caller may catch and abandon
                # the run, so the postmortem ring dumps NOW, not only
                # from the excepthook
                self._finalize_step(i, None, sp.duration_us, root, seg,
                                    lw, failed=True)
                if root is not None:
                    # close the root BEFORE the dump ships the span
                    # ring, or the dumped trace the step record's
                    # trace_id points at would lack its own root
                    # (finish() is idempotent — the finally re-runs it)
                    root.finish()
                self._flight.dump(
                    f"step {i} failed after {self._max_retries + 1} "
                    f"attempt(s)")
                raise
            seg["compute"] = sp.duration_us
            if self._trainer.guard_enabled:
                self._pending_finite.append(
                    self._trainer.last_step_finite)
                if len(self._pending_finite) >= 128:
                    self._drain_finite()
            if self._membership is not None and \
                    not self._preempt_round_open() and \
                    i % self._fleet_sync_every == 0:
                # during a coordinated preemption round the lockstep
                # sync is skipped: the initiator is parked in its
                # vote-wait (the barrier would only time out, ~2 TTLs
                # per catch-up step — long enough to blow the
                # initiator's vote deadline and split the agreed
                # flush), and the fleet is about to flush and exit
                # anyway
                with _span("resilience.fleet_sync_us",
                           args={"step": i}) as fsp:
                    self._fleet_step_sync(i)
                seg["collective"] = fsp.duration_us
            if self._preempt_pending():
                self._preempt_boundary()
            if self._ckpt_dir is not None and self._every > 0 and \
                    self._trainer.num_update % self._every == 0:
                csp = None
                try:
                    # the ckpt-commit child of the step trace (the
                    # inner resilience.checkpoint_us span nests under
                    # it); histogram=False — checkpoint_us already IS
                    # the metric
                    with _span("resilience.ckpt_commit_us",
                               histogram=False) as csp:
                        self.checkpoint()
                except TransientFault:
                    pass   # counted in checkpoints_failed; the next
                    # periodic save (or the preemption path) covers
                    # the gap
                if csp is not None:
                    seg["ckpt"] = csp.duration_us
            self._finalize_step(i, loss, sp.duration_us, root, seg, lw)
            return loss
        finally:
            # the root must close on EVERY exit — success, retry
            # exhaustion, preemption raise, fleet re-form — or the
            # leaked context would adopt unrelated later work
            if root is not None:
                root.finish()

    # -- causal tracing / critical-path attribution --------------------------
    def _step_trace_key(self) -> str:
        """The fleet-uniform component of the deterministic step trace
        id: the fencing generation in a multi-process group (shared by
        every host with zero communication — the lockstep IS the
        causal key), a per-process nonce single-process (so two runs'
        step-N traces never collide)."""
        try:
            from . import dist
            if dist.is_initialized():
                return f"fence{dist.fence_generation()}"
        except Exception:   # noqa: BLE001 — tracing must never fail
            pass            # the step it traces
        return _RUN_NONCE

    def _begin_step_trace(self, i: int):
        """Open step ``i``'s trace root, or None when tracing is off or
        deterministic head sampling dropped this step (every host drops
        or keeps the SAME steps).  The root is backdated to the
        previous step's boundary, so the trace covers the full interval
        the training loop experienced — loader wait included."""
        tr = _tracing.tracer()
        if not tr.sampled_index(i):
            return None
        tid = _tracing.deterministic_trace_id(
            "resilience.step", self._step_trace_key(), i)
        return tr.begin(
            "resilience.step", trace_id=tid, t0=self._boundary_pc,
            args={"step": i,
                  "t": self._trainer.num_update
                  if self._trainer.built else 0})

    def _finalize_step(self, i: int, loss, jit_us: float, root, seg,
                       lw, failed: bool = False) -> None:
        """Close out one supervised step: decompose the
        boundary-to-boundary wall into the measured segments, name the
        bottleneck, attribute the between-steps loader work into the
        trace retroactively, and write gauges + histogram + flight
        record.  Runs while the step root is still ACTIVE, so the
        ``resilience.step_wall_us`` observation carries this trace's
        exemplar."""
        end = _tracing.now()
        start, self._boundary_pc = self._boundary_pc, end
        known = (seg["compute"] + seg["loader"] + seg["device_prefetch"]
                 + seg["collective"] + seg["ckpt"])
        wall = (end - start) * 1e6 if start is not None else known
        seg["other"] = max(0.0, wall - known)
        bottleneck = max(BREAKDOWN_STAGES, key=lambda s: seg[s])
        for s, g in self._g_breakdown.items():
            g.set(round(seg[s], 1))
        self._g_bottleneck.set(BREAKDOWN_STAGES.index(bottleneck))
        if root is not None and lw is not None and lw["wait_us"] > 0:
            # the loader wait happened before this step's body, on the
            # epoch loop — adopt it into the trace retroactively (the
            # device-prefetch dispatch nests inside the same window)
            tr = _tracing.tracer()
            ch = tr.begin("loader.wait", parent=root, activate=False,
                          t0=lw["wait_end"] - lw["wait_us"] / 1e6)
            if ch is not None:
                ch.finish(t_end=lw["wait_end"])
            if lw["device_put_us"] > 0:
                dp = tr.begin(
                    "loader.device_prefetch", parent=root,
                    activate=False,
                    t0=lw["wait_end"] - lw["device_put_us"] / 1e6)
                if dp is not None:
                    dp.finish(t_end=lw["wait_end"])
        if root is not None:
            root.annotate(bottleneck=bottleneck,
                          wall_us=round(wall, 1))
        self._h_step_wall.observe(wall)
        self._record_step(i, loss, jit_us, failed=failed,
                          wall_us=wall, breakdown=seg,
                          bottleneck=bottleneck,
                          trace_id=root.trace_id
                          if root is not None else None)

    # -- elastic fleet ------------------------------------------------------
    def _fire_host_faults(self, i: int, plan) -> None:
        """The host-level fault sites (MXTPU_FAULT_PLAN), wired exactly
        like the step-level kinds — 1-based supervisor step counter,
        each entry consumed on fire.  Only the process whose own plan
        carries the entry is affected: that is how a rank is targeted
        (plans are per-process env/state, not fleet-shared)."""
        spec = plan.scheduled("host_loss", i)
        if spec is not None:
            # a machine loss, not a shutdown: no flush, no atexit, no
            # SIGTERM grace — SIGKILL ourselves (or arg as an exit code
            # for platforms where a test must distinguish the two)
            if spec.arg is None:
                os.kill(os.getpid(), signal.SIGKILL)
                os._exit(137)   # unreachable; SIGKILL is not maskable
            os._exit(int(spec.arg))
        spec = plan.scheduled("heartbeat_stall", i)
        if spec is not None:
            if self._membership is None:
                raise MXNetError(
                    "fault 'heartbeat_stall': no membership layer is "
                    "attached (pass membership=/elastic=True)")
            self._membership.stall_heartbeats(spec.arg)

    def _fleet_step_sync(self, i: int) -> None:
        """Per-step bounded lockstep sync over the active members.  A
        dead peer turns this into ``DeadlineExceeded`` within ~2 lease
        TTLs; a forced lease scan then decides: confirmed loss (or a
        peer already opened a re-form round) routes into the re-form
        arc, anything else re-raises — a timeout with every lease fresh
        is real desync, not host loss, and hiding it would be worse."""
        try:
            self._membership.step_barrier()
        except DeadlineExceeded:
            # a peer parked in a preemption vote-wait skips the step
            # barrier by design — route into the coordination round
            # instead of treating the timeout as desync
            if self._peer_preempt_pending():
                self._preempt_boundary()
                # _preempt_boundary returned instead of raising: this
                # host is BEHIND the agreed flush step — swallow the
                # barrier timeout and keep stepping toward it (the
                # end-of-step boundary flushes once the counter
                # arrives); re-raising here would surface the peer's
                # vote-wait as desync and strand the fleet's agreed
                # `state-<t>` without this host's commit
                return
            self._membership.scan()
            self._membership.raise_if_fenced()
            if self._membership.reform_needed:
                self._reform_and_resume(i)
            raise

    def quiesce(self) -> None:
        """Stop touching shared state at a step boundary: resolve the
        pending device-side skip flags and flush any in-flight async
        checkpoint write.  Fleet-synchronized like a collective — every
        survivor quiesces before the re-form round (the
        collective-safety lint rule checks nothing reaches this from a
        rank-divergent branch)."""
        self._drain_finite()
        try:
            self._trainer.wait_checkpoint()
        except Exception:   # noqa: BLE001 — a torn in-flight write is
            # abandoned; resume only ever reads COMMITTED checkpoints
            pass

    def _reform_and_resume(self, i: int) -> None:
        """The quiesce → re-form → resume arc.  Runs at a step
        boundary on every survivor, then raises the *recoverable*
        :class:`FleetReformed`: the training loop catches it, rebuilds
        its epoch iterator (the shard assignment changed), and
        continues — no operator action.

        Resume restores the newest committed checkpoint (params,
        optimizer state, RNG stream, update counter) and re-winds the
        attached loader's cursor onto the new shard assignment.  With
        no committed checkpoint yet, training state is left as-is
        (survivors are self-consistent — each kept its own params) and
        ``result.resumed_t`` is None."""
        mship = self._membership
        self._flight.record_membership(
            event="quiesce", ts=round(time.time(), 3), step=i,
            t=self._trainer.num_update if self._trainer.built else 0)
        with _span("resilience.reform_us", args={"step": i}):
            self.quiesce()
            result = mship.reform()
            # in-graph re-shard hook (ROADMAP #3): re-build the sharded
            # step at the new world size — shardings re-derived, live
            # state re-placed, jits re-lowered — BEFORE the restore, so
            # the checkpoint (possibly saved at the old dp size) lands
            # on the new layout.  On a host-local mesh (unchanged by a
            # peer's death) reshard() is a no-op; the record still
            # carries the post-re-form fleet dp size so re-form
            # timelines show the re-shard between reform and resume.
            # A mesh that truly SPANS hosts cannot take this path at
            # all: the jax runtime cannot shrink a live multi-host
            # world (the old world's collectives can never complete —
            # the same fact that forces the dirty detach on teardown),
            # so spanning-mesh survivors restart into a new world and
            # re-shard on restore instead.
            if self._trainer.built:
                t0 = time.monotonic()
                self._trainer.reshard()
                mship.record_reshard(result.new_world,
                                     (time.monotonic() - t0) * 1e6)
            resumed = None
            if self._ckpt_dir is not None and self._trainer.built and \
                    ShardedTrainer.latest_checkpoint(self._ckpt_dir) \
                    is not None:
                self._trainer.load_checkpoint(self._ckpt_dir)
                resumed = self._trainer.num_update
                self._last_saved_t = resumed
                self._restore_loader_cursor(resumed)
                self._metrics.inc("resumes")
                self.resumed_t = resumed
        self._metrics.inc("fleet_reforms")
        self._flight.record_membership(
            event="resume", ts=round(time.time(), 3), step=i,
            t=resumed, fence=result.fence,
            members=list(result.members))
        raise FleetReformed(
            result._replace(resumed_t=resumed),
            f"fleet re-formed at generation {result.fence}: lost rank(s) "
            f"{list(result.dead)}, continuing at world size "
            f"{result.new_world} (this host is now rank "
            f"{result.new_rank})"
            + (f" from the step-{resumed} checkpoint" if resumed
               is not None else " with no committed checkpoint to "
               "restore — training state left as-is"))

    # -- loader position sidecar --------------------------------------------
    def _loader_sidecar(self, t: int) -> str:
        return os.path.join(self._ckpt_dir, f"loader-{t:08d}.json")

    def _save_loader_cursor(self, t: int) -> None:
        """Write the attached loader's position cursor next to the
        step's checkpoint dir (synchronous — it is a few bytes; the
        orbax state write stays async).  Best-effort by design: a
        missing sidecar degrades resume to epoch start, never blocks
        the checkpoint."""
        if self._loader is None or \
                not hasattr(self._loader, "state_dict"):
            return
        try:
            payload = json.dumps(self._loader.state_dict())
            tmp = self._loader_sidecar(t) + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self._loader_sidecar(t))
        except Exception:   # noqa: BLE001 — see docstring
            pass

    def _restore_loader_cursor(self, t: int) -> None:
        if self._loader is None or \
                not hasattr(self._loader, "load_state_dict"):
            return
        try:
            with open(self._loader_sidecar(t)) as f:
                self._loader.load_state_dict(json.load(f))
        except FileNotFoundError:
            return   # pre-sidecar checkpoint: epoch restarts from 0
        except Exception:   # noqa: BLE001 — a torn sidecar degrades the
            return          # same way, never blocks resume

    def _record_step(self, i: int, loss, step_us: float,
                     failed: bool = False,
                     wall_us: Optional[float] = None,
                     breakdown: Optional[dict] = None,
                     bottleneck: Optional[str] = None,
                     trace_id: Optional[str] = None) -> None:
        """One flight-recorder record per supervised step.  Cheap by
        construction: counter/gauge reads, one bucket-percentile pass
        over the flush histogram, and a deque append — the loss is
        stored as its live device reference and only materialized if a
        dump ever happens.

        ``trace_id`` cross-references the causal span ring (a crash
        dump's step records point into the trace JSONL/ring);
        ``breakdown``/``bottleneck`` are the step's critical-path
        attribution — the one-line answer to "why was this step slow"
        sits in the postmortem ring itself."""
        if not self._flight.enabled:
            return
        flush = self._h_flush
        self._flight.record(
            step=i,
            t=self._trainer.num_update if self._trainer.built else 0,
            step_us=round(step_us, 1),
            wall_us=None if wall_us is None else round(wall_us, 1),
            loss=loss,
            loss_scale=self._g_loss_scale.value,
            flush_us_p99=round(flush.percentile(99), 1),
            flush_count=flush.count,
            steps_skipped=self._c_skipped.n,
            rollbacks=self._c_rollbacks.n,
            loader_depth=self._g_loader_depth.value,
            # in-flight async checkpoint (the PR-4 gauge, now a
            # per-step flight field): 1 while a background orbax/npz
            # commit overlaps these steps
            ckpt_inflight=self._g_ckpt_inflight.value,
            breakdown=None if breakdown is None else
            {s: round(v, 1) for s, v in breakdown.items()},
            bottleneck=bottleneck,
            trace_id=trace_id,
            failed=failed,
        )

    # -- checkpointing -----------------------------------------------------
    def checkpoint(self, wait: bool = False) -> None:
        """Write an async checkpoint now and prune per retention.  With
        ``wait=True``, block until the write commits."""
        if self._ckpt_dir is None:
            raise MXNetError("ResilientTrainer has no checkpoint_dir")
        t = self._trainer.num_update
        self._save_index += 1
        if self._plan is not None and \
                self._plan.scheduled("ckpt_fail", self._save_index) \
                is not None:
            # simulate a crash mid-async-write: a torn step dir with data
            # but no orbax commit marker — resume must skip it
            torn = os.path.join(self._ckpt_dir, f"state-{t:08d}")
            os.makedirs(torn, exist_ok=True)
            with open(os.path.join(torn, "_TORN_WRITE"), "w") as f:
                f.write("injected by MXTPU_FAULT_PLAN\n")
            self._metrics.inc("checkpoints_failed")
            raise TransientFault(
                f"injected checkpoint write failure "
                f"(save #{self._save_index}, step {t})")
        with _span("resilience.checkpoint_us", args={"step": t}):
            # spans the ASYNC save enqueue (+ optional commit wait), not
            # the background write — host-side stall is what this costs
            # the training loop
            self._trainer.save_checkpoint(self._ckpt_dir)
            self._last_saved_t = t
            self._save_loader_cursor(t)
            self._metrics.inc("checkpoints_written")
            if wait:
                self._trainer.wait_checkpoint()
        self._gc()
        if self._trainer.guard_enabled:
            self._g_loss_scale.set(self._trainer.loss_scale)
        # checkpoint boundaries are the fleet's natural sync point: every
        # host checkpoints the same step (SPMD lockstep; the sharded
        # orbax save is itself fleet-synchronized), so the multi-host
        # metric gather (a collective) lines up here.  Refreshes the
        # merged view the MXTPU_METRICS_AGGREGATE endpoint serves.
        # Deliberately NOT gated on that env var: the gate would be a
        # per-host env read, and hosts disagreeing on it would leave the
        # opted-in host blocked in a collective its peers never enter.
        # The gather is a few KB of JSON over DCN — noise next to the
        # checkpoint write it rides.
        try:
            from . import dist
            if dist.is_initialized():
                _metrics_registry().snapshot(all_hosts=True)
        except Exception:   # noqa: BLE001 — the fleet view is
            pass            # best-effort; checkpointing must win

    def flush(self) -> None:
        """Block until any in-flight async write commits, then apply
        retention to the now-complete committed set."""
        self._trainer.wait_checkpoint()
        if self._ckpt_dir is not None:
            self._gc()

    def _gc(self) -> None:
        """keep-last-K over COMMITTED checkpoints only.  An in-flight
        async write is invisible here (not yet committed) and torn dirs
        are never counted, so the newest committed checkpoint always
        survives; torn partials older than it are swept as garbage."""
        committed = ShardedTrainer.committed_checkpoints(self._ckpt_dir)
        for path in committed[:-self._keep_last]:
            shutil.rmtree(path, ignore_errors=True)
            # the loader-position sidecar rides its step dir's lifetime
            digits = os.path.basename(path).split("-", 1)[-1]
            try:
                os.remove(os.path.join(self._ckpt_dir,
                                       f"loader-{digits}.json"))
            except OSError:
                pass
            self._metrics.inc("checkpoints_pruned")
        if not committed:
            return
        newest = os.path.basename(committed[-1])
        for d in sorted(os.listdir(self._ckpt_dir)):
            full = os.path.join(self._ckpt_dir, d)
            if full in committed or not d.startswith("state-"):
                continue
            # uncommitted (torn or tmp) and strictly older than the newest
            # committed step -> dead weight from a crashed write
            if d.split(".")[0] < newest:
                shutil.rmtree(full, ignore_errors=True)
