"""Device-mesh management: the TPU-native replacement for MXNet's context
lists and KVStore device topology.

Reference parity: the reference scales by enumerating GPU Contexts and
reducing gradients through KVStore comm trees / NCCL rings
(src/kvstore/comm.h, kvstore_nccl.h — SURVEY.md §2.3).  TPU-native design:
ONE `jax.sharding.Mesh` over the chips with named axes

    dp  — data parallel (batch dim; grad reduce rides ICI psum)
    tp  — tensor parallel (megatron-style weight sharding)
    sp  — sequence/context parallel (long-context activations)
    pp  — pipeline parallel (stage dim; reserved)
    ep  — expert parallel (MoE; reserved)

and `NamedSharding` annotations; XLA inserts the collectives (psum,
all_gather, reduce_scatter) that NCCL calls performed by hand in the
reference.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from ..base import MXNetError

__all__ = ["make_mesh", "default_mesh", "ShardingRules", "replicated",
           "shard", "zero_sharding", "axis_size", "comm_buckets",
           "MESH_AXES"]

#: canonical axis order — dp outermost (DCN/ICI-friendly), then pipeline,
#: then the intra-layer axes
MESH_AXES = ("dp", "pp", "tp", "sp", "ep")


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Build a `jax.sharding.Mesh`.

    axes: ordered {axis_name: size}; the product must equal the number of
    devices (pass an explicit `devices` subset to underfill deliberately).
    Default: all devices on the 'dp' axis (pure data parallel — the
    reference's kvstore='device' topology).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axes is None:
        axes = {"dp": len(devices)}
    names = tuple(axes.keys())
    sizes = tuple(int(s) for s in axes.values())
    n = 1
    for s in sizes:
        n *= s
    if n != len(devices):
        raise MXNetError(
            f"mesh {dict(axes)} covers {n} devices but {len(devices)} were "
            f"given — pass an explicit device subset if underfilling is "
            f"intended")
    grid = _np.array(devices, dtype=object).reshape(sizes)
    return Mesh(grid, names)


_default_mesh = None


def default_mesh():
    """Process-wide default mesh (all devices, data parallel)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def replicated(mesh):
    """Fully-replicated NamedSharding on `mesh`."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def shard(mesh, *spec):
    """NamedSharding from a PartitionSpec-like tuple, e.g.
    shard(mesh, 'dp') for batch-dim sharding."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*spec))


def axis_size(mesh, name: str) -> int:
    """Size of mesh axis ``name`` (1 when the mesh has no such axis)."""
    return int(mesh.shape[name]) if name in mesh.axis_names else 1


def zero_sharding(mesh, spec, shape, axis: str = "dp"):
    """ZeRO-style NamedSharding for a per-parameter optimizer-state (or
    gradient-accumulation) tensor: partition dim 0 over the data-parallel
    axis ON TOP of the parameter's own PartitionSpec, so each dp rank
    owns a 1/dp slice of the state it updates (PAPERS.md ZeRO stage 1/2).

    Falls back to the parameter's own sharding — replicated state, the
    pre-ZeRO layout — whenever the partition cannot be formed: no/size-1
    ``axis`` on the mesh, a scalar tensor, dim 0 not divisible by the
    axis size, dim 0 already sharded by the parameter's rules, or the
    axis already consumed by another dim (a dp-sharded parameter cannot
    also dp-shard its state).  The fallback is per-parameter: a model
    keeps ZeRO savings on its big matrices even when a stray odd-shaped
    vector cannot split."""
    from jax.sharding import NamedSharding, PartitionSpec
    dp = axis_size(mesh, axis)
    entries = list(spec) + [None] * (len(shape) - len(spec))

    def uses(entry, name):
        if entry is None:
            return False
        if isinstance(entry, (tuple, list)):
            return name in entry
        return entry == name

    if (dp <= 1 or not shape or int(shape[0]) % dp != 0 or
            entries[0] is not None or
            any(uses(e, axis) for e in entries)):
        return NamedSharding(mesh, PartitionSpec(*spec))
    entries[0] = axis
    return NamedSharding(mesh, PartitionSpec(*entries))


def comm_buckets(nbytes, cap_bytes):
    """Partition gradient indices into size-capped communication
    buckets for the bucketed reduce-scatter (PAPER.md's L4 design
    point: MXNet issued per-parameter KVStore pushes as backward
    produced each gradient; the SPMD-native analog is per-bucket
    collectives the latency-hiding scheduler interleaves with the
    remaining backward compute).

    ``nbytes`` is the per-gradient byte size in PARAMETER order; the
    returned buckets are lists of indices in REVERSE parameter order —
    the order backward materializes gradients (last layer first) — so
    bucket 0's collective can issue while earlier layers' gradients
    are still being computed.  Greedy fill: a bucket closes once it
    holds >= 1 gradient and adding the next would exceed
    ``cap_bytes``; a single gradient larger than the cap gets its own
    bucket.  ``cap_bytes`` of 0/None/inf (or a cap that swallows
    everything) returns ONE bucket — callers treat that as the fused
    (pre-bucketing) path."""
    n = len(nbytes)
    if not n:
        return []
    if not cap_bytes or cap_bytes <= 0 or cap_bytes == float("inf"):
        return [list(range(n - 1, -1, -1))]
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in range(n - 1, -1, -1):
        b = int(nbytes[i])
        if cur and cur_bytes + b > cap_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
    if cur:
        buckets.append(cur)
    return buckets


class ShardingRules:
    """Name-pattern → PartitionSpec table for parameters.

    The TPU-native successor of the reference's `group2ctx` manual model
    parallelism (nnvm PlaceDevice pass — SURVEY.md §2.3): instead of pinning
    ops to devices, parameters matching a regex get a PartitionSpec; XLA
    partitions the matmuls and inserts collectives.

        rules = ShardingRules([
            (r".*_qkv_weight$",  ("tp", None)),   # column parallel
            (r".*_proj_weight$", (None, "tp")),   # row parallel
        ])
    First match wins; no match → replicated.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, tuple]]] = None):
        self._rules: List[Tuple[re.Pattern, tuple]] = [
            (re.compile(pat), tuple(spec)) for pat, spec in (rules or [])]

    def spec_for(self, name: str, shape=None):
        from jax.sharding import PartitionSpec
        for pat, spec in self._rules:
            if pat.search(name):
                return PartitionSpec(*spec)
        return PartitionSpec()

    def sharding_for(self, mesh, name: str, shape=None):
        from jax.sharding import NamedSharding
        return NamedSharding(mesh, self.spec_for(name, shape))


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across jax versions: modern ``jax.shard_map`` with
    ``check_vma`` vs older ``jax.experimental.shard_map`` with
    ``check_rep`` — the one shim for every per-device kernel in this
    package (ring attention, pipeline schedule)."""
    try:
        from jax import shard_map
    except ImportError:                      # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:                        # older spelling
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
