"""Mixture-of-Experts with expert parallelism (the 'ep' mesh axis).

BEYOND reference parity: the 2018-era reference has no MoE (SURVEY.md
§2.3 lists EP as absent), but the build mandate makes distributed
first-class, so the framework ships a TPU-native MoE layer whose experts
shard over an ``ep`` mesh axis.

TPU-native design (the Switch/GShard dense-dispatch formulation): top-1
routing with a capacity limit, expressed entirely as one-hot matmuls and
batched matmuls — static shapes, everything lands on the MXU, and under
``pjit`` with the expert-stacked weights sharded ``P('ep', ...)`` XLA
inserts the dispatch/combine all-to-alls over ICI itself.

    rules = ShardingRules(EP_RULES() + TP_RULES)
"""
from __future__ import annotations

import math

from ..gluon.block import HybridBlock

__all__ = ["MoEFFN", "EP_RULES"]


def EP_RULES():
    """ShardingRules entries placing stacked expert weights on 'ep'."""
    from jax.sharding import PartitionSpec as P
    return [(r".*expert_w[12]$", P("ep", None, None))]


class MoEFFN(HybridBlock):
    """Switch-style MoE feed-forward: router → top-1 dispatch (capacity
    limited) → per-expert FFN → weighted combine.

    Parameters
    ----------
    units : model dim D (input and output).
    hidden_size : per-expert FFN hidden dim H.
    num_experts : E — shard this axis over the 'ep' mesh axis.
    capacity_factor : per-expert slots = ceil(tokens/E * factor); tokens
        over capacity pass through the residual (standard Switch drop).
    """

    def __init__(self, units, hidden_size, num_experts,
                 capacity_factor=1.25, activation="relu", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._hidden = hidden_size
        self._E = num_experts
        self._cap_factor = capacity_factor
        self._act = activation
        with self.name_scope():
            self.router = self.params.get(
                "router", shape=(units, num_experts), init="xavier")
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, units, hidden_size),
                init="xavier")
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, units),
                init="xavier")

    def hybrid_forward(self, F, x, router, expert_w1, expert_w2):
        # x: (B, S, D) -> tokens (N, D)
        B, S, D = x.shape
        E = self._E
        N = B * S
        C = max(1, math.ceil(N / max(E, 1) * self._cap_factor))
        tok = F.reshape(x, shape=(N, D))

        logits = F.dot(tok, router)                     # (N, E)
        probs = F.softmax(logits, axis=-1)
        eidx = F.argmax(probs, axis=-1)                 # (N,)
        gate = F.max(probs, axis=-1)                    # (N,) top-1 prob
        onehot = F.one_hot(eidx, depth=E)                     # (N, E)

        # position of each token within its expert's queue
        pos = F.cumsum(onehot, axis=0) * onehot         # 1-based ranks
        keep = (pos <= C) * onehot                      # capacity mask
        posC = F.one_hot(
            F.where(keep > 0, pos - 1, F.ones_like(pos) * C),
            depth=C)                                    # (N, E, C)
        dispatch = posC * F.reshape(keep, shape=(N, E, 1))    # (N, E, C)

        # dispatch: (E*C, N) @ (N, D) -> (E, C, D); MXU matmuls only
        disp2 = F.transpose(F.reshape(dispatch, shape=(N, E * C)))
        expert_in = F.reshape(F.dot(disp2, tok), shape=(E, C, D))
        h = F.batch_dot(expert_in, expert_w1)           # (E, C, H)
        h = F.Activation(h, act_type=self._act)
        expert_out = F.batch_dot(h, expert_w2)          # (E, C, D)

        # combine, weighted by the gate prob of kept tokens
        combine = dispatch * F.reshape(gate, shape=(N, 1, 1))
        out = F.dot(F.reshape(combine, shape=(N, E * C)),
                    F.reshape(expert_out, shape=(E * C, D)))  # (N, D)
        # dropped (over-capacity) tokens pass through as residual zeros;
        # standard Switch keeps the residual connection outside this block
        return F.reshape(out, shape=(B, S, D))
