"""mxnet_tpu.parallel — multi-chip scaling over `jax.sharding`.

The TPU-native replacement for the reference's KVStore comm stack
(device/NCCL/ps-lite — SURVEY.md §2.3, §5.8): one device Mesh with named
axes (dp/tp/sp/pp/ep), sharding rules instead of manual device placement,
and a whole-train-step jit in which XLA inserts the ICI/DCN collectives.
"""
from .mesh import (MESH_AXES, ShardingRules, default_mesh, make_mesh,
                   replicated, shard)
from .optim import FunctionalOptimizer, make_functional_optimizer
from .ring import ring_attention
from .trainer import ShardedTrainer
from .membership import (FleetLost, FleetReformed, HostFenced,
                         MembershipManager)
from .resilience import ResilientTrainer, TrainingPreempted
from . import dist

__all__ = ["MESH_AXES", "ShardingRules", "default_mesh", "make_mesh",
           "replicated", "shard", "FunctionalOptimizer",
           "make_functional_optimizer", "ring_attention", "ShardedTrainer",
           "ResilientTrainer", "TrainingPreempted", "MembershipManager",
           "FleetReformed", "FleetLost", "HostFenced", "dist"]
