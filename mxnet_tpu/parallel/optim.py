"""Functional optimizer layer for the sharded training step.

Reference parity: the reference updates weights in-place via fused optimizer
ops inside the engine (src/operator/optimizer_op.cc — SURVEY.md §2.2); the
KVStore 'device'/'nccl' path reduces first, then each device updates its
replica.  TPU-native design: the ENTIRE step — forward, backward, gradient
psum (implicit from shardings), optimizer update — is one jitted XLA
computation with donated buffers, so weights update in place at the HBM
level.  This module lowers an imperative `mxnet_tpu.optimizer.Optimizer`
(hyperparams + per-param lr/wd multipliers) into pure
`update(params, grads, state, t, lr, rescale) -> (params, state)` functions
over pytrees.  Formulas mirror ndarray/ops_optimizer.py exactly so the
sharded path is numerically identical to the single-chip Trainer.
"""
from __future__ import annotations

from typing import Any, List, Sequence

from ..base import MXNetError
from .. import optimizer as opt_mod

__all__ = ["FunctionalOptimizer", "make_functional_optimizer"]


class FunctionalOptimizer:
    """Pure pytree optimizer: `init(params) -> state`,
    `update(params, grads, state, t, base_lr, rescale) -> (params, state)`.

    `t` (update count), `base_lr` and `rescale` (grad scale, 1/batch_size)
    are traced inputs so LR schedules and batch-size changes never
    recompile.  Per-param lr_mult/wd_mult/clip are trace-time constants.
    """

    def __init__(self, kind: str, hyper: dict,
                 lr_mults: Sequence[float], wds: Sequence[float]):
        self.kind = kind
        self.hyper = hyper
        self.lr_mults = list(lr_mults)
        self.wds = list(wds)

    # -- state -------------------------------------------------------------
    def init(self, params: List[Any]) -> List[Any]:
        import jax.numpy as jnp
        k = self.kind
        if k == "sgd":
            if self.hyper.get("momentum", 0.0):
                return [jnp.zeros_like(p) for p in params]
            return [() for _ in params]
        if k in ("nag", "signum"):
            # momentum state even at mu=0 (formulas degrade gracefully)
            return [jnp.zeros_like(p) for p in params]
        if k == "adam":
            return [(jnp.zeros_like(p), jnp.zeros_like(p)) for p in params]
        if k == "adagrad":
            return [jnp.zeros_like(p) for p in params]
        if k == "rmsprop":
            if self.hyper.get("centered", False):
                return [(jnp.zeros_like(p), jnp.zeros_like(p),
                         jnp.zeros_like(p)) for p in params]
            return [jnp.zeros_like(p) for p in params]
        raise MXNetError(f"no functional lowering for optimizer {k!r}")

    # -- update ------------------------------------------------------------
    def update(self, params, grads, state, t, base_lr, rescale,
               sparse=frozenset()):
        """Apply one step.  Indices in ``sparse`` carry their gradient as
        a ``(values, unique_ids)`` pair (sparse_grad.py) and take the
        lazy gather→update→scatter row path; everything else is dense."""
        import jax.numpy as jnp
        h = self.hyper
        clip = h.get("clip_gradient") or 0.0
        new_p, new_s = [], []
        for i, (w, g, s) in enumerate(zip(params, grads, state)):
            lr = (base_lr * self.lr_mults[i]).astype(w.dtype)
            wd = self.wds[i]
            if i in sparse:
                w, s = self._update_rows(w, g, s, t, lr, wd, rescale, clip)
                new_p.append(w)
                new_s.append(s)
                continue
            g = g * rescale.astype(g.dtype)
            if clip and clip > 0:
                g = jnp.clip(g, -clip, clip)
            k = self.kind
            if k != "adagrad":   # adagrad: decoupled wd (fused-op parity)
                g = g + wd * w
            if k == "sgd":
                mu = h.get("momentum", 0.0)
                if mu:
                    m = mu * s - lr * g
                    w, s = w + m, m
                else:
                    w = w - lr * g
            elif k == "nag":
                mu = h.get("momentum", 0.0)
                m = mu * s + g
                w, s = w - lr * (g + mu * m), m
            elif k == "signum":
                mu = h.get("momentum", 0.0)
                wd_lh = h.get("wd_lh", 0.0)
                m = mu * s - (1 - mu) * g
                w, s = (1 - lr * wd_lh) * w + lr * jnp.sign(m), m
            elif k == "adam":
                b1, b2 = h["beta1"], h["beta2"]
                eps = h["epsilon"]
                # bias-corrected lr, t is a traced count (reference Adam)
                tt = t.astype(jnp.float32)
                coef = jnp.sqrt(1.0 - b2 ** tt) / (1.0 - b1 ** tt)
                m, v = s
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * jnp.square(g)
                w = w - (lr * coef.astype(w.dtype)) * m / (jnp.sqrt(v) + eps)
                s = (m, v)
            elif k == "adagrad":
                eps = h.get("eps", 1e-7)
                s = s + jnp.square(g)
                w = w - lr * (g / jnp.sqrt(s + eps) + wd * w)
            elif k == "rmsprop":
                g1 = h.get("gamma1", 0.95)
                eps = h.get("epsilon", 1e-8)
                if h.get("centered", False):
                    # rmspropalex (centered) — mirrors the fused op exactly
                    n, mg, d = s
                    n = g1 * n + (1 - g1) * jnp.square(g)
                    mg = g1 * mg + (1 - g1) * g
                    d = h.get("gamma2", 0.9) * d - \
                        lr * g / jnp.sqrt(n - jnp.square(mg) + eps)
                    w = w + d
                    s = (n, mg, d)
                else:
                    s = g1 * s + (1 - g1) * jnp.square(g)
                    w = w - lr * g / jnp.sqrt(s + eps)
                cw = h.get("clip_weights") or 0.0
                if cw and cw > 0:
                    w = jnp.clip(w, -cw, cw)
            else:
                raise MXNetError(f"no functional lowering for {k!r}")
            new_p.append(w)
            new_s.append(s)
        return new_p, new_s

    # -- lazy row update ---------------------------------------------------
    def _update_rows(self, w, grad, s, t, lr, wd, rescale, clip):
        """The in-graph lazy update (reference optimizer_op.cc row_sparse
        kernels): gather state for the batch's live rows, apply the dense
        formula to those rows only, scatter back.  ``grad`` is the
        ``(values, unique_ids)`` pair; padded bucket slots carry the
        out-of-range id ``nrows`` so their scatters DROP (XLA out-of-bounds
        scatter semantics) — untouched rows' weight AND optimizer state
        are never read or written.  Weight decay applies to touched rows
        only, the reference's documented lazy_update semantics."""
        import jax.numpy as jnp
        values, uids = grad
        nrows = w.shape[0]
        # clipped twin for GATHERS (padded slots read row 0's garbage,
        # discarded because the uids scatter drops); raw uids for scatters
        safe = jnp.clip(uids, 0, nrows - 1)
        g = values * rescale.astype(values.dtype)
        if clip and clip > 0:
            g = jnp.clip(g, -clip, clip)
        k = self.kind
        g = g + wd * w[safe]
        if k == "sgd":
            mu = self.hyper.get("momentum", 0.0)
            if mu:
                m_rows = mu * s[safe] - lr * g
                return w.at[uids].add(m_rows), s.at[uids].set(m_rows)
            return w.at[uids].add(-lr * g), s
        if k == "adam":
            b1, b2 = self.hyper["beta1"], self.hyper["beta2"]
            eps = self.hyper["epsilon"]
            tt = t.astype(jnp.float32)
            coef = jnp.sqrt(1.0 - b2 ** tt) / (1.0 - b1 ** tt)
            m, v = s
            m_rows = b1 * m[safe] + (1 - b1) * g
            v_rows = b2 * v[safe] + (1 - b2) * jnp.square(g)
            w = w.at[uids].add(-(lr * coef.astype(w.dtype)) * m_rows /
                               (jnp.sqrt(v_rows) + eps))
            return w, (m.at[uids].set(m_rows), v.at[uids].set(v_rows))
        raise MXNetError(
            f"optimizer {k!r} has no lazy row-sparse lowering — use "
            f"sgd/adam or drop sparse_grad=True")


def make_functional_optimizer(opt: "opt_mod.Optimizer",
                              param_names: Sequence[str]) -> FunctionalOptimizer:
    """Lower an imperative Optimizer instance (reference API) to the pure
    pytree form, capturing per-param lr_mult/wd_mult by name/index."""
    kind = type(opt).__name__.lower()
    hyper = dict(
        momentum=getattr(opt, "momentum", 0.0),
        beta1=getattr(opt, "beta1", 0.9),
        beta2=getattr(opt, "beta2", 0.999),
        epsilon=getattr(opt, "epsilon", 1e-8),
        eps=getattr(opt, "float_stable_eps", 1e-7),
        gamma1=getattr(opt, "gamma1", 0.95),
        gamma2=getattr(opt, "gamma2", 0.9),
        centered=getattr(opt, "centered", False),
        clip_weights=getattr(opt, "clip_weights", None),
        wd_lh=getattr(opt, "wd_lh", 0.0),
        clip_gradient=getattr(opt, "clip_gradient", None),
    )
    def _mult(table, i, name):
        p = opt.param_dict.get(i)
        attr = "lr_mult" if table is opt.lr_mult else "wd_mult"
        if p is not None:
            return getattr(p, attr, 1.0)
        if i in table:
            return table[i]
        return table.get(name, 1.0)

    lr_mults, wds = [], []
    for i, name in enumerate(param_names):
        lr_mults.append(float(_mult(opt.lr_mult, i, name)))
        wds.append(float(opt.wd) * float(_mult(opt.wd_mult, i, name)))
    return FunctionalOptimizer(kind, hyper, lr_mults, wds)
