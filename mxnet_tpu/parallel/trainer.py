"""ShardedTrainer: the whole training step as ONE jitted XLA computation
over a device mesh.

Reference parity: this subsumes the reference's data-parallel machinery —
`split_and_load` + Trainer.step → KVStore push/pull → fused optimizer ops
(python/mxnet/gluon/trainer.py, src/kvstore/comm.h — SURVEY.md §2.3, §3.2).
TPU-native design (the BASELINE north star): instead of object-level
push/pull loops, the step function

    (params, aux, opt_state, key, t, lr, rescale, x, y)
        -> (params', aux', opt_state', loss)

is jitted with `NamedSharding`s: batch sharded over the 'dp' mesh axis,
params replicated (or tensor-parallel via ShardingRules), so XLA emits the
gradient psum over ICI that the reference performed through NCCL, fuses it
with the optimizer update, and donates the param buffers (true in-place
update at the HBM level).  Numerics match the imperative Trainer exactly
(same formulas — parallel/optim.py).

ZeRO scale-out (``zero_stage``, PAPERS.md ZeRO / Megatron-LM lineage):
stage 0 replicates optimizer state on every chip (the reference's
NCCL-KVStore layout, bitwise-identical to the pre-ZeRO step); stage 1
shards optimizer state 1/dp per chip — gradients are reduce-SCATTERED
into each chip's slice instead of psum-replicated, each chip runs its
slice of the functional optimizer update, and the updated params are
all-gathered, all inside the one donated jit so XLA overlaps the
collectives with backward compute; stage 2 additionally keeps the
gradient (accumulation) buffer sharded, so with ``accum_steps > 1`` the
carried grad state costs 1/dp per chip too.  ``accum_steps=N``
microbatches the global batch through a ``lax.scan`` (per-microbatch
RNG split, rescale-correct: the accumulated gradient equals the
full-batch gradient), so global batch scales past per-chip memory.
"""
from __future__ import annotations

import re as _re
import threading as _threading
import time as _time
import warnings as _warnings
from typing import Any, Callable, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError, get_env, hot_path
from ..context import current_context
from .. import autograd as _autograd
from .. import optimizer as opt_mod
from .. import random as _grandom
from ..ndarray import NDArray
from ..gluon.block import _TraceCtx, _KeyScope
from ..gluon.parameter import Parameter
from ..observability.registry import registry as _metrics_registry
from ..sparse_grad import SparseGradTrace as _SparseGradTrace
from .mesh import (ShardingRules, axis_size, comm_buckets, default_mesh,
                   replicated, shard, zero_sharding)
from .optim import make_functional_optimizer

__all__ = ["ShardedTrainer"]

# a committed orbax checkpoint dir is exactly `state-<8 digits>` AND carries
# the commit marker; anything else under the root (orbax's
# `*.orbax-checkpoint-tmp-*` rename staging, a dir torn by a crash
# mid-async-write) is an uncommitted partial and must never be restored
_STEP_DIR_RE = _re.compile(r"^state-(\d+)$")
_COMMIT_MARKER = "_CHECKPOINT_METADATA"


class ShardedTrainer:
    """Data/tensor/sequence-parallel trainer over a jax Mesh.

    Parameters
    ----------
    block : gluon.Block — the model (need not be hybridized; the step IS
        the jit).
    loss : callable — `loss(out, y) -> NDArray` (a gluon loss Block works).
    optimizer : str or Optimizer — lowered to a pure update (optim.py).
    mesh : jax.sharding.Mesh — default: all devices on 'dp'.
    rules : ShardingRules — parameter PartitionSpecs (tensor parallelism).
    data_spec / label_spec : PartitionSpec tuples for the batch, default
        ('dp',) — add 'sp' on the sequence dim for context parallelism,
        e.g. data_spec=('dp', 'sp').
    zero_stage : {0, 1, 2} — optimizer-state partitioning over the 'dp'
        axis (default: the ``MXTPU_ZERO_STAGE`` knob).  0 = replicated
        state (bitwise-identical to the pre-ZeRO step); 1 = state
        sharded, gradients reduce-scattered for the update, updated
        params all-gathered; 2 = the gradient (accumulation) buffer is
        sharded too.  Per-parameter fallback: a tensor whose dim 0
        cannot split over dp keeps replicated state (see
        :func:`~mxnet_tpu.parallel.mesh.zero_sharding`).
    accum_steps : int — microbatched gradient accumulation (default: the
        ``MXTPU_ACCUM_STEPS`` knob).  The step consumes the same global
        batch but runs it as N sequential microbatches under a
        ``lax.scan``; peak activation memory drops ~N-fold while the
        update is rescale-correct against the full batch.
    comm_bucket_mb : float — bucketed gradient reduce-scatter (default:
        the ``MXTPU_COMM_BUCKET_MB`` knob).  0 (off) keeps ONE fused
        reduction after the full backward — bitwise-identical to the
        pre-bucketing step; > 0 splits the gradients into buckets of
        at most this many MB (reverse parameter order — the order
        backward materializes them) whose dp-reductions are pinned
        with ``optimization_barrier``-chained sharding constraints so
        XLA's latency-hiding scheduler overlaps each bucket's
        collective with the remaining backward compute.
    """

    def __init__(self, block, loss: Callable, optimizer,
                 optimizer_params: Optional[dict] = None, mesh=None,
                 rules: Optional[ShardingRules] = None,
                 data_spec: Sequence = ("dp",),
                 label_spec: Optional[Sequence] = None,
                 zero_stage: Optional[int] = None,
                 accum_steps: Optional[int] = None,
                 comm_bucket_mb: Optional[float] = None,
                 guard_nonfinite: bool = False,
                 dynamic_loss_scale: bool = False,
                 init_loss_scale: float = 2.0 ** 15,
                 scale_growth_interval: int = 2000,
                 scale_backoff: float = 0.5,
                 min_loss_scale: float = 1.0,
                 max_loss_scale: float = 2.0 ** 24):
        self._block = block
        self._loss = loss
        self._mesh = mesh if mesh is not None else default_mesh()
        self._rules = rules if rules is not None else ShardingRules()
        self._data_spec = tuple(data_spec)
        self._label_spec = tuple(label_spec) if label_spec is not None \
            else (self._data_spec[0],)
        if zero_stage is None:
            zero_stage = int(get_env("MXTPU_ZERO_STAGE"))
        if zero_stage not in (0, 1, 2):
            raise MXNetError(
                f"zero_stage must be 0, 1 or 2, got {zero_stage!r}")
        self._zero = int(zero_stage)
        if accum_steps is None:
            accum_steps = int(get_env("MXTPU_ACCUM_STEPS"))
        if int(accum_steps) < 1:
            raise MXNetError(
                f"accum_steps must be >= 1, got {accum_steps!r}")
        self._accum = int(accum_steps)
        if comm_bucket_mb is None:
            comm_bucket_mb = float(get_env("MXTPU_COMM_BUCKET_MB"))
        if float(comm_bucket_mb) < 0:
            raise MXNetError(
                f"comm_bucket_mb must be >= 0 (0 = one fused "
                f"reduction), got {comm_bucket_mb!r}")
        self._bucket_mb = float(comm_bucket_mb)
        self._grad_buckets = None
        # forced checkpoint layout: None = auto (_host_local_checkpoint
        # decides from the process group); tests/bench set True to
        # exercise the self-contained npz writer in a single process
        self.host_local_ckpt: Optional[bool] = None
        self._hl_writer = None       # in-flight async npz commit thread
        self._hl_error = None
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._built = False
        self._t = 0
        self._ctx = current_context()
        self._guard = bool(guard_nonfinite)
        self._dyn_scale = bool(dynamic_loss_scale)
        self._init_ls = float(init_loss_scale) if dynamic_loss_scale else 1.0
        self._growth_interval = int(scale_growth_interval)
        self._scale_backoff = float(scale_backoff)
        self._min_ls = float(min_loss_scale)
        self._max_ls = float(max_loss_scale)
        self._gstate = None          # (loss_scale, clean_step_count) arrays
        self._last_finite = None     # device bool from the last guarded step

    def enable_nonfinite_guard(self, dynamic_loss_scale: bool = False,
                               init_loss_scale: float = 2.0 ** 15,
                               scale_growth_interval: int = 2000,
                               scale_backoff: float = 0.5) -> None:
        """Turn on the in-graph all-finite guard (see step_fn): a step
        whose loss or any gradient is non-finite leaves params, optimizer
        state and aux bit-identical instead of applying the update.  Must
        be called before the first step — the guard changes the jitted
        step function."""
        if self._built:
            raise MXNetError("enable_nonfinite_guard() must be called "
                             "before the first step() builds the jit")
        self._guard = True
        self._dyn_scale = bool(dynamic_loss_scale)
        self._init_ls = float(init_loss_scale) if dynamic_loss_scale else 1.0
        self._growth_interval = int(scale_growth_interval)
        self._scale_backoff = float(scale_backoff)

    # -- lazy build --------------------------------------------------------
    def _ensure_built(self, xs, y: _np.ndarray) -> None:
        if self._built:
            return
        import jax
        import jax.numpy as jnp

        # one tiny eager forward to settle deferred param shapes
        probes = [NDArray(jnp.asarray(v[:1]), ctx=self._ctx) for v in xs]
        self._block(*probes)

        all_params = list(self._block.collect_params().values())
        self._train_params: List[Parameter] = \
            [p for p in all_params if p.grad_req != "null"]
        self._aux_params: List[Parameter] = \
            [p for p in all_params if p.grad_req == "null"]
        self._optimizer.param_dict = {
            i: p for i, p in enumerate(self._train_params)}
        names = [p.name for p in self._train_params]
        self._fopt = make_functional_optimizer(self._optimizer, names)

        # row-sparse gradient layout (sparse_grad.py): params marked
        # grad_stype='row_sparse' whose gradient is produced in-graph as
        # a (values, unique_ids) pair and updated lazily.  The mark is
        # an intent; whether a given trace actually takes the sparse
        # path is decided per-retrace by the eval_shape probe in
        # make_grads (a hybridized table silently stays dense).
        self._sparse_marked = frozenset(
            i for i, p in enumerate(self._train_params)
            if getattr(p, "grad_stype", "default") == "row_sparse")
        if self._sparse_marked and not get_env("MXTPU_SPARSE_GRAD"):
            self._sparse_marked = frozenset()
        if self._sparse_marked and self._accum > 1:
            _warnings.warn(
                "sparse_grad embeddings fall back to dense gradients "
                "under accum_steps > 1 (the scan's carried accumulation "
                "buffer is dense)")
            self._sparse_marked = frozenset()
        if self._sparse_marked and self._fopt.kind not in ("sgd", "adam"):
            _warnings.warn(
                f"optimizer {self._fopt.kind!r} has no lazy row-sparse "
                f"lowering — sparse_grad embeddings fall back to dense")
            self._sparse_marked = frozenset()
        # trace-time record {param_idx: (bucket, vocab)} from the last
        # sparse probe — feeds the sparse.* metrics in step()
        self._sparse_trace_info = {}

        # input/label structure, captured once: reshard() re-derives the
        # shardings and rebuilds the jits on a new mesh without needing
        # fresh example data
        self._x_ndims = tuple(v.ndim for v in xs)
        self._y_multi = isinstance(y, tuple)
        self._y_ndims = tuple(v.ndim for v in y) if self._y_multi \
            else y.ndim

        self._make_shardings()

        # move weights onto the mesh — the trainer owns them from here on
        self._pvals = [jax.device_put(p.data(self._ctx)._read(), s)
                       for p, s in zip(self._train_params, self._p_sh)]
        self._avals = [jax.device_put(p.data(self._ctx)._read(), s)
                       for p, s in zip(self._aux_params, self._a_sh)]
        state = self._fopt.init(self._pvals)
        self._s_sh = self._state_shardings(state)
        self._state = jax.tree.map(
            lambda v, s: jax.device_put(v, s), state, self._s_sh)

        self._build_jits()
        self._built = True

    def _make_shardings(self) -> None:
        """Derive every sharding from the CURRENT mesh: parameter/aux
        (rules), inputs/labels (data_spec), and the ZeRO layout for
        optimizer state + stage-2 gradient buffers.  Split out of the
        lazy build so :meth:`reshard` can re-derive them when the mesh
        (dp size) changes."""
        mesh = self._mesh
        self._dp = axis_size(mesh, "dp")
        self._p_sh = [self._rules.sharding_for(mesh, p.name, p.shape)
                      for p in self._train_params]
        # RowShardedEmbedding: the table itself (not just its state)
        # partitions dim 0 over the marked axis, with zero_sharding's
        # per-parameter fallback (indivisible vocab / axis of size 1 /
        # dim 0 already ruled → replicated as before)
        for i, p in enumerate(self._train_params):
            ax = getattr(p, "_row_shard_axis", None)
            if ax is not None:
                self._p_sh[i] = zero_sharding(
                    mesh, self._rules.spec_for(p.name, p.shape), p.shape,
                    axis=ax)
        self._a_sh = [self._rules.sharding_for(mesh, p.name, p.shape)
                      for p in self._aux_params]
        # ZeRO layout: stage >= 1 partitions optimizer state (and the
        # stage-2 grad buffer) dim-0 over 'dp' — per-parameter fallback
        # to the parameter's own sharding when dim 0 cannot split
        if self._zero >= 1:
            self._z_sh = [
                zero_sharding(mesh, self._rules.spec_for(p.name, p.shape),
                              p.shape)
                for p in self._train_params]
            # a row-sharded table's state lives WITH its weight rows —
            # the param sharding already is the 1/dp layout
            for i, p in enumerate(self._train_params):
                if getattr(p, "_row_shard_axis", None) is not None:
                    self._z_sh[i] = self._p_sh[i]
        else:
            self._z_sh = list(self._p_sh)
        # per-input sharding: the data spec truncated to each input's rank
        self._x_sh = tuple(
            shard(mesh, *self._data_spec[:nd]) for nd in self._x_ndims)
        # tuple labels (multi-stream, e.g. MLM+NSP) shard element-wise
        if self._y_multi:
            self._y_sh = tuple(shard(mesh, *self._label_spec[:nd])
                               for nd in self._y_ndims)
        else:
            self._y_sh = shard(mesh, *self._label_spec[:self._y_ndims])
        self._r_sh = replicated(mesh)

    def _state_shardings(self, state):
        """Optimizer-state shardings: every leaf of param i's state tree
        carries the ZeRO sharding (== param sharding at stage 0)."""
        import jax
        return [jax.tree.map(lambda _, sh=sh: sh, st)
                for st, sh in zip(state, self._z_sh)]

    def _build_jits(self) -> None:
        import jax
        import jax.numpy as jnp

        block, loss_blk = self._block, self._loss
        tparams, aparams = self._train_params, self._aux_params
        fopt, ctx = self._fopt, self._ctx

        def apply_fn(pvals, avals, key, xv, training, yv=None):
            """Shared traced forward (+ optional loss) for train and eval.
            xv is a tuple of input arrays (multi-input models: BERT takes
            tokens/token_types/mask)."""
            tw = [NDArray(v, ctx=ctx) for v in pvals]
            aw = [NDArray(v, ctx=ctx) for v in avals]
            subs = {id(p): w for p, w in zip(tparams + aparams, tw + aw)}
            with _TraceCtx(subs), \
                    _autograd._RecordingScope(False, training), \
                    _KeyScope(key):
                out = block(*[NDArray(v, ctx=ctx) for v in xv])
                if yv is None:
                    l_nd = None
                elif isinstance(yv, tuple):
                    l_nd = loss_blk(out, tuple(NDArray(v, ctx=ctx)
                                               for v in yv))
                else:
                    l_nd = loss_blk(out, NDArray(yv, ctx=ctx))
            for w in tw:
                if w._version > 0:
                    raise MXNetError(
                        "in-place write to a trainable parameter inside the "
                        "sharded step is not supported")
            new_avals = [w._read() if w._version > 0 else v
                         for w, v in zip(aw, avals)]
            return out, l_nd, new_avals

        accum, zero = self._accum, self._zero
        dp = self._dp
        marked = self._sparse_marked if accum == 1 else frozenset()
        sparse_info = self._sparse_trace_info
        z_sh, p_sh = list(self._z_sh), list(self._p_sh)
        wsc = jax.lax.with_sharding_constraint
        # communication buckets for the gradient reduction (reverse
        # parameter order — the order backward materializes gradients);
        # a single bucket IS the fused path, kept as None so the
        # pre-bucketing trace stays byte-for-byte the same graph
        cap = self._bucket_mb * 2 ** 20 if self._bucket_mb else 0
        # sparse-marked params never ride the dense reduction buckets —
        # their (values, ids) grads have their own exchange
        dense_i = [i for i in range(len(self._pvals))
                   if i not in self._sparse_marked]
        bks = comm_buckets([int(self._pvals[i].nbytes) for i in dense_i],
                           cap)
        bks = [[dense_i[j] for j in b] for b in bks]
        self._grad_buckets = bks if len(bks) > 1 else None
        buckets = self._grad_buckets

        def constrain_grads(grads):
            """The gradient-reduction schedule.  Fused (``buckets is
            None``): one constraint sweep — at stage >= 1 XLA lowers
            every gradient's dp reduction to a reduce-scatter right
            before the update, all after the full backward (the PR-10
            trace).  Bucketed: each bucket is constrained separately
            and chained through ``jax.lax.optimization_barrier`` —
            bucket k's gradients are tied to bucket k-1's constrained
            output, so XLA can neither merge the per-bucket
            reductions back into one fused collective nor sink them
            all past the backward; the latency-hiding scheduler then
            issues bucket 0's collective (the last layers' grads, the
            first to materialize) while earlier layers' gradients are
            still being computed."""
            if buckets is None:
                # a (values, ids) sparse grad passes through unconstrained
                return [g if isinstance(g, tuple) else wsc(g, s)
                        for g, s in zip(grads, z_sh)]
            out = list(grads)
            prev = None
            for idx in buckets:
                vals = [out[i] for i in idx]
                if prev is not None:
                    tied = jax.lax.optimization_barrier(
                        tuple(vals) + (prev,))
                    vals = list(tied[:-1])
                vals = [wsc(v, z_sh[i]) for v, i in zip(vals, idx)]
                prev = vals[0]
                for i, v in zip(idx, vals):
                    out[i] = v
            return out
        if accum > 1:
            # microbatch shardings: after the (B, ...) -> (accum, B/accum,
            # ...) reshape the batch axis moves to dim 1; the scan axis
            # (dim 0) stays unsharded
            mb_x_sh = tuple(shard(self._mesh, None,
                                  *self._data_spec[:nd])
                            for nd in self._x_ndims)
            if self._y_multi:
                mb_y_sh = tuple(shard(self._mesh, None,
                                      *self._label_spec[:nd])
                                for nd in self._y_ndims)
            else:
                mb_y_sh = shard(self._mesh, None,
                                *self._label_spec[:self._y_ndims])

        def split_mb(v):
            return v.reshape((accum, v.shape[0] // accum) + v.shape[1:])

        def make_grads(scaled):
            """grads_of(pvals, avals, key, xv, yv, ls) ->
            (grads, mean_loss, new_avals) — the gradient of the
            FULL-batch SUM loss (reference semantics: loss.backward()
            seeds ones, Trainer.step(batch_size) folds the 1/batch
            rescale into the optimizer update; the MEAN is what we
            report).  ``scaled`` (trace-time bool) multiplies the
            differentiated loss by ``ls`` — the guarded path's loss
            scaling.  ``accum == 1`` traces EXACTLY the
            pre-accumulation graph (the zero_stage=0 bitwise contract);
            ``accum > 1`` scans the batch as microbatches with a
            per-microbatch RNG split, accumulating gradients — the sum
            over microbatch sum-loss gradients equals the full-batch
            gradient, so the optimizer's rescale is unchanged."""
            def grads_of(pvals, avals, key, xv, yv, ls):
                if accum == 1:
                    # trace-time probe: which sparse-marked tables does
                    # THIS trace's forward actually reach, and with how
                    # many ids?  eval_shape emits no ops and re-runs on
                    # every retrace, so a new batch shape re-sizes the
                    # id buckets.
                    sparse_idx, zb0 = [], []
                    if marked:
                        probe = _SparseGradTrace("probe")
                        with probe:
                            jax.eval_shape(
                                lambda pv: apply_fn(
                                    pv, avals, key, xv, True, yv)[1]._read(),
                                pvals)
                        for i in sorted(marked):
                            pid = id(tparams[i])
                            if pid in probe.buckets and \
                                    pid not in probe.multi:
                                sparse_idx.append(i)
                                zb0.append(jnp.zeros(
                                    (probe.buckets[pid],
                                     pvals[i].shape[1]), pvals[i].dtype))
                        sparse_info.clear()
                        sparse_info.update(
                            {i: (int(z.shape[0]), int(pvals[i].shape[0]))
                             for i, z in zip(sparse_idx, zb0)})
                    if sparse_idx:
                        def loss_of_sp(pv, zb):
                            tr = _SparseGradTrace("grad", {
                                id(tparams[i]): z
                                for i, z in zip(sparse_idx, zb)})
                            with tr:
                                _, l_nd, new_avals = apply_fn(
                                    pv, avals, key, xv, True, yv)
                            lraw = l_nd._read()
                            total = jnp.sum(lraw)
                            if scaled:
                                total = total * ls
                            uids = [tr.uids[id(tparams[i])]
                                    for i in sparse_idx]
                            return total, (jnp.mean(lraw), new_avals, uids)

                        (_, (lval, new_avals, uids)), (grads, zgrads) = \
                            jax.value_and_grad(loss_of_sp, argnums=(0, 1),
                                               has_aux=True)(pvals, zb0)
                        # the table itself sat behind stop_gradient: its
                        # dense cotangent is an unused zeros buffer XLA
                        # DCEs once we swap in the (values, ids) pair
                        grads = list(grads)
                        for i, zg, u in zip(sparse_idx, zgrads, uids):
                            grads[i] = (zg, u)
                        if zero >= 2:
                            grads = [g if isinstance(g, tuple)
                                     else wsc(g, s)
                                     for g, s in zip(grads, z_sh)]
                        return grads, lval, new_avals

                    def loss_of(pv):
                        _, l_nd, new_avals = apply_fn(pv, avals, key, xv,
                                                      True, yv)
                        lraw = l_nd._read()
                        total = jnp.sum(lraw)
                        if scaled:
                            total = total * ls
                        return total, (jnp.mean(lraw), new_avals)

                    (_, (lval, new_avals)), grads = \
                        jax.value_and_grad(loss_of, has_aux=True)(pvals)
                    if zero >= 2:
                        # ZeRO-2: the gradient is reduce-scattered the
                        # moment it exists — never replicated
                        grads = [wsc(g, s) for g, s in zip(grads, z_sh)]
                    return grads, lval, new_avals

                def mb(v, s):
                    # constrain the microbatched view back onto the dp
                    # layout only when the microbatch still divides the
                    # axis — an uneven constraint would force XLA into a
                    # full rematerialization instead of a local reshape
                    m = split_mb(v)
                    return wsc(m, s) if m.shape[1] % dp == 0 else m

                keys = jax.random.split(key, accum)
                xms = tuple(mb(v, s) for v, s in zip(xv, mb_x_sh))
                if isinstance(yv, tuple):
                    yms = tuple(mb(v, s) for v, s in zip(yv, mb_y_sh))
                else:
                    yms = mb(yv, mb_y_sh)

                def body(carry, mb):
                    g_acc, av, lsum = carry
                    k_m, xm, ym = mb

                    def loss_of(pv):
                        _, l_nd, new_av = apply_fn(pv, av, k_m, xm, True,
                                                   ym)
                        lraw = l_nd._read()
                        total = jnp.sum(lraw)
                        if scaled:
                            total = total * ls
                        return total, (jnp.mean(lraw).astype(jnp.float32),
                                       new_av)

                    (_, (lmean, new_av)), g = \
                        jax.value_and_grad(loss_of, has_aux=True)(pvals)
                    g_acc = [a + b for a, b in zip(g_acc, g)]
                    if zero >= 2:
                        # ZeRO-2: the carried accumulation buffer stays
                        # sharded — 1/dp of the grads per chip across
                        # the whole scan
                        g_acc = [wsc(a, s) for a, s in zip(g_acc, z_sh)]
                    return (g_acc, new_av, lsum + lmean), None

                g0 = [jnp.zeros_like(p) for p in pvals]
                if zero >= 2:
                    g0 = [wsc(a, s) for a, s in zip(g0, z_sh)]
                (grads, new_avals, lsum), _ = jax.lax.scan(
                    body, (g0, list(avals), jnp.float32(0.0)),
                    (keys, xms, yms))
                # equal microbatches: full-batch mean = mean of means
                return grads, lsum / accum, new_avals
            return grads_of

        def run_update(pvals, grads, state, t, lr, rescale):
            """The (optionally ZeRO-sharded) optimizer update.  Stage 0
            is the plain call — bitwise the pre-ZeRO step.  Stage >= 1
            pins the collective schedule with sharding constraints:
            grads constrained to the ZeRO layout (XLA lowers the dp
            gradient reduction to a reduce-SCATTER into each chip's
            slice instead of a full psum), each chip updates its slice
            of params/state, and the updated params constrained back to
            the parameter layout (the all-gather) — all inside the one
            donated jit, so XLA overlaps the collectives with
            compute."""
            if zero >= 1 or buckets is not None:
                # stage 0 with bucketing on: the constraint target is
                # the param's own (replicated) sharding — the barrier
                # chain still pins WHERE each bucket's psum lands in
                # the schedule
                grads = constrain_grads(grads)
            sp = frozenset(i for i, g in enumerate(grads)
                           if isinstance(g, tuple))
            new_pvals, new_state = fopt.update(pvals, grads, state, t,
                                               lr, rescale, sparse=sp)
            if zero >= 1:
                new_pvals = [wsc(wsc(w, zs), ps) for w, zs, ps in
                             zip(new_pvals, z_sh, p_sh)]
            return new_pvals, new_state

        if not self._guard:
            grads_of = make_grads(scaled=False)

            def step_fn(pvals, avals, state, key, t, lr, rescale, xv, yv):
                grads, lval, new_avals = grads_of(pvals, avals, key, xv,
                                                  yv, None)
                new_pvals, new_state = run_update(pvals, grads, state, t,
                                                  lr, rescale)
                return new_pvals, new_avals, new_state, lval

            self._jit_step = jax.jit(
                step_fn,
                in_shardings=(self._p_sh, self._a_sh, self._s_sh,
                              self._r_sh, self._r_sh, self._r_sh,
                              self._r_sh, self._x_sh, self._y_sh),
                out_shardings=(self._p_sh, self._a_sh, self._s_sh,
                               self._r_sh),
                donate_argnums=(0, 1, 2))
        else:
            # guarded step: differentiate loss * loss_scale, unscale inside
            # the optimizer rescale, and gate the WHOLE update on an
            # all-finite reduction over loss+grads — a poisoned step passes
            # params/momenta/aux through bit-identical.  The gate is a
            # jnp.where inside the one XLA computation, so skipping costs
            # no extra host sync or dispatch.
            dyn = self._dyn_scale
            growth_n = self._growth_interval
            backoff = self._scale_backoff
            min_ls, max_ls = self._min_ls, self._max_ls
            grads_of = make_grads(scaled=True)

            def step_fn(pvals, avals, state, key, t, lr, rescale, gstate,
                        xv, yv):
                ls, good = gstate
                grads, lval, new_avals = grads_of(pvals, avals, key, xv,
                                                  yv, ls)
                finite = jnp.isfinite(lval)
                for g in jax.tree.leaves(grads):
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
                new_pvals, new_state = run_update(
                    pvals, grads, state, t, lr, rescale / ls)

                def keep(new, old):
                    return jnp.where(finite, new, old)

                new_pvals = [keep(n, o) for n, o in zip(new_pvals, pvals)]
                new_state = jax.tree.map(keep, new_state, state)
                new_avals = [keep(n, o) for n, o in zip(new_avals, avals)]
                if dyn:
                    good = jnp.where(finite, good + 1, 0)
                    grow = jnp.logical_and(finite, good >= growth_n)
                    new_ls = jnp.where(
                        grow, jnp.minimum(ls * 2.0, max_ls),
                        jnp.where(finite, ls,
                                  jnp.maximum(ls * backoff, min_ls)))
                    good = jnp.where(grow, jnp.zeros_like(good), good)
                else:
                    new_ls = ls
                    good = jnp.where(finite, good + 1, 0)
                return (new_pvals, new_avals, new_state, lval,
                        (new_ls, good), finite)

            self._jit_step = jax.jit(
                step_fn,
                in_shardings=(self._p_sh, self._a_sh, self._s_sh,
                              self._r_sh, self._r_sh, self._r_sh,
                              self._r_sh, (self._r_sh, self._r_sh),
                              self._x_sh, self._y_sh),
                out_shardings=(self._p_sh, self._a_sh, self._s_sh,
                               self._r_sh, (self._r_sh, self._r_sh),
                               self._r_sh),
                donate_argnums=(0, 1, 2))
            if self._gstate is None:
                self._gstate = (
                    jax.device_put(jnp.asarray(self._init_ls, jnp.float32),
                                   self._r_sh),
                    jax.device_put(jnp.asarray(0, jnp.int32), self._r_sh))

        def fwd_fn(pvals, avals, key, xv):
            out, _, _ = apply_fn(pvals, avals, key, xv, False)
            if isinstance(out, (list, tuple)):
                return tuple(o._read() for o in out)
            return out._read()

        self._jit_fwd = jax.jit(
            fwd_fn, in_shardings=(self._p_sh, self._a_sh,
                                  self._r_sh, self._x_sh))

    # -- public API --------------------------------------------------------
    @property
    def mesh(self):
        return self._mesh

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def built(self) -> bool:
        """True once the first step() has built the jit and taken
        ownership of the weights."""
        return self._built

    @property
    def num_update(self) -> int:
        """The optimizer update counter (steps taken / restored)."""
        return self._t

    @property
    def guard_enabled(self) -> bool:
        return self._guard

    @property
    def zero_stage(self) -> int:
        """ZeRO optimizer-state partitioning stage (0, 1 or 2)."""
        return self._zero

    @property
    def accum_steps(self) -> int:
        """Microbatches per step (1 = no accumulation)."""
        return self._accum

    @property
    def dp_size(self) -> int:
        """Size of the mesh's 'dp' axis (1 before the first build only
        if the mesh has no dp axis)."""
        return axis_size(self._mesh, "dp")

    @property
    def comm_bucket_mb(self) -> float:
        """Gradient-reduction bucket cap in MB (0 = one fused
        reduction, the pre-bucketing trace)."""
        return self._bucket_mb

    @property
    def grad_buckets(self):
        """The live bucket partition (index lists in reverse parameter
        order), or None on the fused path.  Introspection only."""
        return None if self._grad_buckets is None \
            else [list(b) for b in self._grad_buckets]

    def set_comm_bucket_mb(self, mb: float) -> None:
        """Change the communication bucket cap on a live trainer — the
        CommBucketController's apply target.  Rebuilds the jitted step
        (a recompile) only when the cap actually changes the bucket
        PARTITION; a cap move that lands on the same partition is
        free.  Training state is untouched (the jit closes over
        shardings, not values)."""
        mb = float(mb or 0.0)
        if mb < 0:
            # same contract as the constructor: a negative cap is a
            # caller bug, not a request to turn bucketing off
            raise MXNetError(
                f"comm_bucket_mb must be >= 0 (0 = one fused "
                f"reduction), got {mb!r}")
        if mb == self._bucket_mb:
            return
        self._bucket_mb = mb
        if not self._built:
            return
        cap = mb * 2 ** 20 if mb else 0
        dense_i = [i for i in range(len(self._pvals))
                   if i not in self._sparse_marked]
        bks = comm_buckets([int(self._pvals[i].nbytes) for i in dense_i],
                           cap)
        bks = [[dense_i[j] for j in b] for b in bks]
        new = bks if len(bks) > 1 else None
        if new == self._grad_buckets:
            return
        self._build_jits()

    def opt_state_bytes_per_device(self) -> dict:
        """Actually-resident optimizer-state bytes per device id — the
        ZeRO acceptance metric.  At stage 0 every chip carries the full
        state; at stage >= 1 each chip carries ~1/dp of every
        partitionable tensor."""
        import jax
        if not self._built:
            raise MXNetError("run at least one step() before "
                             "opt_state_bytes_per_device()")
        out: dict = {}
        for leaf in jax.tree.leaves(self._state):
            for sh in leaf.addressable_shards:
                d = sh.device.id
                out[d] = out.get(d, 0) + int(sh.data.nbytes)
        return out

    def peak_opt_state_bytes(self) -> int:
        """max over devices of :meth:`opt_state_bytes_per_device`."""
        per_dev = self.opt_state_bytes_per_device()
        return max(per_dev.values()) if per_dev else 0

    def table_bytes_per_device(self) -> dict:
        """Actually-resident embedding-table bytes per device id, over
        the ROW-SHARDED tables (RowShardedEmbedding) — the dp-sharded
        table acceptance metric, sibling of
        :meth:`opt_state_bytes_per_device`."""
        if not self._built:
            raise MXNetError("run at least one step() before "
                             "table_bytes_per_device()")
        out: dict = {}
        for p, v in zip(self._train_params, self._pvals):
            if getattr(p, "_row_shard_axis", None) is None:
                continue
            for sh in v.addressable_shards:
                d = sh.device.id
                out[d] = out.get(d, 0) + int(sh.data.nbytes)
        return out

    def peak_table_bytes(self) -> int:
        """max over devices of :meth:`table_bytes_per_device` — what one
        chip actually holds of the row-sharded tables (``vocab/dp``
        rows each when the shard formed, the full table on fallback)."""
        per_dev = self.table_bytes_per_device()
        return max(per_dev.values()) if per_dev else 0

    def reshard(self, mesh=None) -> None:
        """Rebuild shardings and the jitted step on ``mesh`` and
        re-place the live training state onto the new layout.  A
        ``mesh`` equal to the current one (or None) is a no-op on a
        built trainer — safe to call unconditionally after a fleet
        re-form.  This is the in-graph re-shard hook the elastic
        fleet uses after a re-form changes the dp world size, and what
        makes a checkpoint saved at one dp size restorable at another
        (load_checkpoint builds its restore template from the CURRENT
        shardings, so a re-sharded trainer restores any layout).

        Fleet-synchronized like a collective: every host must reshard
        together (the rebuilt step's collectives span the new mesh), so
        the collective-safety lint rule keeps it off rank-divergent
        branches.  Unbuilt trainers just adopt the mesh — the first
        step builds everything on it."""
        unchanged = mesh is None or mesh == self._mesh
        if mesh is not None:
            self._mesh = mesh
        if not self._built or unchanged:
            # identical mesh = identical layout: skip the full state
            # host round-trip and jit rebuild.  The elastic re-form
            # hook calls reshard() unconditionally after every re-form;
            # on host-local meshes (each process owns its devices) the
            # local mesh survives a peer's death unchanged, and paying
            # a recompile for a bit-identical layout would only stretch
            # the re-form timeline
            return
        import jax
        host = jax.device_get({
            "p": list(self._pvals), "a": list(self._avals),
            "s": self._state,
            "g": list(self._gstate) if self._gstate is not None else None,
        })
        self._make_shardings()
        self._s_sh = self._state_shardings(host["s"])
        self._pvals = [jax.device_put(v, s)
                       for v, s in zip(host["p"], self._p_sh)]
        self._avals = [jax.device_put(v, s)
                       for v, s in zip(host["a"], self._a_sh)]
        self._state = jax.tree.map(
            lambda v, s: jax.device_put(v, s), host["s"], self._s_sh)
        if host["g"] is not None:
            self._gstate = tuple(jax.device_put(v, self._r_sh)
                                 for v in host["g"])
        self._build_jits()

    @property
    def last_step_finite(self):
        """Device bool from the last guarded step: False means the update
        was skipped (non-finite loss/grads).  None before the first
        guarded step or with the guard off.  Reading it with bool()/
        device_get syncs — the resilience layer batches these."""
        return self._last_finite

    @property
    def loss_scale(self) -> float:
        """Current (dynamic) loss scale; 1.0 unless the guard was enabled
        with dynamic_loss_scale.  Syncs the device scalar."""
        if self._gstate is None:
            return self._init_ls if self._guard else 1.0
        import jax
        return float(jax.device_get(self._gstate[0]))

    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr: float) -> None:
        self._optimizer.set_learning_rate(lr)

    def shard_batch(self, x, y):
        """Pre-place a batch onto the mesh with the trainer's input
        shardings; feeding the returned arrays to step() skips the
        host→device transfer (how a real input pipeline should feed)."""
        import jax
        xv = _to_vals(x)
        yv = _to_val(y)
        self._ensure_built(xv, yv)
        xs = tuple(jax.device_put(v, s)
                   for v, s in zip(xv, self._x_sh))
        if self._y_multi:
            ys = tuple(jax.device_put(v, s)
                       for v, s in zip(yv, self._y_sh))
        else:
            ys = jax.device_put(yv, self._y_sh)
        return (xs if len(xs) > 1 else xs[0], ys)

    def place_batch(self, batch):
        """Sharding-aware device placement for ONE loader batch — the
        DataLoader device-prefetch stage's ``put_fn``
        (``loader.set_device_put_fn(trainer.place_batch)``; the
        ResilientTrainer wires this automatically for an attached
        loader).  A ``(x, y)`` pair routes through :meth:`shard_batch`
        (building the trainer on first use); any other batch shape
        falls back to leaf-wise default-device placement, so a loader
        that yields something this trainer cannot shard still
        double-buffers plain transfers."""
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            return self.shard_batch(batch[0], batch[1])
        from ..gluon.data.dataloader import default_device_put
        return default_device_put(batch)

    @hot_path("step")
    def step(self, x, y, batch_size: Optional[int] = None):
        """Run one sharded train step; returns the (device) mean loss.
        `x` may be a single array or a tuple of inputs."""
        import jax
        import jax.numpy as jnp
        xv = _to_vals(x)
        yv = _to_val(y)
        self._ensure_built(xv, yv)
        if len(xv) != len(self._x_sh):
            raise MXNetError(
                f"step() got {len(xv)} inputs but the trainer was built "
                f"with {len(self._x_sh)} — optional inputs must be passed "
                f"consistently from the first call")
        if isinstance(yv, tuple) != self._y_multi or \
                (self._y_multi and len(yv) != len(self._y_sh)):
            want = (f"a tuple of {len(self._y_sh)} label streams"
                    if self._y_multi else "a single label array")
            raise MXNetError(
                f"step() label structure changed: the trainer was built "
                f"with {want} — labels must keep the first call's shape")
        if self._accum > 1 and int(xv[0].shape[0]) % self._accum:
            raise MXNetError(
                f"step() batch of {int(xv[0].shape[0])} does not divide "
                f"into accum_steps={self._accum} microbatches — pad the "
                f"batch or change accum_steps")
        if batch_size is None:
            batch_size = int(xv[0].shape[0])
        self._t += 1
        self._optimizer.num_update = self._t
        key = _grandom.next_key()
        xv = tuple(jax.device_put(v, s) for v, s in zip(xv, self._x_sh))
        if self._y_multi:
            yv = tuple(jax.device_put(v, s)
                       for v, s in zip(yv, self._y_sh))
        else:
            yv = jax.device_put(yv, self._y_sh)
        t = jnp.asarray(self._t, dtype=jnp.int32)
        lr = jnp.asarray(self._optimizer.learning_rate, dtype=jnp.float32)
        rescale = jnp.asarray(self._scale / batch_size, dtype=jnp.float32)
        if self._guard:
            (self._pvals, self._avals, self._state, lval, self._gstate,
             self._last_finite) = self._jit_step(
                self._pvals, self._avals, self._state, key, t, lr,
                rescale, self._gstate, xv, yv)
        else:
            self._pvals, self._avals, self._state, lval = self._jit_step(
                self._pvals, self._avals, self._state, key, t, lr, rescale,
                xv, yv)
        if self._sparse_trace_info:
            self._record_sparse_metrics()
        return NDArray(lval, ctx=self._ctx)

    def _record_sparse_metrics(self) -> None:
        """Host-side sparse.* metrics from the last trace's probe record
        — static shapes only, no device sync.  ``exchange_bytes`` counts
        what the sparse layout PUTS ON THE WIRE per step (ids + rows,
        once per dp peer pair is XLA's business; we count the logical
        payload), vs the dense table-sized reduction it replaced."""
        reg = _metrics_registry()
        rows = buckets_b = dense_b = 0
        vocab_sum = 0
        for i, (bucket, vocab) in self._sparse_trace_info.items():
            v = self._pvals[i]
            width = int(v.shape[1])
            item = int(_np.dtype(v.dtype).itemsize)
            # the pow2 bucket can exceed a tiny vocab; a table never
            # carries more live rows than it has
            rows += min(bucket, vocab)
            buckets_b += bucket * (4 + width * item)
            dense_b += vocab * width * item
            vocab_sum += vocab
        reg.counter(
            "sparse.grad_rows",
            "embedding rows carried by row-sparse gradients").inc(rows)
        if self._dp > 1:
            reg.counter(
                "sparse.exchange_bytes",
                "bytes of (ids, rows) row-sparse gradient payload "
                "exchanged instead of dense table reductions").inc(
                    buckets_b)
            reg.counter(
                "sparse.exchange_bytes_dense_equiv",
                "bytes the SAME gradients would have cost as dense "
                "reductions — the wire win denominator").inc(dense_b)
        if vocab_sum:
            reg.gauge(
                "sparse.grad_density",
                "id-bucket rows / vocab across sparse tables (last "
                "step)").set(rows / vocab_sum)

    # -- supervised-retry support (ResilientTrainer) -----------------------
    def step_state(self):
        """Host-side snapshot of everything a FAILED step() attempt may
        have advanced before dying: the update counter and the global RNG
        stream key.  Cheap (two references); taken by the resilience
        layer before every supervised attempt so a mid-step failure can
        be rolled back instead of desyncing the retry (ROADMAP 'Known
        gap' from PR 1)."""
        return (self._t, _grandom.get_state())

    @property
    def donation_consumed(self) -> bool:
        """True once a failed jitted step has consumed (deleted) the
        donated parameter buffers: the training state no longer exists on
        device, so a retry cannot run — restore from a checkpoint
        instead.  Always False before the first build and on backends
        that ignore donation (CPU)."""
        if not self._built:
            return False
        for v in self._pvals:
            is_deleted = getattr(v, "is_deleted", None)
            if is_deleted is not None and is_deleted():
                return True
        return False

    def rollback_step(self, state) -> None:
        """Undo the host-side effects of a failed step() attempt —
        restore the update counter and RNG stream from a
        :meth:`step_state` snapshot so the retry replays the attempt
        bit-for-bit.  Refuses (clear error, not a crash later) when the
        failed attempt already consumed its donated buffers."""
        if self.donation_consumed:
            raise MXNetError(
                "cannot roll back this step: the failed attempt already "
                "consumed (donated) the parameter buffers — the training "
                "state is gone; restore from the newest committed "
                "checkpoint (ResilientTrainer auto_resume) instead of "
                "retrying")
        t, key = state
        self._t = t
        self._optimizer.num_update = t
        _grandom.set_state(key)

    def forward(self, x):
        """Sharded inference forward with the trainer-owned weights."""
        import jax
        xv = _to_vals(x)
        if not self._built:
            raise MXNetError("run at least one step() before forward(), or "
                             "use the block directly")
        if len(xv) != len(self._x_sh):
            raise MXNetError(
                f"forward() got {len(xv)} inputs but the trainer was built "
                f"with {len(self._x_sh)}")
        key = _grandom.next_key()
        out = self._jit_fwd(self._pvals, self._avals, key,
                            tuple(jax.device_put(v, s)
                                  for v, s in zip(xv, self._x_sh)))
        if isinstance(out, tuple):
            return tuple(NDArray(o, ctx=self._ctx) for o in out)
        return NDArray(out, ctx=self._ctx)

    def _checkpointer(self):
        # one long-lived async checkpointer: save() returns once the
        # arrays are snapshotted and the write overlaps training; call
        # wait_checkpoint() (or let process exit paths flush) to block.
        #
        # Multi-process groups get explicit MultiprocessingOptions:
        # orbax's default process sync is a DEVICE collective
        # (sync_global_devices), which the multi-process CPU backend
        # cannot run at all and which, on any backend, spans the FULL
        # launcher world — a dead host would wedge every later save.
        # Passing active_processes routes every orbax barrier through
        # the coordination service over the ACTIVE member set (the same
        # tiering dist.py uses), and each host is its own primary
        # because checkpoint directories are per-host in this stack
        # (ResilientTrainer's per-rank layout): every host writes its
        # own commit metadata.  Rebuilt whenever a fleet re-form
        # changes the member set — the old instance's barrier set
        # still contains the dead host.
        from . import dist
        members = tuple(dist.active_members()) \
            if dist.is_initialized() else None
        if getattr(self, "_ckptr", None) is not None and \
                getattr(self, "_ckptr_members", None) != members:
            try:
                self._ckptr.wait_until_finished()
            except Exception:   # noqa: BLE001 — an in-flight write
                pass            # racing a re-form is abandoned; resume
            self._ckptr = None  # only ever reads COMMITTED checkpoints
        if getattr(self, "_ckptr", None) is None:
            import orbax.checkpoint as ocp
            if members is not None and len(members) > 1:
                mp = ocp.options.MultiprocessingOptions(
                    primary_host=dist.phys_rank(),
                    active_processes=set(members),
                    barrier_sync_key_prefix=(
                        f"mxtpu_f{dist.fence_generation()}"))
                self._ckptr = ocp.StandardCheckpointer(
                    multiprocessing_options=mp)
            else:
                self._ckptr = ocp.StandardCheckpointer()
            self._ckptr_members = members
        return self._ckptr

    def _ckpt_inflight_gauge(self):
        return _metrics_registry().gauge(
            "resilience.ckpt_inflight",
            help="async checkpoint writes enqueued but not yet "
                 "committed (0 or 1 — one orbax checkpointer per "
                 "trainer process)")

    def wait_checkpoint(self) -> None:
        """Block until any in-flight async checkpoint write commits
        (the orbax writer AND the host-local npz commit thread)."""
        self._wait_host_local()
        if getattr(self, "_ckptr", None) is not None:
            self._ckptr.wait_until_finished()
            self._ckpt_inflight_gauge().set(0)

    def _join_host_local(self) -> None:
        """Drain the background npz commit thread WITHOUT raising —
        the step-path variant: a periodic save must be able to start
        its own write after a failed predecessor (the previous
        committed dir is intact; that is the whole crash contract).
        The stored error stays armed for the next explicit flush."""
        th, self._hl_writer = self._hl_writer, None
        if th is not None:
            th.join()
            self._ckpt_inflight_gauge().set(0)

    def _wait_host_local(self) -> None:
        """Join the background npz commit thread (MXTPU_ASYNC_CKPT)
        and surface its failure, if any, HERE — the same contract as
        orbax's wait_until_finished: the write path never raises into
        the training step, only into the explicit flush."""
        self._join_host_local()
        err, self._hl_error = self._hl_error, None
        if err is not None:
            raise MXNetError(
                f"async host-local checkpoint write failed: "
                f"{err!r}") from err

    def _host_local_checkpoint(self) -> bool:
        """True when this trainer's state must be saved as HOST values:
        a multi-process group whose mesh is local to this host (each
        process trains its own replica — the elastic-fleet CPU layout).
        Orbax refuses to serialize such 'host-local' jax arrays, and
        they carry no cross-host sharding worth preserving anyway.  A
        mesh that genuinely spans processes (TPU pod) keeps the sharded
        orbax path.  ``self.host_local_ckpt`` (a plain attribute)
        overrides the auto-detection either way — how the bench and
        the torn-dir tests exercise the npz writer in one process."""
        if self.host_local_ckpt is not None:
            return bool(self.host_local_ckpt)
        from . import dist
        if not dist.is_initialized():
            return False
        import jax
        if jax.process_count() <= 1:
            return False
        local = set(jax.local_devices())
        return all(d in local for d in self._mesh.devices.flat)

    def save_checkpoint(self, directory: str) -> None:
        """Write the trainer-owned SHARDED state (params, aux, optimizer
        state, update counter, RNG stream) with orbax — the §5.4
        'async-writes internally' story for multi-chip training.  Each
        host writes its own shards; the write is ASYNC and lands in a
        step-suffixed subdir, so a crash mid-save never destroys the
        previous checkpoint."""
        import os
        if not self._built:
            raise MXNetError("run at least one step() before "
                             "save_checkpoint()")
        directory = os.path.abspath(directory)
        tree = {"params": list(self._pvals),
                "aux": list(self._avals),
                "opt_state": self._state,
                "rng": _grandom.get_state(),
                "t": self._t}
        if self._guard and self._gstate is not None:
            # loss scale + clean-step counter ride along so a resumed run
            # replays the dynamic-scale trajectory bit-for-bit
            tree["guard"] = list(self._gstate)
        if self._host_local_checkpoint():
            # _save_host_local owns the inflight gauge on the async
            # path (set to 1 before its thread starts — no race with
            # the thread's own set(0)); synchronous writes are
            # committed by the time it returns
            if not self._save_host_local(directory, tree):
                self._ckpt_inflight_gauge().set(0)
            return
        self._checkpointer().save(
            os.path.join(directory, f"state-{self._t:08d}"), tree,
            force=True)
        # the write overlaps training from here until the next
        # wait_checkpoint() — the ROADMAP's checkpoint-in-flight gauge
        self._ckpt_inflight_gauge().set(1)

    _HOST_LOCAL_NPZ = "host_local.npz"

    def _save_host_local(self, directory: str, tree: dict) -> bool:
        """Per-host atomic checkpoint for multi-process groups whose
        mesh is host-local: orbax refuses to serialize host-local jax
        arrays, and its replicated-numpy handler writes on GLOBAL
        process 0 only — neither fits a fleet of independent per-host
        replicas.  This path writes the host's full state itself (npz
        into a tmp dir, commit marker, atomic rename), producing
        exactly the committed-dir shape ``committed_checkpoints`` /
        ``latest_checkpoint`` already filter on.  Barrier-free by
        design: per-host independence is the elastic-fleet story — no
        cross-host coordination can wedge this save when a peer is
        dead.

        Synchronous by default.  With ``MXTPU_ASYNC_CKPT`` the
        device_get SNAPSHOT still happens here, at the step boundary
        (the next donated step invalidates these buffers), but the npz
        serialization + commit rename — the part whose cost scales
        with model size — move to a background thread; the boundary
        stall shrinks to the host copy.  A crash mid-write leaves the
        tmp dir uncommitted (no marker, no rename), which resume
        already filters out, so the previous committed ``state-<t>``
        always survives.  Returns True when the write went async."""
        import os
        import jax
        flat = {f"p{i}": v for i, v in enumerate(tree["params"])}
        flat.update({f"a{i}": v for i, v in enumerate(tree["aux"])})
        flat.update({f"s{i}": v for i, v in
                     enumerate(jax.tree.leaves(tree["opt_state"]))})
        flat["rng"] = tree["rng"]
        flat["t"] = tree["t"]
        if "guard" in tree:
            flat.update({f"g{i}": v for i, v in enumerate(tree["guard"])})
        flat = jax.device_get(flat)          # the boundary snapshot
        final = os.path.join(directory, f"state-{self._t:08d}")
        tmp = f"{final}.mxtpu-tmp-{os.getpid()}"
        if not bool(get_env("MXTPU_ASYNC_CKPT")):
            self._write_host_local(flat, tmp, final)
            return False
        # one write in flight at a time (the orbax contract): a second
        # save first drains the previous commit — without raising (a
        # failed predecessor must not abort the step-path save that
        # replaces it; its error stays armed for the explicit flush)
        self._join_host_local()
        hist = _metrics_registry().histogram(
            "ckpt.async_commit_us",
            help="background npz checkpoint commit time (serialize + "
                 "marker + atomic rename) — the write the async path "
                 "takes OFF the step boundary")

        def commit():
            t0 = _time.perf_counter()
            try:
                self._write_host_local(flat, tmp, final)
                hist.observe((_time.perf_counter() - t0) * 1e6)
            except BaseException as exc:   # noqa: BLE001 — re-raised
                self._hl_error = exc       # by the next wait_checkpoint
            finally:
                self._ckpt_inflight_gauge().set(0)

        th = _threading.Thread(target=commit, name="mxtpu-ckpt-writer",
                               daemon=True)
        self._hl_writer = th
        # gauge up BEFORE the thread starts: a fast commit's set(0)
        # must never be overwritten by a caller-side set(1) racing it
        self._ckpt_inflight_gauge().set(1)
        th.start()
        return True

    @staticmethod
    def _write_host_local(flat: dict, tmp: str, final: str) -> None:
        """The commit sequence: npz into the tmp dir, marker, atomic
        rename.  Interruptible at any point without losing the
        previous committed dir — the marker is written only after the
        full npz, and the rename is the single commit point."""
        import os
        import shutil
        import numpy as _nnp
        os.makedirs(tmp, exist_ok=True)
        _nnp.savez(os.path.join(tmp, ShardedTrainer._HOST_LOCAL_NPZ),
                   **flat)
        with open(os.path.join(tmp, _COMMIT_MARKER), "w") as f:
            f.write("mxtpu host-local checkpoint\n")
        if os.path.isdir(final):
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)

    def _load_host_local(self, path: str) -> None:
        """Restore a :meth:`_save_host_local` checkpoint onto this
        trainer's shardings."""
        import os
        import jax
        import jax.numpy as jnp
        import numpy as _nnp
        data = _nnp.load(os.path.join(path, self._HOST_LOCAL_NPZ))
        self._pvals = [jax.device_put(data[f"p{i}"], s)
                       for i, s in enumerate(self._p_sh)]
        self._avals = [jax.device_put(data[f"a{i}"], s)
                       for i, s in enumerate(self._a_sh)]
        s_flat, s_def = jax.tree.flatten(self._state)
        sh_flat = jax.tree.leaves(self._s_sh)
        self._state = jax.tree.unflatten(
            s_def, [jax.device_put(data[f"s{i}"], sh)
                    for i, sh in enumerate(sh_flat[:len(s_flat)])])
        _grandom.set_state(jnp.asarray(data["rng"]))
        self._t = int(data["t"])
        self._optimizer.num_update = self._t
        if "g0" in data and self._guard:
            self._gstate = tuple(
                jax.device_put(jnp.asarray(data[f"g{i}"]), self._r_sh)
                for i in range(2))

    @staticmethod
    def committed_checkpoints(directory: str) -> List[str]:
        """Sorted (oldest → newest) step dirs under ``directory`` that
        orbax fully COMMITTED.  Two filters, both load-bearing for crash
        safety: the name must be exactly ``state-<digits>`` (orbax's
        ``*.orbax-checkpoint-tmp-*`` rename staging also starts with
        ``state-`` and sorts NEWER than its target), and the commit
        marker file must exist (covers torn writes on filesystems where
        the rename is not atomic)."""
        import os
        if not os.path.isdir(directory):
            return []
        steps = []
        for d in os.listdir(directory):
            if not _STEP_DIR_RE.match(d):
                continue
            if not os.path.exists(os.path.join(directory, d,
                                               _COMMIT_MARKER)):
                continue
            steps.append(d)
        return [os.path.join(directory, d) for d in sorted(steps)]

    @staticmethod
    def latest_checkpoint(directory: str):
        """Newest COMMITTED step dir under ``directory`` (or None).  A
        crash mid-async-write leaves a partial dir behind; it is skipped
        and the next-older committed checkpoint wins."""
        steps = ShardedTrainer.committed_checkpoints(directory)
        return steps[-1] if steps else None

    def load_checkpoint(self, directory: str) -> None:
        """Restore the NEWEST checkpoint under ``directory`` directly
        into the trainer's shardings (arrays land on their mesh
        positions — no host round-trip).  The trainer must be built with
        the same model/mesh/rules (run one step on dummy data first, as
        the reference's bind-then-load flow does)."""
        import orbax.checkpoint as ocp   # noqa: F401  (orbax presence)
        if not self._built:
            raise MXNetError("build the trainer (one step on dummy data) "
                             "before load_checkpoint()")
        import jax
        path = self.latest_checkpoint(directory)
        if path is None:
            raise MXNetError(f"no checkpoint under {directory!r}")
        self.wait_checkpoint()
        import os
        if os.path.exists(os.path.join(path, self._HOST_LOCAL_NPZ)):
            # written by _save_host_local (per-host multi-process
            # checkpoint) — restore without orbax
            self._load_host_local(path)
            return
        rng_now = _grandom.get_state()
        if rng_now is None:              # seed the stream so the
            _grandom.next_key()          # template has a concrete leaf
            rng_now = _grandom.get_state()
        template = {
            "params": [jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=s)
                       for v, s in zip(self._pvals, self._p_sh)],
            "aux": [jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=s)
                    for v, s in zip(self._avals, self._a_sh)],
            "opt_state": jax.tree.map(
                lambda v, s: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                                  sharding=s),
                self._state, self._s_sh),
            "rng": rng_now,
            "t": 0,
        }
        # the template must match the SAVED tree exactly (orbax rejects
        # both extra and missing keys), so ask the checkpoint whether it
        # carries guard state rather than assuming this trainer's config:
        # guard-on trainers must restore guard-less checkpoints and vice
        # versa
        try:
            saved_has_guard = \
                "guard" in self._checkpointer().metadata(path)
        except Exception:   # noqa: BLE001 — metadata unavailable: fall
            # back to mirroring this trainer's own configuration
            saved_has_guard = self._guard and self._gstate is not None
        if saved_has_guard:
            import jax.numpy as jnp
            gs = self._gstate if self._gstate is not None else \
                (jnp.float32(1.0), jnp.int32(0))
            template["guard"] = [
                jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=self._r_sh)
                for v in gs]
        tree = self._checkpointer().restore(path, template)
        self._pvals = list(tree["params"])
        self._avals = list(tree["aux"])
        self._state = tree["opt_state"]
        _grandom.set_state(tree["rng"])
        self._t = int(tree["t"])
        self._optimizer.num_update = self._t
        if "guard" in tree and self._guard:
            self._gstate = tuple(tree["guard"])

    def sync_params(self) -> None:
        """Copy trainer-owned (sharded) weights back into the block's
        Parameters (gathered to the default device) — call before
        save_parameters/export."""
        import jax
        if not self._built:
            return   # pre-build, the block still owns the weights
        with _autograd.pause():
            for p, v in zip(self._train_params, self._pvals):
                p.data(self._ctx)._set_data(
                    _np_to_dev(jax.device_get(v), self._ctx))
            for p, v in zip(self._aux_params, self._avals):
                p.data(self._ctx)._set_data(
                    _np_to_dev(jax.device_get(v), self._ctx))


def _np_to_dev(val, ctx):
    import jax.numpy as jnp
    return jnp.asarray(val)


def _to_val(y):
    """Normalize the label side.  A TUPLE means multiple label streams
    (e.g. BERT pretraining: mlm_labels, mlm_weights, nsp_labels) — each is
    normalized and the tuple preserved; a python LIST stays one array of
    values (reference mx.nd.array(list) semantics)."""
    import jax

    def one(v):
        if isinstance(v, NDArray):
            return v._read()
        if isinstance(v, jax.Array):
            return v
        # ingestion boundary: reached only for host data (lists /
        # np arrays); NDArray and jax.Array pass through above
        # mxlint: disable=hidden-host-sync — host-data ingestion
        return _np.asarray(v)

    if isinstance(y, tuple):
        return tuple(one(v) for v in y)
    return one(y)


def _to_vals(x):
    """Normalize a single array / NDArray or a tuple of them to a tuple of
    raw values.  jax.Arrays pass through untouched so pre-device_put batches
    skip the host round-trip (device_put on an already-correctly-sharded
    array is a no-op)."""
    import jax
    xs = x if isinstance(x, (tuple, list)) else (x,)
    return tuple(
        v._read() if isinstance(v, NDArray)
        # ingestion boundary: _np.asarray reached only for host data
        # mxlint: disable=hidden-host-sync — host-data ingestion
        else v if isinstance(v, jax.Array) else _np.asarray(v)
        for v in xs)
