"""Pipeline parallelism over the 'pp' mesh axis (GPipe microbatching).

BEYOND reference parity (the reference has no in-tree pipeline schedule —
SURVEY.md §2.3 lists PP as absent); built because distributed is
first-class in this framework and 'pp' completes the dp/tp/sp/ep/pp set.

TPU-native design: single-program SPMD under ``shard_map`` — every device
runs the SAME scan; stage weights are STACKED on a leading axis sharded
``P('pp', ...)`` so each device holds exactly its stage; activations flow
between neighbouring stages with ``lax.ppermute`` over ICI each step.
The schedule is classic GPipe: M microbatches drain through S stages in
M + S - 1 ticks; JAX autodiff reverses the permutes for the backward, so
``jax.grad`` of a pipelined loss just works.
"""
from __future__ import annotations

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh,
                   axis: str = "pp"):
    """Run homogeneous pipeline stages over microbatches.

    Parameters
    ----------
    stage_fn : callable ``(params_i, x) -> y`` — one stage's compute;
        inputs and outputs must share shape/dtype (homogeneous pipeline,
        the stacked-weights TPU idiom).
    stacked_params : pytree whose leaves have leading axis S (= mesh
        size along ``axis``); shard them ``P('pp', ...)``.
    microbatches : array ``(M, mb, ...)`` — M microbatches.
    mesh : jax Mesh containing ``axis``.

    Returns ``(M, mb, ...)`` outputs, as if ``stage_{S-1}(...stage_0(x))``
    ran per microbatch.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map_compat

    S = mesh.shape[axis]
    M = microbatches.shape[0]
    T = M + S - 1                    # total pipeline ticks

    p_params = jax.tree.map(lambda _: P(axis), stacked_params)
    # microbatches replicated over 'pp' (the dp axis may shard dim 1+)
    p_x = P()
    perm = [(i, i + 1) for i in range(S - 1)]

    def per_device(params, xs):
        # params: leaves (1, ...) — this device's stage; xs: (M, mb, ...)
        params = jax.tree.map(lambda v: v[0], params)
        rank = lax.axis_index(axis)
        zero = jnp.zeros_like(xs[0])

        def tick(carry, t):
            buf, outs = carry
            # receive the previous stage's output (stage 0 receives junk)
            recv = lax.ppermute(buf, axis, perm)
            feed = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), keepdims=False)
            x_in = jnp.where(rank == 0,
                             jnp.where(t < M, feed, zero),
                             recv)
            y = stage_fn(params, x_in)
            # last stage commits microbatch t-S+1 on ticks t >= S-1
            oidx = jnp.clip(t - (S - 1), 0, M - 1)
            commit = (rank == S - 1) & (t >= S - 1)
            outs = lax.cond(
                commit,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, oidx, axis=0),
                lambda o: o, outs)
            return (y, outs), None

        outs0 = jnp.zeros_like(xs)
        (_, outs), _ = lax.scan(tick, (zero, outs0), jnp.arange(T))
        # every device returns outs; only the last stage's is real —
        # mask + psum broadcasts it so the result replicates over 'pp'
        masked = jnp.where(rank == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(masked, axis)

    fn = shard_map_compat(per_device, mesh, (p_params, p_x), p_x)
    return fn(stacked_params, microbatches)
