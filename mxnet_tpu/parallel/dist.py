"""Multi-process distributed runtime (the DCN story).

Reference parity: ps-lite's process bootstrap — workers/servers wired up
from ``DMLC_*`` environment variables set by the launcher (SURVEY.md §2.3
ps-lite row, §5.8).  TPU-native replacement: no parameter server; all
processes join one JAX coordination service (`jax.distributed.initialize`)
and gradient reduction rides XLA collectives / host allgather over DCN.

The same launcher env-var names are honored so reference launch scripts
carry over:

- ``DMLC_PS_ROOT_URI`` / ``DMLC_PS_ROOT_PORT`` — coordinator address
  (reference: the ps-lite scheduler address).
- ``DMLC_NUM_WORKER`` — total number of worker processes.
- ``DMLC_WORKER_ID`` — this process's rank (assigned by the launcher).

``dist_async`` has no analog here by design: synchronous SPMD replaces
stale parameter-server updates (SURVEY.md §5.8).

**The collectivity contract is machine-checked.**  Every public entry
point here (``allgather_*``, ``allreduce_host``, ``broadcast_host``,
``barrier``) must be reached by EVERY process or by none — the KV-path
generation counters below depend on per-process call counts staying in
lockstep, and a rank that skips a collective wedges the fleet until
the DCN timeout.  mxlint's ``collective-safety`` rule enforces this
repo-wide and *interprocedurally*: a call to one of these functions —
or to any helper that transitively reaches one, resolved through the
project call graph — from under a branch conditioned on
``rank``/``process_index``/``host_id``/... is a lint failure carrying
the call chain as evidence.  Branch on fleet-uniform state only
(``is_initialized()``, ``num_workers()``); the deterministic
backend-capability fallbacks inside this module (every rank takes the
same branch) are the sanctioned pattern.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from ..base import MXNetError, get_env, hot_path

__all__ = ["init_process_group", "is_initialized", "rank", "num_workers",
           "phys_rank", "active_members", "fence_generation",
           "set_active_members", "reset_active_members",
           "allreduce_host", "allgather_host", "allgather_bytes",
           "allgather_rows", "dedup_sum_rows",
           "reduce_scatter_host", "broadcast_host", "barrier",
           "kv_publish", "kv_collect", "kv_purge_rank"]


def is_initialized() -> bool:
    """True if this process has joined a multi-process JAX runtime."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        # no backend-initializing fallback here: this runs before
        # jax.distributed.initialize, which must precede the first backend
        # query — assume uninitialized
        return False


def init_process_group(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       timeout: Optional[float] = None,
                       retries: int = 2,
                       backoff: float = 1.0,
                       elastic: Optional[bool] = None) -> None:
    """Join the multi-process runtime (idempotent).

    Arguments default to the reference's launcher env vars
    (``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``,
    ``DMLC_WORKER_ID``).  Raises if neither arguments nor env are present.

    Failure handling (this used to hang forever on an unreachable
    coordinator): each join attempt waits at most ``timeout`` seconds
    (default: ``MXTPU_DIST_TIMEOUT`` env or 300), and is retried up to
    ``retries`` times with exponential backoff starting at ``backoff``
    seconds — under a real launcher the coordinator routinely comes up
    AFTER the workers.  The final failure is wrapped in an
    :class:`MXNetError` naming the coordinator and rank.

    ``elastic`` (default: the ``MXTPU_ELASTIC`` env knob) prepares the
    group for host loss: the coordination service's OWN task-heartbeat
    reaper is effectively disabled, because its reaction to a silent
    task is to propagate a fatal error that TERMINATES every surviving
    process (~100s after the death, with jax defaults) — the opposite
    of surviving it.  Liveness judgment then belongs solely to the
    membership lease layer (:mod:`mxnet_tpu.parallel.membership`),
    which detects the loss within one lease TTL and re-forms the fleet
    instead of dying with it.
    """
    if is_initialized():
        return
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9099")
        coordinator = f"{uri}:{port}" if uri else None
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        wid = os.environ.get("DMLC_WORKER_ID")
        process_id = int(wid) if wid else None
    if num_processes == 1:
        return  # single worker: nothing to join
    if coordinator is None or num_processes is None or process_id is None:
        missing = []
        if coordinator is None:
            missing.append("DMLC_PS_ROOT_URI (+ optional DMLC_PS_ROOT_PORT)")
        if num_processes is None:
            missing.append("DMLC_NUM_WORKER")
        if process_id is None:
            missing.append("DMLC_WORKER_ID")
        raise MXNetError(
            "multi-process kvstore requires the process group to be "
            "initialized, but these launcher env vars are unset: "
            + ", ".join(missing) +
            " — set them (reference launcher env vars) or call "
            "mxnet_tpu.parallel.dist.init_process_group(coordinator, "
            "num_processes, process_id) before kv.create('dist_sync')")
    if timeout is None:
        timeout = float(get_env("MXTPU_DIST_TIMEOUT"))
    if elastic is None:
        elastic = bool(get_env("MXTPU_ELASTIC"))
    join_kwargs = {}
    if elastic:
        # the service reaper would otherwise broadcast a FATAL error on
        # the first silent task and jax's error-polling thread would
        # terminate every survivor — the membership lease layer is the
        # liveness authority in an elastic fleet
        join_kwargs["service_heartbeat_interval_seconds"] = 10
        join_kwargs["service_max_missing_heartbeats"] = 1_000_000
    import jax
    from ..faults import retry_call

    def _join():
        try:
            if not join_kwargs:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=max(1, int(timeout)))
            else:
                # the public wrapper does not forward the heartbeat
                # knobs — replicate its two lines (backend guard +
                # global_state.initialize) with them added
                from jax._src import distributed as _jdist
                from jax._src import xla_bridge as _xb
                if _xb.backends_are_initialized():
                    raise MXNetError(
                        "init_process_group(elastic=True) must run "
                        "before any JAX computation initializes the "
                        "backend")
                _jdist.global_state.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=max(1, int(timeout)),
                    **join_kwargs)
        except Exception:
            # a failed connect leaves jax's global client/service assigned
            # (State.initialize sets them BEFORE connect()), and a retry
            # would then die on 'initialize should only be called once' —
            # reset so the next attempt is a real join
            try:
                jax.distributed.shutdown()
            except Exception:   # noqa: BLE001 — best-effort state reset
                pass
            raise

    from ..observability.registry import registry as _metrics_registry

    def _count_retry(attempt, exc, delay):
        _metrics_registry().counter("dist.init_retries").inc()

    try:
        retry_call(_join, retries=retries, base_delay=backoff,
                   max_delay=30.0,
                   retry_on=(RuntimeError, ConnectionError, TimeoutError,
                             OSError), on_retry=_count_retry)
    except Exception as exc:
        raise MXNetError(
            f"could not join the process group at {coordinator!r} as rank "
            f"{process_id}/{num_processes} after {retries + 1} attempt(s) "
            f"({timeout:.0f}s connect timeout each): {exc}") from exc


# -- the active process group (elastic-fleet narrowing) ---------------------
#
# The coordination service is joined ONCE at the launcher's world size and
# its process ids never change.  After a host loss the survivors re-form
# the logical process group at the new world size (parallel/membership.py):
# the surviving ORIGINAL process ids become the active member set, logical
# ranks are re-assigned contiguously by sorting them, and every KV-path
# collective below iterates the active set only — so the group keeps
# working over the same coordinator without the dead host.  Physical ids
# (``phys_rank``) stay stable across re-forms and key every per-host KV
# namespace; logical coordinates (``rank``/``num_workers``) are what data
# sharding and collective result indexing see.

_group_lock = threading.Lock()
_members: Optional[Tuple[int, ...]] = None   # original ids, sorted; None =
_fence = 0                                   # full launcher world


def phys_rank() -> int:
    """This process's ORIGINAL id in the coordination service — stable
    across fleet re-forms (logical :func:`rank` is not)."""
    import jax
    return jax.process_index()


def rank() -> int:
    """Logical rank: contiguous in the ACTIVE member set.  Equal to
    :func:`phys_rank` until a fleet re-form narrows the group."""
    with _group_lock:
        members = _members
    if members is None:
        import jax
        return jax.process_index()
    return members.index(phys_rank())


def num_workers() -> int:
    """Logical world size: the ACTIVE member count after re-forms."""
    with _group_lock:
        members = _members
    if members is None:
        import jax
        return jax.process_count()
    return len(members)


def active_members() -> Tuple[int, ...]:
    """The ORIGINAL process ids of the active group, sorted (logical
    rank r is ``active_members()[r]``)."""
    with _group_lock:
        members = _members
    if members is not None:
        return members
    import jax
    return tuple(range(jax.process_count()))


def fence_generation() -> int:
    """The membership fencing generation: bumped by every fleet re-form;
    KV state stamped with an older generation belongs to a fenced-out
    incarnation and must be ignored."""
    with _group_lock:
        return _fence


def set_active_members(members, fence: int) -> None:
    """Install a re-formed process group (every survivor calls this with
    the SAME committed member set — parallel/membership.py's consensus
    round is the only sanctioned caller).  ``members`` are original
    process ids; this process must be one of them."""
    global _members, _fence
    members = tuple(sorted(int(m) for m in members))
    if not members:
        raise MXNetError("set_active_members: empty member set")
    me = phys_rank()
    if me not in members:
        raise MXNetError(
            f"set_active_members: this process (id {me}) is not in the "
            f"re-formed member set {members} — it has been fenced out "
            f"and must exit, not install the group")
    with _group_lock:
        _members = members
        _fence = int(fence)


def reset_active_members() -> None:
    """Drop the narrowed group (back to the full launcher world)."""
    global _members, _fence
    with _group_lock:
        _members = None
        _fence = 0


def _deadline_wait(what: str, timeout: float, fn, *args, **kwargs):
    """Run one blocking coordination-service call and convert its
    DEADLINE_EXCEEDED into the typed :class:`~mxnet_tpu.faults.
    DeadlineExceeded` every KV wait path promises.  A dead host then
    produces a catchable fault the membership watcher takes over from,
    instead of an opaque runtime error (or, before timeouts were
    threaded through, an unbounded hang)."""
    from ..faults import DeadlineExceeded
    try:
        return fn(*args, **kwargs)
    except TimeoutError as exc:
        raise DeadlineExceeded(
            f"{what} timed out after {timeout:.1f}s "
            f"(MXTPU_DIST_TIMEOUT) — a peer never arrived; if a host "
            f"died, the membership layer (parallel.membership) re-forms "
            f"the fleet from this signal") from exc
    except Exception as exc:   # noqa: BLE001 — narrow re-raise below:
        # jaxlib surfaces coordination-service timeouts as
        # XlaRuntimeError('DEADLINE_EXCEEDED: ...'), not TimeoutError
        if "DEADLINE_EXCEEDED" not in str(exc):
            raise
        raise DeadlineExceeded(
            f"{what} timed out after {timeout:.1f}s "
            f"(MXTPU_DIST_TIMEOUT) — a peer never arrived; if a host "
            f"died, the membership layer (parallel.membership) re-forms "
            f"the fleet from this signal") from exc


def _gather_arrays_kv(arr, timeout: Optional[float] = None):
    """KV-store transport for the host collectives: each rank ships its
    numpy array (npy-serialized) through :func:`_allgather_bytes_kv` and
    stacks the fleet's contributions.  Same contract as
    ``process_allgather`` with equal shapes; exists because device
    collectives don't span processes on every backend (multi-process
    CPU), while the coordination service always does."""
    import io
    import numpy as np
    if timeout is None:
        timeout = float(get_env("MXTPU_DIST_TIMEOUT"))
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    blobs = _allgather_bytes_kv(buf.getvalue(), timeout)
    return np.stack([np.load(io.BytesIO(b), allow_pickle=False)
                     for b in blobs])


def allreduce_host(x):
    """Sum a host-local numpy array across all processes.

    DCN-path reduction for the kvstore object plane (the compiled trainer
    path uses in-graph psum over the device mesh instead).
    """
    import numpy as np
    return np.sum(allgather_host(x), axis=0)


def reduce_scatter_host(x):
    """Reduce-scatter a host-local numpy array: sum it across all
    processes and return THIS rank's 1/num_workers slice along dim 0.

    The DCN object plane's analog of the in-graph ZeRO gradient
    reduce-scatter (``ShardedTrainer(zero_stage>=1)`` — there XLA emits
    the collective inside the jitted step; here the object plane gets
    the same reduce-then-own-slice contract for host-side state).  Dim
    0 must divide by the active world size.  Like every entry point in
    this module it is a COLLECTIVE: all ranks must call it or none —
    the collective-safety lint rule enforces that, rank-gated calls are
    a lint failure."""
    import numpy as np
    if not is_initialized():
        # local-only fallback (1-rank world): the sum is the input and
        # the slice is everything — same tiering as allgather_bytes
        return np.asarray(x)
    total = allreduce_host(x)
    n = num_workers()
    if total.shape[0] % n:
        raise MXNetError(
            f"reduce_scatter_host: dim 0 of {total.shape} does not "
            f"divide by the world size {n}")
    chunk = total.shape[0] // n
    r = rank()
    return total[r * chunk:(r + 1) * chunk]


def allgather_host(x):
    """Gather each process's host-local numpy array; returns an array with
    a leading num_workers axis (this process's slot included).

    Transport is tiered like :func:`allgather_bytes`: the XLA device
    collective where the backend spans processes (TPU pods), else the
    coordination-service KV store — so the object plane works on the
    multi-process CPU backend too (where XLA reports 'Multiprocess
    computations aren't implemented')."""
    import numpy as np
    from jax.experimental import multihost_utils
    arr = np.asarray(x)
    if _narrowed():
        # a re-formed group no longer matches the device world the
        # backend was built with (the dead host is still in it) — the
        # KV path over the surviving member set is the only transport
        return _gather_arrays_kv(arr)
    try:
        return np.asarray(multihost_utils.process_allgather(arr))
    except Exception:   # noqa: BLE001 — backend capability, determinis-
        # tic per backend: every rank takes the same branch
        if not is_initialized():
            raise
        return _gather_arrays_kv(arr)


def _allgather_bytes_device(data: bytes):
    """Byte gather over the raw ``process_allgather`` device collective
    (deliberately NOT :func:`allgather_host`, whose KV fallback would
    turn one logical gather into two — an unsupported backend should
    fail fast here so :func:`allgather_bytes` takes its single-gather
    KV path instead).  Variable lengths need two collectives (equal
    shapes are required): gather the lengths, then gather payloads
    padded to the fleet maximum and trim each back to its sender's
    true length."""
    import numpy as np
    from jax.experimental import multihost_utils
    sizes = np.asarray(multihost_utils.process_allgather(
        np.asarray([len(data)], dtype=np.int64)))[:, 0]
    cap = int(sizes.max())  # mxlint: disable=hidden-host-sync — the length gather is itself a host collective; its result sizes the payload buffer
    if cap == 0:
        return [b""] * len(sizes)
    buf = np.zeros((cap,), dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [gathered[i, :int(sizes[i])].tobytes()
            for i in range(len(sizes))]


# generation counters for the KV-store fallbacks below.  Every KV-path
# entry point is a COLLECTIVE (each process calls it the same number of
# times in the same order), so per-process counters stay in lockstep
# across the fleet and key/barrier names never collide across calls.
_gen_lock = threading.Lock()
_agb_gen = 0


def _narrowed() -> bool:
    """True once a fleet re-form has narrowed the active group below the
    launcher world — device collectives (which still span the ORIGINAL
    world, dead host included) are then off the table and every
    collective takes its coordination-service KV path."""
    with _group_lock:
        return _members is not None


def _barrier_ids(members: Tuple[int, ...]):
    """``process_ids`` for a coordination-service barrier: None (= the
    full launcher world, every jaxlib supports it) until a re-form has
    narrowed the group, then the explicit surviving id list."""
    with _group_lock:
        narrowed = _members is not None
    return list(members) if narrowed else None


def _allgather_bytes_kv(data: bytes, timeout: float):
    """Byte gather over the coordination-service KV store (the same
    coordinator TCP fabric ``jax.distributed.initialize`` joined): each
    rank publishes its payload under a generation-unique key and blocks
    reading every peer's.  No device round-trip and no padding — and it
    works on backends whose device collectives don't span processes
    (the multi-process CPU backend used in tests).

    Every blocking read is bounded by ``timeout`` and a peer that never
    arrives raises :class:`~mxnet_tpu.faults.DeadlineExceeded` naming
    it — the signal the membership watcher turns into a fleet re-form.
    Peers are the ACTIVE member set: after a re-form the gather spans
    the survivors only, indexed by logical rank."""
    import base64
    from jax._src import distributed

    # the KV gather is a blocking fleet-wide wait: measured as a span so
    # it lands in the histogram AND — inside a traced region (a step or
    # re-form trace) — as a child span attributing collective time
    from ..observability.trace import span as _span
    global _agb_gen
    client = distributed.global_state.client
    me = phys_rank()
    members = active_members()
    with _gen_lock:
        gen = _agb_gen
        _agb_gen += 1
    # fence-scoped namespace: a fenced-out incarnation's in-flight gather
    # writes under the OLD fence and can never collide with the re-formed
    # group's generation counters
    key = f"mxtpu/agb/{fence_generation()}/{gen}"
    timeout_ms = max(1000, int(timeout * 1000))
    client.key_value_set(f"{key}/{me}",
                         base64.b64encode(data).decode("ascii"))
    with _span("dist.allgather_kv_us", args={"gen": gen}):
        out = [base64.b64decode(_deadline_wait(
            f"allgather_bytes gen {gen}: waiting for rank {i}", timeout,
            client.blocking_key_value_get, f"{key}/{i}", timeout_ms))
            for i in members]
    try:
        # only safe to delete our key once EVERY rank has read it
        client.wait_at_barrier(
            f"mxtpu_agb_{fence_generation()}_{gen}", timeout_ms,
            _barrier_ids(members))
        client.key_value_delete(f"{key}/{me}")
    except Exception:   # noqa: BLE001 — cleanup is best-effort; a few
        pass            # stale keys beat a wedged gather
    return out


def allgather_bytes(data: bytes, timeout: Optional[float] = None):
    """Gather one variable-length byte payload from every process;
    returns a list of ``num_workers`` byte strings indexed by rank.

    The DCN object plane for non-array payloads (the multi-host metrics
    gather ships JSON snapshots through here).  Transport is tiered:
    the ``allgather_host`` device collective when the backend spans
    processes (TPU pods — the efficient DCN path), else the
    coordination-service KV store (always available once the process
    group is up).  Local-only fallback: a single-element list when the
    process group is not initialized."""
    data = bytes(data)
    if not is_initialized():
        return [data]
    if timeout is None:
        timeout = float(get_env("MXTPU_DIST_TIMEOUT"))
    if _narrowed():
        return _allgather_bytes_kv(data, timeout)
    try:
        return _allgather_bytes_device(data)
    except Exception:   # noqa: BLE001 — backend-dependent capability
        # (e.g. CPU: "Multiprocess computations aren't implemented");
        # deterministic per backend, so every rank takes the same branch
        return _allgather_bytes_kv(data, timeout)


# -- row-sparse gradient exchange --------------------------------------------


@hot_path("step")
def allgather_rows(ids, rows, timeout: Optional[float] = None):
    """Gather one ``(ids, rows)`` row-sparse gradient slab from every
    process; returns a list of ``num_workers`` ``(ids, rows)`` numpy
    pairs indexed by rank.  The modern ps-lite push/pull: each worker
    ships only the rows its batch touched (ids ``(n,)`` int, rows
    ``(n, width)`` float) instead of allreducing the dense table, and
    the caller reduces with :func:`dedup_sum_rows`.

    Rides :func:`allgather_bytes` (device collective on pods, KV store
    fallback), so slabs may be DIFFERENT lengths per rank — no padding
    protocol needed.  Bumps the ``sparse.exchange_bytes`` counter with
    the actual wire payload.  Single-process: a one-element list."""
    import io
    import numpy as np
    from ..observability.registry import registry as _registry
    ids = np.ascontiguousarray(np.asarray(ids))  # mxlint: disable=hidden-host-sync — the exchange IS the host boundary: ids leave the device to ride the DCN
    rows = np.ascontiguousarray(np.asarray(rows))  # mxlint: disable=hidden-host-sync — same boundary: rows serialize into the wire payload
    if ids.shape[0] != rows.shape[0]:
        raise MXNetError(
            f"allgather_rows: {ids.shape[0]} ids vs {rows.shape[0]} rows")
    buf = io.BytesIO()
    np.savez(buf, ids=ids, rows=rows)
    payload = buf.getvalue()
    _registry().counter(
        "sparse.exchange_bytes",
        "bytes of (ids, rows) row-sparse gradient payload "
        "exchanged instead of dense table reductions").inc(len(payload))
    out = []
    for blob in allgather_bytes(payload, timeout=timeout):
        z = np.load(io.BytesIO(blob))
        out.append((z["ids"], z["rows"]))
    return out


def dedup_sum_rows(pairs):
    """Reduce :func:`allgather_rows` output: union the id sets and sum
    rows that collide — the server-side aggregation of the push/pull.
    Returns one ``(ids, rows)`` pair with ids sorted unique."""
    import numpy as np
    pairs = [p for p in pairs if p[0].size]
    if not pairs:
        return np.zeros((0,), np.int64), np.zeros((0, 0), np.float32)
    all_ids = np.concatenate([p[0] for p in pairs])
    all_rows = np.concatenate([p[1] for p in pairs], axis=0)
    uids, inv = np.unique(all_ids, return_inverse=True)
    out = np.zeros((uids.size, all_rows.shape[1]), all_rows.dtype)
    np.add.at(out, inv, all_rows)
    return uids, out


# -- barrier-free KV publish/collect ----------------------------------------
#
# NOT collectives: no barrier, no blocking peer read, no lockstep
# call-count requirement — which is exactly why the timer-thread fleet
# metric gather (tuning.FleetGatherController) can run free on every
# host at its own cadence.  Each rank overwrite-publishes its newest
# payload under a generation-stamped key; a collect reads whatever
# generation every peer has published most recently (possibly one tick
# stale — staleness is the price of barrier freedom, and the consumer's
# contract already labels remote hosts "as-of last gather").

_kv_pub_lock = threading.Lock()
_kv_pub_gens = {}      # prefix -> next generation for THIS process


def kv_publish(prefix: str, payload: bytes) -> None:
    """Publish this rank's ``payload`` under ``prefix`` (overwrite
    semantics: a fresh generation-stamped key is written, older own
    generations deleted best-effort).  Requires an initialized process
    group.

    Restart-safe: the first publish of a fresh process resumes ABOVE
    any generations a dead predecessor of the same rank left in the
    store (and purges them), so ``kv_collect`` prefers the live
    incarnation's state immediately instead of serving the dead
    process's frozen payload until the new counter catches up."""
    import base64
    from jax._src import distributed
    if not is_initialized():
        raise MXNetError("kv_publish requires an initialized process "
                         "group (init_process_group)")
    client = distributed.global_state.client
    r = phys_rank()   # stable across re-forms: a host's namespace is its
    own = f"{prefix}/{r}"   # ORIGINAL id, so survivors' keys never move
    with _kv_pub_lock:
        gen = _kv_pub_gens.get(prefix)
        if gen is None:
            gen = 0
            try:
                for k, _v in client.key_value_dir_get(own):
                    try:
                        gen = max(gen, int(k.rsplit("/", 1)[1]) + 1)
                    except (ValueError, IndexError):
                        continue
            except Exception:   # noqa: BLE001 — empty/missing dir (the
                pass            # common case) or transport hiccup: gen 0
        _kv_pub_gens[prefix] = gen + 1
    key = f"{own}/{gen:012d}"
    client.key_value_set(key, base64.b64encode(payload).decode("ascii"))
    try:
        # purge every strictly-OLDER own generation — the previous
        # tick's and any dead predecessor's.  Gen-compared, not
        # key-compared: a concurrent publisher (two controllers on one
        # process) may have already written a NEWER generation, which
        # must survive this purge.  Best-effort; collect picks the
        # highest either way.
        for k, _v in client.key_value_dir_get(own):
            try:
                if int(k.rsplit("/", 1)[1]) < gen:
                    client.key_value_delete(k)
            except (ValueError, IndexError):
                continue
    except Exception:   # noqa: BLE001 — cleanup is best-effort; a few
        pass            # stale keys beat a failed publish


def kv_collect(prefix: str):
    """Every rank's most recently published payload under ``prefix`` as
    ``{rank: bytes}`` (only ranks that have published appear).  Never
    blocks on a peer: a rank that has not published yet is simply
    absent from this collect and present in a later one."""
    import base64
    from jax._src import distributed
    if not is_initialized():
        raise MXNetError("kv_collect requires an initialized process "
                         "group (init_process_group)")
    client = distributed.global_state.client
    newest = {}            # rank -> (gen, value)
    for key, value in client.key_value_dir_get(prefix):
        parts = key.rsplit("/", 2)
        if len(parts) != 3:
            continue
        try:
            r, gen = int(parts[1]), int(parts[2])
        except ValueError:
            continue
        if r not in newest or gen > newest[r][0]:
            newest[r] = (gen, value)
    return {r: base64.b64decode(v) for r, (_g, v) in newest.items()}


def kv_purge_rank(prefix: str, dead_rank: int) -> int:
    """Best-effort deletion of every key under ``prefix`` belonging to
    ``dead_rank`` (by its ORIGINAL process id); returns the count
    removed.  Covers both per-rank key shapes used in this module:
    ``{prefix}/{rank}/{gen}`` (the :func:`kv_publish` namespace — lease
    and fleet-gather state) and ``{prefix}/.../{rank}`` (the allgather
    generation keys).  The membership reaper calls this after a re-form
    commits so a dead host's frozen generations can never be served to
    a later collect — the restart-safety purge in :func:`kv_publish`
    only covers the SAME rank coming back, not a rank that never
    returns."""
    from jax._src import distributed
    if not is_initialized():
        return 0
    client = distributed.global_state.client
    tag = str(int(dead_rank))
    removed = 0
    try:
        entries = client.key_value_dir_get(prefix)
    except Exception:   # noqa: BLE001 — purge is best-effort; a few
        return 0        # stale keys beat a crashed reaper
    for key, _value in entries:
        parts = key.split("/")
        owned = parts[-1] == tag or \
            (len(parts) >= 2 and parts[-2] == tag and parts[-1].isdigit())
        if not owned:
            continue
        try:
            client.key_value_delete(key)
            removed += 1
        except Exception:   # noqa: BLE001 — same best-effort contract
            continue
    return removed


def broadcast_host(x):
    """Broadcast rank 0's host-local numpy array to all processes."""
    import numpy as np
    from jax.experimental import multihost_utils
    arr = np.asarray(x)
    if _narrowed():
        # logical rank 0 = the lowest surviving member: its slot leads
        # the KV gather, same contract as the device broadcast
        return _gather_arrays_kv(arr)[0]
    try:
        return np.asarray(multihost_utils.broadcast_one_to_all(arr))
    except Exception:   # noqa: BLE001 — same tiering as allgather_host
        if not is_initialized():
            raise
        return _gather_arrays_kv(arr)[0]


_barrier_gen = 0


def _barrier_kv(name: str, timeout: Optional[float] = None) -> None:
    """Coordination-service barrier over the ACTIVE member set, bounded
    by ``timeout`` (default ``MXTPU_DIST_TIMEOUT``) — an absent peer
    raises :class:`~mxnet_tpu.faults.DeadlineExceeded` instead of
    wedging the fleet.  Barrier ids must be unique per use; the
    generation counter stays in lockstep because barrier() is a
    collective, and it is fence-scoped so a fenced-out incarnation's
    barriers can never alias the re-formed group's."""
    global _barrier_gen   # noqa: PLW0603 — lockstep generation counter
    from jax._src import distributed
    with _gen_lock:
        gen = _barrier_gen
        _barrier_gen += 1
    if timeout is None:
        timeout = float(get_env("MXTPU_DIST_TIMEOUT"))
    timeout_ms = max(1000, int(timeout * 1000))
    members = active_members()
    # span: the barrier wait is collective time on the step/re-form
    # critical path — histogram always, trace child inside a traced
    # region
    from ..observability.trace import span as _span
    with _span("dist.barrier_kv_us", args={"name": name, "gen": gen}):
        _deadline_wait(
            f"barrier '{name}' gen {gen} over ranks {list(members)}",
            timeout, distributed.global_state.client.wait_at_barrier,
            f"mxtpu_barrier_{fence_generation()}_{name}_{gen}",
            timeout_ms, _barrier_ids(members))


def barrier(name: str = "mxnet_tpu_barrier",
            timeout: Optional[float] = None) -> None:
    """Fleet barrier, tiered like the gathers.  ``timeout`` bounds the
    coordination-service tier (typed ``DeadlineExceeded`` on an absent
    peer); the device-collective tier, when the backend supports it, is
    bounded only by the backend's own collective timeout — Python
    cannot interrupt an XLA collective.  The elastic arc therefore
    never relies on this function for loss detection: the membership
    layer's ``step_barrier`` goes straight to the bounded
    coordination-service barrier."""
    if _narrowed():
        _barrier_kv(name, timeout)
        return
    from jax.experimental import multihost_utils
    try:
        multihost_utils.sync_global_devices(name)
    except Exception:   # noqa: BLE001 — same tiering: the coordination
        # service's own barrier when device collectives can't span
        # processes
        if not is_initialized():
            raise
        _barrier_kv(name, timeout)
