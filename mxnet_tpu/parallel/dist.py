"""Multi-process distributed runtime (the DCN story).

Reference parity: ps-lite's process bootstrap — workers/servers wired up
from ``DMLC_*`` environment variables set by the launcher (SURVEY.md §2.3
ps-lite row, §5.8).  TPU-native replacement: no parameter server; all
processes join one JAX coordination service (`jax.distributed.initialize`)
and gradient reduction rides XLA collectives / host allgather over DCN.

The same launcher env-var names are honored so reference launch scripts
carry over:

- ``DMLC_PS_ROOT_URI`` / ``DMLC_PS_ROOT_PORT`` — coordinator address
  (reference: the ps-lite scheduler address).
- ``DMLC_NUM_WORKER`` — total number of worker processes.
- ``DMLC_WORKER_ID`` — this process's rank (assigned by the launcher).

``dist_async`` has no analog here by design: synchronous SPMD replaces
stale parameter-server updates (SURVEY.md §5.8).
"""
from __future__ import annotations

import os
from typing import Optional

from ..base import MXNetError

__all__ = ["init_process_group", "is_initialized", "rank", "num_workers",
           "allreduce_host", "allgather_host", "broadcast_host", "barrier"]


def is_initialized() -> bool:
    """True if this process has joined a multi-process JAX runtime."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        # no backend-initializing fallback here: this runs before
        # jax.distributed.initialize, which must precede the first backend
        # query — assume uninitialized
        return False


def init_process_group(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> None:
    """Join the multi-process runtime (idempotent).

    Arguments default to the reference's launcher env vars
    (``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``,
    ``DMLC_WORKER_ID``).  Raises if neither arguments nor env are present.
    """
    if is_initialized():
        return
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9099")
        coordinator = f"{uri}:{port}" if uri else None
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        wid = os.environ.get("DMLC_WORKER_ID")
        process_id = int(wid) if wid else None
    if num_processes == 1:
        return  # single worker: nothing to join
    if coordinator is None or num_processes is None or process_id is None:
        raise MXNetError(
            "multi-process kvstore requires the process group to be "
            "initialized: set DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT/"
            "DMLC_NUM_WORKER/DMLC_WORKER_ID (reference launcher env vars) "
            "or call mxnet_tpu.parallel.dist.init_process_group("
            "coordinator, num_processes, process_id) before "
            "kv.create('dist_sync')")
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id)


def rank() -> int:
    import jax
    return jax.process_index()


def num_workers() -> int:
    import jax
    return jax.process_count()


def allreduce_host(x):
    """Sum a host-local numpy array across all processes.

    DCN-path reduction for the kvstore object plane (the compiled trainer
    path uses in-graph psum over the device mesh instead).
    """
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(x))
    return np.sum(gathered, axis=0)


def allgather_host(x):
    """Gather each process's host-local numpy array; returns an array with
    a leading num_workers axis (this process's slot included)."""
    import numpy as np
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


def broadcast_host(x):
    """Broadcast rank 0's host-local numpy array to all processes."""
    import numpy as np
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.broadcast_one_to_all(np.asarray(x)))


def barrier(name: str = "mxnet_tpu_barrier") -> None:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
