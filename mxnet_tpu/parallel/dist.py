"""Multi-process distributed runtime (the DCN story).

Reference parity: ps-lite's process bootstrap — workers/servers wired up
from ``DMLC_*`` environment variables set by the launcher (SURVEY.md §2.3
ps-lite row, §5.8).  TPU-native replacement: no parameter server; all
processes join one JAX coordination service (`jax.distributed.initialize`)
and gradient reduction rides XLA collectives / host allgather over DCN.

The same launcher env-var names are honored so reference launch scripts
carry over:

- ``DMLC_PS_ROOT_URI`` / ``DMLC_PS_ROOT_PORT`` — coordinator address
  (reference: the ps-lite scheduler address).
- ``DMLC_NUM_WORKER`` — total number of worker processes.
- ``DMLC_WORKER_ID`` — this process's rank (assigned by the launcher).

``dist_async`` has no analog here by design: synchronous SPMD replaces
stale parameter-server updates (SURVEY.md §5.8).
"""
from __future__ import annotations

import os
from typing import Optional

from ..base import MXNetError

__all__ = ["init_process_group", "is_initialized", "rank", "num_workers",
           "allreduce_host", "allgather_host", "broadcast_host", "barrier"]


def is_initialized() -> bool:
    """True if this process has joined a multi-process JAX runtime."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        # no backend-initializing fallback here: this runs before
        # jax.distributed.initialize, which must precede the first backend
        # query — assume uninitialized
        return False


def init_process_group(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       timeout: Optional[float] = None,
                       retries: int = 2,
                       backoff: float = 1.0) -> None:
    """Join the multi-process runtime (idempotent).

    Arguments default to the reference's launcher env vars
    (``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``,
    ``DMLC_WORKER_ID``).  Raises if neither arguments nor env are present.

    Failure handling (this used to hang forever on an unreachable
    coordinator): each join attempt waits at most ``timeout`` seconds
    (default: ``MXTPU_DIST_TIMEOUT`` env or 300), and is retried up to
    ``retries`` times with exponential backoff starting at ``backoff``
    seconds — under a real launcher the coordinator routinely comes up
    AFTER the workers.  The final failure is wrapped in an
    :class:`MXNetError` naming the coordinator and rank.
    """
    if is_initialized():
        return
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9099")
        coordinator = f"{uri}:{port}" if uri else None
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        wid = os.environ.get("DMLC_WORKER_ID")
        process_id = int(wid) if wid else None
    if num_processes == 1:
        return  # single worker: nothing to join
    if coordinator is None or num_processes is None or process_id is None:
        missing = []
        if coordinator is None:
            missing.append("DMLC_PS_ROOT_URI (+ optional DMLC_PS_ROOT_PORT)")
        if num_processes is None:
            missing.append("DMLC_NUM_WORKER")
        if process_id is None:
            missing.append("DMLC_WORKER_ID")
        raise MXNetError(
            "multi-process kvstore requires the process group to be "
            "initialized, but these launcher env vars are unset: "
            + ", ".join(missing) +
            " — set them (reference launcher env vars) or call "
            "mxnet_tpu.parallel.dist.init_process_group(coordinator, "
            "num_processes, process_id) before kv.create('dist_sync')")
    if timeout is None:
        timeout = float(os.environ.get("MXTPU_DIST_TIMEOUT", "300"))
    import jax
    from ..faults import retry_call

    def _join():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(1, int(timeout)))
        except Exception:
            # a failed connect leaves jax's global client/service assigned
            # (State.initialize sets them BEFORE connect()), and a retry
            # would then die on 'initialize should only be called once' —
            # reset so the next attempt is a real join
            try:
                jax.distributed.shutdown()
            except Exception:   # noqa: BLE001 — best-effort state reset
                pass
            raise

    from ..observability.registry import registry as _metrics_registry

    def _count_retry(attempt, exc, delay):
        _metrics_registry().counter("dist.init_retries").inc()

    try:
        retry_call(_join, retries=retries, base_delay=backoff,
                   max_delay=30.0,
                   retry_on=(RuntimeError, ConnectionError, TimeoutError,
                             OSError), on_retry=_count_retry)
    except Exception as exc:
        raise MXNetError(
            f"could not join the process group at {coordinator!r} as rank "
            f"{process_id}/{num_processes} after {retries + 1} attempt(s) "
            f"({timeout:.0f}s connect timeout each): {exc}") from exc


def rank() -> int:
    import jax
    return jax.process_index()


def num_workers() -> int:
    import jax
    return jax.process_count()


def allreduce_host(x):
    """Sum a host-local numpy array across all processes.

    DCN-path reduction for the kvstore object plane (the compiled trainer
    path uses in-graph psum over the device mesh instead).
    """
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(x))
    return np.sum(gathered, axis=0)


def allgather_host(x):
    """Gather each process's host-local numpy array; returns an array with
    a leading num_workers axis (this process's slot included)."""
    import numpy as np
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


def broadcast_host(x):
    """Broadcast rank 0's host-local numpy array to all processes."""
    import numpy as np
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.broadcast_one_to_all(np.asarray(x)))


def barrier(name: str = "mxnet_tpu_barrier") -> None:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
