"""Progress watchdog: detect silent hangs and dump one postmortem.

The async engine's classic failure mode (PAPER.md's survey: MXNet hangs
were notoriously undiagnosable) is not a crash — it's *silence*: the
trainer stops stepping, the decode loop stops decoding, a dispatch
worker wedges on a collective, and every after-the-fact recorder keeps
humming with stale data.  The watchdog closes that gap with three
pieces:

- **Touchpoints** (:class:`Touchpoint`): heartbeat counters bumped from
  the progress loops that matter — ``ResilientTrainer.step``, the
  ``GenerationServer`` decode loop, ``ModelServer`` dispatch workers.
  A beat is ONE attribute increment (the registry Counter direct-bump
  idiom) — hot-path free.
- **The monitor** (:class:`Watchdog`): a daemon thread ticking every
  ``interval_s``.  All silence math lives in :meth:`Watchdog.tick`
  (dt)`` and runs on *accumulated tick time*, never the wall clock —
  the controller idiom, so unit tests drive the full arc with
  synthetic ``dt`` and zero sleeps.  A touchpoint is stalled when it
  goes silent for ``MXTPU_WATCHDOG_FACTOR`` × its own recent p99
  interval, taken from the metrics spine (each touchpoint names the
  duration histogram its loop already feeds; the p99 comes from a
  bucket-count delta — the HistogramDelta idiom — with a lifetime
  fallback), floored at :data:`MIN_THRESHOLD_S` so idle-loop
  heartbeats can't false-fire.
- **The postmortem** (:func:`build_postmortem`): on the FIRST stall of
  a quiet period (dump-once dedup — re-armed only after every stalled
  touchpoint progresses again) the watchdog writes one bundle via the
  flight recorder's atomic writer: all-thread stacks (trace-tagged),
  the four flight rings, the completed-span ring, the active
  cross-thread spans, the sampler's last profile window, and a full
  registry snapshot.  ``watchdog.stalls`` counts detections;
  ``MXTPU_WATCHDOG_ACTION=term`` additionally SIGTERMs the process so
  the existing drain/checkpoint handlers take over.

Also here: :func:`install_stack_signal` — the manual probe.  SIGQUIT
(or ``MXTPU_STACKS_SIGNAL``) dumps all-thread stacks + flight rings to
a flight-adjacent path WITHOUT killing the process, chaining any
previous handler the way the serving SIGTERM drains do.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from ..base import get_env
from .registry import _percentile_from, host_id, registry, state_bounds

__all__ = ["Touchpoint", "Watchdog", "watchdog", "touchpoint",
           "build_postmortem", "install_stack_signal",
           "WATCHDOG_FACTOR_ENV", "WATCHDOG_ACTION_ENV",
           "STACKS_SIGNAL_ENV"]

WATCHDOG_FACTOR_ENV = "MXTPU_WATCHDOG_FACTOR"
WATCHDOG_ACTION_ENV = "MXTPU_WATCHDOG_ACTION"
STACKS_SIGNAL_ENV = "MXTPU_STACKS_SIGNAL"

#: silence floor (seconds): progress loops beat on idle timeouts every
#: 0.1-0.25s, so a sub-second p99 × factor could flag a merely-idle
#: loop — no stall below this is ever actionable
MIN_THRESHOLD_S = 1.0

#: p99 snapshot refresh cadence (accumulated tick seconds): the delta
#: window the "recent p99 interval" is computed over
SNAP_REFRESH_S = 60.0


class Touchpoint:
    """One heartbeat: a progress loop bumps :attr:`n` (``tp.beat()`` is
    a single attribute increment — GIL-atomic, allocation-free, safe on
    dispatch hot paths); the monitor compares successive values.
    ``hist`` names the registry histogram whose observations are this
    loop's per-beat durations — the spine the stall threshold is
    computed from."""

    __slots__ = ("name", "hist", "n")

    def __init__(self, name: str, hist: Optional[str] = None):
        self.name = name
        self.hist = hist
        self.n = 0

    def beat(self) -> None:
        self.n += 1


class Watchdog:
    """The monitor.  Constructor args override the env knobs for tests;
    ``kill_fn`` injects the ``term`` action (default: SIGTERM self, so
    the serving/trainer drain handlers run)."""

    def __init__(self, factor: Optional[float] = None,
                 action: Optional[str] = None,
                 interval_s: float = 0.5,
                 floor_s: float = MIN_THRESHOLD_S,
                 snap_refresh_s: float = SNAP_REFRESH_S,
                 path: Optional[str] = None,
                 kill_fn: Optional[Callable[[], None]] = None):
        self._factor = factor
        self._action = action
        self.interval_s = float(interval_s)
        self.floor_s = float(floor_s)
        self.snap_refresh_s = float(snap_refresh_s)
        self.path = path
        self._kill = kill_fn
        self._touchpoints: Dict[str, Touchpoint] = {}
        self._state: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._dumped = False
        self.last_postmortem: Optional[str] = None
        reg = registry()
        self._c_stalls = reg.counter(
            "watchdog.stalls",
            help="touchpoints flagged stalled (silent past factor x "
                 "their recent p99 interval)")
        self._c_postmortems = reg.counter(
            "watchdog.postmortems",
            help="postmortem bundles written (dump-once per quiet "
                 "period)")

    # -- knobs ---------------------------------------------------------------
    @property
    def factor(self) -> float:
        if self._factor is not None:
            return float(self._factor)
        try:
            return float(get_env(WATCHDOG_FACTOR_ENV) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    @property
    def action(self) -> str:
        if self._action is not None:
            return str(self._action)
        return str(get_env(WATCHDOG_ACTION_ENV) or "dump").strip().lower()

    # -- touchpoints ---------------------------------------------------------
    def touchpoint(self, name: str, hist: Optional[str] = None
                   ) -> Touchpoint:
        """Get-or-create the named touchpoint (idempotent: re-inits —
        trainer restarts, server rebuilds — reuse the heartbeat)."""
        with self._lock:
            tp = self._touchpoints.get(name)
            if tp is None:
                tp = Touchpoint(name, hist)
                self._touchpoints[name] = tp
                self._state[name] = {"last_n": 0, "silent_s": 0.0,
                                     "fired": False, "snap": None,
                                     "snap_age": 0.0, "hist_obj": None,
                                     "p99_us": None}
            elif hist and tp.hist is None:
                tp.hist = hist
        return tp

    def touchpoints(self) -> List[dict]:
        """Live view for ``/debug``: per-touchpoint beat count,
        silence, threshold inputs."""
        with self._lock:
            names = list(self._touchpoints)
        out = []
        for name in names:
            tp = self._touchpoints.get(name)
            st = self._state.get(name)
            if tp is None or st is None:
                continue
            out.append({"touchpoint": name, "beats": tp.n,
                        "hist": tp.hist,
                        "silent_s": round(st["silent_s"], 3),
                        "fired": st["fired"],
                        "p99_us": st["p99_us"]})
        return out

    # -- threshold math (wall-clock-free: everything runs on tick dt) -------
    def _hist_for(self, tp: Touchpoint, st: dict):
        if tp.hist is None:
            return None
        h = st["hist_obj"]
        if h is None:
            h = registry().histogram(tp.hist)
            st["hist_obj"] = h
        return h

    def _recent_p99_us(self, tp: Touchpoint, st: dict
                       ) -> Optional[float]:
        """The touchpoint's recent p99 beat duration from its spine
        histogram: bucket-count delta since the last snapshot refresh
        (the HistogramDelta idiom) when the delta has data, lifetime
        p99 otherwise; None when the histogram never observed (the
        loop hasn't produced a single beat duration — nothing to
        compare silence against)."""
        h = self._hist_for(tp, st)
        if h is None:
            return None
        state = h.state()
        if state["count"] <= 0:
            return None
        snap = st["snap"]
        bounds = state_bounds(state)
        if snap is not None:
            delta_n = state["count"] - snap["count"]
            if delta_n > 0:
                counts = [a - b for a, b in
                          zip(state["counts"], snap["counts"])]
                p99 = _percentile_from(bounds, counts, delta_n,
                                       state["min"], state["max"], 99)
                st["p99_us"] = p99
                return p99
        p99 = _percentile_from(bounds, state["counts"], state["count"],
                               state["min"], state["max"], 99)
        st["p99_us"] = p99
        return p99

    def _exemplar_trace_ids(self, tp: Touchpoint, st: dict, k: int = 3
                            ) -> List[str]:
        """trace_ids from the spine histogram's slowest exemplar
        buckets, newest first — the concrete recent executions of the
        now-silent loop (empty when tracing is off)."""
        h = self._hist_for(tp, st)
        if h is None:
            return []
        try:
            ex = h.exemplars()
        except Exception:   # noqa: BLE001 — introspection only
            return []
        ids: List[str] = []
        for bound in sorted(ex, reverse=True):
            for tid, _v, _ts in reversed(ex[bound]):
                if tid not in ids:
                    ids.append(tid)
                if len(ids) >= k:
                    return ids
        return ids

    def tick(self, dt: float) -> List[dict]:
        """One monitor pass, advancing every touchpoint's silence clock
        by ``dt`` seconds.  Returns the touchpoints that NEWLY crossed
        their stall threshold this tick (after dump/action handling).
        Pure in time: calling ``tick(0.5)`` twelve times is exactly six
        seconds of monitoring, no wall clock consulted."""
        factor = self.factor
        if factor <= 0:
            return []
        with self._lock:
            items = [(tp, self._state[tp.name])
                     for tp in self._touchpoints.values()]
        newly: List[dict] = []
        any_stalled = False
        for tp, st in items:
            n = tp.n
            if n != st["last_n"]:
                st["last_n"] = n
                st["silent_s"] = 0.0
                st["fired"] = False
                st["snap_age"] += dt
                if st["snap"] is None \
                        or st["snap_age"] >= self.snap_refresh_s:
                    h = self._hist_for(tp, st)
                    if h is not None:
                        st["snap"] = h.state()
                    st["snap_age"] = 0.0
                continue
            if n == 0:
                continue   # never beat: the loop hasn't started
            st["silent_s"] += dt
            p99_us = self._recent_p99_us(tp, st)
            if p99_us is None:
                continue
            threshold_s = max(factor * p99_us / 1e6, self.floor_s)
            if st["silent_s"] < threshold_s:
                continue
            any_stalled = True
            if st["fired"]:
                continue
            st["fired"] = True
            self._c_stalls.inc()
            newly.append({"touchpoint": tp.name,
                          "beats": n,
                          "silent_s": round(st["silent_s"], 3),
                          "threshold_s": round(threshold_s, 3),
                          "p99_us": round(p99_us, 1),
                          "factor": factor,
                          "recent_trace_ids":
                              self._exemplar_trace_ids(tp, st)})
        if newly and not self._dumped:
            # dump-once dedup: one bundle per quiet period — a second
            # touchpoint starving behind the same hang (decode stalls
            # because dispatch stalled) must not overwrite the bundle
            # that shows the original stall
            self._dumped = True
            self._fire(newly)
        if not any_stalled and self._dumped:
            self._dumped = False   # everything progressed: re-arm
        return newly

    def _fire(self, stalled: List[dict]) -> None:
        names = ",".join(s["touchpoint"] for s in stalled)
        path = self._dump_postmortem(f"watchdog stall: {names}", stalled)
        if self.action == "term":
            kill = self._kill
            if kill is None:
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                kill()
        else:
            _ = path

    def _dump_postmortem(self, reason: str, stalled: List[dict]
                         ) -> Optional[str]:
        from . import flight as _flight
        bundle = build_postmortem(reason, stalled)
        path = self.path
        if path is None:
            try:
                path = _flight.recorder().sibling_path("postmortem")
            except Exception:   # noqa: BLE001 — fall back to tmp
                path = os.path.join(
                    "/tmp", f"mxtpu_postmortem_{os.getpid()}.json")
        out = _flight.write_json_atomic(bundle, path)
        if out is not None:
            self.last_postmortem = out
            self._c_postmortems.inc()
            try:
                print(f"mxnet_tpu watchdog: wrote postmortem to {out} "
                      f"({reason})", file=sys.stderr)
            except Exception:   # noqa: BLE001 — bookkeeping only
                pass
        return out

    # -- monitor thread ------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> bool:
        """Start the monitor daemon (idempotent).  Enables cross-thread
        span tracking for its lifetime so postmortems carry the active
        spans."""
        from . import tracing as _tracing
        with self._lock:
            if self.running:
                return False
            self._stop_evt.clear()
            _tracing.enable_thread_span_tracking()
            t = threading.Thread(target=self._run,
                                 name="mxtpu-watchdog", daemon=True)
            self._thread = t
        t.start()
        return True

    def stop(self, timeout: float = 2.0) -> None:
        from . import tracing as _tracing
        with self._lock:
            t, self._thread = self._thread, None
            if t is None:
                return
            self._stop_evt.set()
        t.join(timeout)
        _tracing.disable_thread_span_tracking()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick(self.interval_s)
            except Exception:   # noqa: BLE001 — the watchdog must
                pass            # never take down the watched job


def build_postmortem(reason: str,
                     stalled: Optional[List[dict]] = None) -> dict:
    """Assemble the full hang-postmortem bundle: stacks + flight rings
    + span ring + active spans + last profile window + registry
    snapshot.  Every section is best-effort — a half-wedged process
    still yields whatever it can."""
    bundle: dict = {"reason": reason,
                    "ts": round(time.time(), 3),
                    "host": host_id(),
                    "pid": os.getpid(),
                    "stalled": stalled or []}
    from . import sampler as _sampler
    try:
        bundle["stacks"] = _sampler.thread_stacks()
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["stacks"] = []
    try:
        from . import flight as _flight
        bundle["flight"] = _flight.recorder().live()
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["flight"] = {}
    try:
        from . import tracing as _tracing
        bundle["trace_spans"] = _tracing.tracer().spans()
        bundle["active_spans"] = {
            str(ident): {"trace_id": getattr(sp, "trace_id", None),
                         "span": getattr(sp, "name", None)}
            for ident, sp in _tracing.thread_spans().items()}
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["trace_spans"] = []
        bundle["active_spans"] = {}
    try:
        win = _sampler.sampler().last_window()
        if win is not None:
            bundle["profile"] = win.to_dict()
            bundle["profile"]["collapsed"] = win.collapsed()
    except Exception:   # noqa: BLE001 — best-effort section
        pass
    try:
        bundle["snapshot"] = registry().snapshot()
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["snapshot"] = {}
    return bundle


# -- manual stack-dump signal -------------------------------------------------

_signal_installed = False


def _dump_stacks_bundle() -> Optional[str]:
    """Stacks + flight rings to a flight-adjacent path (the signal
    handler's payload; also directly callable)."""
    from . import flight as _flight
    from . import sampler as _sampler
    bundle: dict = {"reason": "stack signal",
                    "ts": round(time.time(), 3),
                    "host": host_id(),
                    "pid": os.getpid()}
    try:
        bundle["stacks"] = _sampler.thread_stacks()
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["stacks"] = []
    try:
        rec = _flight.recorder()
        bundle["flight"] = rec.live()
        path = rec.sibling_path("stacks")
    except Exception:   # noqa: BLE001 — best-effort section
        bundle["flight"] = {}
        path = os.path.join("/tmp", f"mxtpu_stacks_{os.getpid()}.json")
    out = _flight.write_json_atomic(bundle, path)
    if out is not None:
        try:
            print(f"mxnet_tpu: wrote thread stacks to {out}",
                  file=sys.stderr)
        except Exception:   # noqa: BLE001 — bookkeeping only
            pass
    return out


def install_stack_signal() -> bool:
    """Install the ``MXTPU_STACKS_SIGNAL`` (default SIGQUIT) handler:
    dump all-thread stacks + flight rings WITHOUT dying, then chain the
    previous handler (the serving SIGTERM-drain chaining discipline, so
    stacking this on an already-handled signal keeps both behaviors).
    Idempotent; returns False when disabled (empty knob), the name is
    unknown, or installation is impossible (non-main thread)."""
    global _signal_installed
    name = str(get_env(STACKS_SIGNAL_ENV) or "").strip()
    if not name:
        return False
    if _signal_installed:
        return True
    sig = getattr(signal, name, None)
    if not isinstance(sig, signal.Signals):
        return False
    prev = signal.getsignal(sig)

    def _handler(signum, frame):
        # the dump walks every thread and may sync device values —
        # never do that inside a signal frame; hand it to a thread and
        # return immediately (the install_sigterm drain-thread shape)
        threading.Thread(target=_dump_stacks_bundle,
                         name="mxtpu-stacks-dump", daemon=True).start()
        if callable(prev) and prev not in (signal.SIG_IGN,
                                           signal.SIG_DFL):
            try:
                prev(signum, frame)
            except Exception:   # noqa: BLE001 — a broken chained
                pass            # handler must not kill the dump

    try:
        signal.signal(sig, _handler)
    except ValueError:   # not the main thread
        return False
    _signal_installed = True
    return True


# -- process singleton + env opt-in ------------------------------------------

_watchdog_lock = threading.Lock()
_watchdog_inst: Optional[Watchdog] = None


def watchdog() -> Watchdog:
    """THE process-global watchdog (the registry()/tracer() idiom)."""
    global _watchdog_inst
    inst = _watchdog_inst
    if inst is not None:
        return inst
    with _watchdog_lock:
        if _watchdog_inst is None:
            _watchdog_inst = Watchdog()
        return _watchdog_inst


def touchpoint(name: str, hist: Optional[str] = None) -> Touchpoint:
    """Register (or fetch) a heartbeat touchpoint on the global
    watchdog and start the monitor when ``MXTPU_WATCHDOG_FACTOR`` > 0
    — the one-liner the progress loops call at init."""
    wd = watchdog()
    tp = wd.touchpoint(name, hist)
    if wd.factor > 0 and not wd.running:
        wd.start()
    return tp
