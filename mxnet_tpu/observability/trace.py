"""Lightweight trace spans over the metrics registry.

``span(name)`` is a context manager that records the wall-time of its
body (in µs) into the histogram ``name`` — the per-step / per-flush
timing surface the ROADMAP's observability follow-up asks for.  Two
integration points:

- **Registry**: every exit observes the duration into
  ``registry().histogram(name)``, so percentiles surface through
  ``snapshot()`` / the Prometheus endpoint with zero extra plumbing.
- **Profiler**: when engine dispatch listeners are installed (i.e. the
  profiler is running), the span additionally emits a ``span:<name>``
  event through the same listener hook op dispatches use, so spans
  appear in the chrome trace next to the ops they contain.

Spans nest: a thread-local stack tracks the active chain (``current()``
returns the innermost name, ``stack()`` the whole chain outermost-first).
The stack is maintained exception-safely — a span body that raises still
pops and still records its duration.

Cost discipline: entering a span is a perf_counter() call and a list
append; exiting is a perf_counter(), a list pop, and one histogram
observe (bisect + int adds under a lock).  No allocation beyond the span
object, no formatting.  Spans guard paths that run per step / per flush
/ per batch — not per op; the op hot path keeps its existing
listener-gated timing.
"""
from __future__ import annotations

import threading
from time import perf_counter
from typing import List, Optional

from ..engine import engine
from . import tracing as _tracing
from .registry import registry

__all__ = ["span", "current", "stack", "add_span_listener",
           "remove_span_listener"]

_tls = threading.local()

# span sinks: fn(name, t_end_seconds, duration_us, args) called on
# every span exit (``args`` is the span's metadata dict or None).  The
# profiler installs one so spans land on its chrome-trace timeline as
# PROPER duration events (pid=host, tid=thread, chrome-trace ``args``
# carrying step/batch ids) next to op events — unlike the
# engine-listener echo below, installing a span listener does NOT
# suspend bulked dispatch (spans wrap steps/flushes, not ops, so they
# need no per-op outputs).
_span_listeners: List = []


def add_span_listener(fn) -> None:
    """Install a span sink: ``fn(name, t_end, duration_us, args)`` with
    ``t_end`` in ``time.perf_counter()`` seconds and ``args`` the
    span's metadata dict (or None)."""
    if fn not in _span_listeners:
        _span_listeners.append(fn)


def remove_span_listener(fn) -> None:
    if fn in _span_listeners:
        _span_listeners.remove(fn)


def _stack() -> List[str]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current() -> Optional[str]:
    """Innermost active span name on this thread, or None."""
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


def stack() -> List[str]:
    """The active span chain on this thread, outermost first (a copy)."""
    return list(getattr(_tls, "stack", ()))


class span:
    """``with span("resilience.step_us"): ...`` — record the body's
    wall-time into the histogram of that name.

    ``histogram=False`` keeps the nesting/bookkeeping (and the profiler
    event) without creating a registry metric — for ad-hoc scoping.
    The measured duration is available afterwards as ``.duration_us``.

    ``args`` is an optional metadata dict (step number, batch id, ...):
    it never touches the histogram (labels would explode cardinality)
    but rides to span listeners, so the profiler surfaces it as the
    chrome-trace event's ``args`` — hover a step span in the timeline
    and see WHICH step it was.  Cost: one attribute store when unused.
    """

    __slots__ = ("name", "duration_us", "args", "t_end", "_t0",
                 "_record")

    def __init__(self, name: str, histogram: bool = True,
                 args: Optional[dict] = None):
        self.name = name
        self.duration_us = 0.0
        self.t_end = 0.0
        self.args = args
        self._record = histogram
        # create (or fetch) the histogram at construction, not exit —
        # name errors surface where the span is written, and __exit__
        # stays allocation-free
        if histogram:
            registry().histogram(name)

    def __enter__(self) -> "span":
        _stack().append(self.name)
        self._t0 = perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t_end = self.t_end = perf_counter()
        self.duration_us = (t_end - self._t0) * 1e6
        s = getattr(_tls, "stack", None)
        if s:
            s.pop()
        if self._record:
            registry().get(self.name).observe(self.duration_us)
        # causal tracing: inside a traced region (an active tracing
        # context) every measured span ALSO lands in the trace as a
        # child — the jit step, checkpoint commit, and collective spans
        # join the step trace with zero call-site changes.  Idle cost:
        # one ContextVar.get.
        _tracing.record_child(self.name, t_end, self.duration_us,
                              self.args)
        for fn in _span_listeners:
            # the profiler's timeline sink: proper duration events with
            # real start/end timestamps on the host/thread lanes (and
            # the span's args as chrome-trace event args)
            fn(self.name, t_end, self.duration_us, self.args)
        eng = engine()
        if eng._listeners:
            # monitors tapping raw engine dispatches still see the span
            # in the same event stream (the profiler ignores this echo —
            # it gets the real event through the span listener above)
            for fn in eng._listeners:
                fn(f"span:{self.name}", (), self.duration_us)
        return None
