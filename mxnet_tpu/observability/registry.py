"""Process-global metrics registry: Counter / Gauge / Histogram.

Design constraints, in order:

1. **The disabled path must stay cheap.**  Nothing here formats, logs, or
   allocates per event: a counter bump is a plain int add and a histogram
   observation is a bisect over a small tuple plus two adds — formatting
   happens only when something actually scrapes (``snapshot()`` /
   the Prometheus endpoint).  Hot paths that previously bumped a bare
   ``self._n += 1`` may keep exactly that cost by bumping ``counter.n``
   directly (the documented inlined idiom — same GIL-granularity fidelity
   the plain attributes they replace had); ``inc()`` is the exact,
   lock-protected path for everything that is not a per-op hot loop.
2. **One surface.**  Every metric in the process is reachable through
   ``registry().snapshot()`` under a namespaced dotted name
   (``engine.ops_dispatched``, ``resilience.steps_skipped``, ...), so an
   exporter or a test needs exactly one call.
3. **Pull-based.**  Producers only ever mutate ints; aggregation
   (percentiles, means, text formats) is computed at read time by the
   consumer.

Histogram buckets are FIXED log-scale: ``bounds[i] = base * growth**i``.
The default (``base=1.0``, ``growth=10**0.1``, 10 buckets per decade over
12 decades) resolves p50/p90/p99 of microsecond-scale latencies to within
about ±12% — plenty for flush/step timing — while keeping ``observe()``
allocation-free and O(log n_buckets).
"""
from __future__ import annotations

import json
import re
import threading
import time as _time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Tuple, Union

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "host_id", "gather_host_states", "last_host_states",
           "ingest_host_states", "merge_host_states",
           "group_host_entries", "state_bounds",
           "state_cumulative_buckets", "set_exemplar_trace_hook"]

# -- histogram exemplars (causal tracing) ------------------------------------
#
# When the tracing layer is live it installs a hook returning the ACTIVE
# trace_id (or None); every Histogram.observe then records that id into
# the observed bucket (last-EXEMPLAR_K per bucket), so a histogram's p99
# bucket points at real traces instead of an anonymous count.  With no
# hook installed (tracing never imported/enabled) observe pays exactly
# one module-global read over its pre-exemplar cost.

EXEMPLAR_K = 4

_exemplar_trace_hook: Optional[Callable[[], Optional[str]]] = None


def set_exemplar_trace_hook(fn: Optional[Callable[[], Optional[str]]]
                            ) -> None:
    """Install (or clear, with None) the active-trace-id provider the
    tracing layer exposes — :func:`mxnet_tpu.observability.tracing.
    tracer` is the only sanctioned caller."""
    global _exemplar_trace_hook
    _exemplar_trace_hook = fn

# namespaced dotted names: `engine.ops_dispatched`, `loader.batches`, ...
_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*(\.[a-z0-9_]+)+$")


class Counter:
    """Monotonic event count.  ``inc()`` is the lock-exact path; hot
    loops may bump ``.n`` directly (see module docstring)."""

    __slots__ = ("name", "n", "help", "_lock")
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.n = 0
        self.help = help
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        with self._lock:
            self.n += amount

    @property
    def value(self) -> int:
        return self.n

    def reset(self) -> None:
        with self._lock:
            self.n = 0

    def read(self) -> int:
        return self.n

    def __repr__(self) -> str:
        return f"Counter({self.name}={self.n})"


class Gauge:
    """Last-write-wins instantaneous value (queue depth, loss scale)."""

    __slots__ = ("name", "_v", "help", "_lock")
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self._v = 0.0
        self.help = help
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        self._v = float(value)

    @property
    def value(self) -> float:
        return self._v

    def reset(self) -> None:
        self._v = 0.0

    def read(self) -> float:
        return self._v

    def __repr__(self) -> str:
        return f"Gauge({self.name}={self._v})"


def _percentile_from(bounds, counts, count, vmin, vmax, q: float) -> float:
    """Bucket-percentile math shared by live Histograms and merged
    multi-host states: the containing bucket's upper bound, clamped to
    the observed min/max so edge buckets don't overstate."""
    if not count:
        return 0.0
    rank = max(1, int(round(q / 100.0 * count)))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            if i >= len(bounds):              # overflow bucket
                return float(vmax)
            hi = bounds[i]
            lo = vmin if vmin is not None else hi
            return float(min(max(hi, lo), vmax))
    return float(vmax)


def _aggregate_from(bounds, counts, count, total, vmin, vmax) -> dict:
    """The ``read()``-style aggregate dict from raw bucket state."""
    return {
        "count": count,
        "sum": round(total, 3),
        "mean": round(total / count, 3) if count else 0.0,
        "min": round(vmin, 3) if vmin is not None else 0.0,
        "max": round(vmax, 3) if vmax is not None else 0.0,
        "p50": round(_percentile_from(bounds, counts, count, vmin, vmax,
                                      50), 3),
        "p90": round(_percentile_from(bounds, counts, count, vmin, vmax,
                                      90), 3),
        "p99": round(_percentile_from(bounds, counts, count, vmin, vmax,
                                      99), 3),
    }


class Histogram:
    """Fixed log-scale-bucket histogram (see module docstring).

    ``counts[i]`` counts observations with ``v <= bounds[i]`` (and above
    ``bounds[i-1]``); ``counts[-1]`` is the overflow bucket.  All updates
    happen under one lock — a handful of int/float adds, no formatting.
    """

    __slots__ = ("name", "base", "growth", "bounds", "counts", "count",
                 "total", "vmin", "vmax", "help", "_lock", "_ex")
    kind = "histogram"

    def __init__(self, name: str, base: float = 1.0,
                 growth: float = 10.0 ** 0.1, buckets: int = 120,
                 help: str = ""):
        if base <= 0 or growth <= 1.0 or buckets < 1:
            raise MXNetError(
                f"Histogram {name!r}: need base > 0, growth > 1, "
                f"buckets >= 1 (got {base}, {growth}, {buckets})")
        self.name = name
        self.base = float(base)
        self.growth = float(growth)
        self.bounds: Tuple[float, ...] = tuple(
            base * growth ** i for i in range(buckets))
        self.counts: List[int] = [0] * (buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self.help = help
        self._lock = threading.Lock()
        # bucket index -> [(trace_id, value, wall_ts), ...] last-K, only
        # ever populated while the tracing exemplar hook is installed
        self._ex: Optional[Dict[int, list]] = None

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        i = bisect_left(self.bounds, value)
        hook = _exemplar_trace_hook
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += value
            if self.vmin is None or value < self.vmin:
                self.vmin = value
            if self.vmax is None or value > self.vmax:
                self.vmax = value
            if hook is not None:
                tid = trace_id if trace_id is not None else hook()
                if tid:
                    ex = self._ex
                    if ex is None:
                        ex = self._ex = {}
                    lst = ex.get(i)
                    if lst is None:
                        ex[i] = lst = []
                    lst.append((tid, value, round(_time.time(), 3)))
                    if len(lst) > EXEMPLAR_K:
                        lst.pop(0)

    def exemplars(self) -> Dict[float, list]:
        """Recorded exemplars keyed by bucket UPPER BOUND (``inf`` for
        the overflow bucket): ``{bound: [(trace_id, value, ts), ...]}``
        newest last.  The resolution path for a tail outlier: p99 bucket
        → trace_id → the span ring
        (:meth:`~mxnet_tpu.observability.tracing.Tracer.find`)."""
        with self._lock:
            if not self._ex:
                return {}
            n = len(self.bounds)
            return {(self.bounds[i] if i < n else float("inf")): list(lst)
                    for i, lst in self._ex.items()}

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) from the buckets.
        Resolution = one bucket (±(growth-1)/2 relative)."""
        with self._lock:
            return _percentile_from(self.bounds, self.counts, self.count,
                                    self.vmin, self.vmax, q)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * len(self.counts)
            self.count = 0
            self.total = 0.0
            self.vmin = None
            self.vmax = None
            self._ex = None

    def read(self) -> dict:
        """Aggregate view (the snapshot() value for histograms)."""
        with self._lock:
            counts = list(self.counts)
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        return _aggregate_from(self.bounds, counts, count, total, vmin,
                               vmax)

    def state(self) -> dict:
        """Raw, merge-able state (JSON-serializable) — the unit the
        multi-host gather ships over DCN.  ``base``/``growth`` travel
        along so a peer can rebuild the bounds and refuse to merge a
        histogram whose bucketing differs."""
        with self._lock:
            return {"kind": "histogram", "base": self.base,
                    "growth": self.growth, "counts": list(self.counts),
                    "count": self.count, "total": self.total,
                    "min": self.vmin, "max": self.vmax, "help": self.help}

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs for Prometheus-style
        export; the final pair is (inf, total_count).  Empty buckets with
        no observations at or above them are elided to keep scrapes
        small."""
        out: List[Tuple[float, int]] = []
        with self._lock:
            acc = 0
            for i, c in enumerate(self.counts[:-1]):
                acc += c
                if c:
                    out.append((self.bounds[i], acc))
            out.append((float("inf"), self.count))
        return out

    def __repr__(self) -> str:
        return f"Histogram({self.name}, n={self.count})"


_Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Thread-safe name → metric map.  ``counter``/``gauge``/``histogram``
    get-or-create (idempotent — every call site can ask for its metric
    without coordination); asking for an existing name with a different
    type is always a bug and raises."""

    def __init__(self):
        # process-global registry, constructed once (reached from
        # dispatch only via the one-time Engine singleton __init__)
        # mxlint: disable=hot-path-purity — one-time singleton init
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, name: str, cls, **kwargs) -> _Metric:
        m = self._metrics.get(name)       # lock-free fast path (GIL dict)
        if m is not None:
            if type(m) is not cls:
                raise MXNetError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {cls.__name__}")
            if kwargs.get("help") and not m.help:
                # a later call site may carry the description the first
                # (hot-path) registration omitted
                m.help = kwargs["help"]
            return m
        if not _NAME_RE.match(name):
            raise MXNetError(
                f"bad metric name {name!r}: use namespaced lowercase "
                f"dotted names like 'engine.ops_dispatched'")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise MXNetError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str, **kwargs) -> Histogram:
        return self._get_or_create(name, Histogram, **kwargs)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self, all_hosts: bool = False) -> dict:
        """Every metric in ONE dict: counters → int, gauges → float,
        histograms → their aggregate sub-dict.  The single pull surface
        the exporters, tests, and the back-compat views read.

        ``all_hosts=True`` is the FLEET view: every host's raw metric
        state is gathered over the DCN ``allgather_host`` path (a
        collective — all processes must call it together, e.g. at a
        checkpoint boundary) and merged: counters sum, histogram buckets
        add, and every series carries a ``host`` map keyed by process
        index.  Falls back to the local host (labeled ``host=0``) when
        the process group is not initialized, so single-process code
        paths need no guard."""
        if all_hosts:
            return merge_host_states(gather_host_states(self))
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.read() for name, m in items}

    def export_state(self) -> dict:
        """Raw per-metric state (JSON-serializable) — what one host
        contributes to the multi-host gather."""
        with self._lock:
            items = sorted(self._metrics.items())
        out = {}
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = {"kind": "counter", "n": m.n, "help": m.help}
            elif isinstance(m, Gauge):
                out[name] = {"kind": "gauge", "v": m.value,
                             "help": m.help}
            else:
                out[name] = m.state()
        return out

    def reset(self, prefix: str = "") -> None:
        """Zero every metric under ``prefix`` ('' = all) — test harness /
        benchmark epoch boundaries."""
        with self._lock:
            targets = [m for name, m in self._metrics.items()
                       if name.startswith(prefix)]
        for m in targets:
            m.reset()


_registry_lock = threading.Lock()
_registry_inst: Optional[MetricsRegistry] = None


def registry() -> MetricsRegistry:
    """THE process-global registry (analog of ``Engine.get()``)."""
    global _registry_inst
    inst = _registry_inst          # lock-free fast path: set-once
    if inst is not None:
        return inst
    with _registry_lock:
        if _registry_inst is None:
            _registry_inst = MetricsRegistry()
        return _registry_inst


# -- multi-host aggregation (the fleet view) --------------------------------
#
# Per-host registries stay strictly local (producers never pay a network
# cost); the fleet view is assembled on demand by gathering every host's
# export_state() as one JSON blob over parallel.dist's allgather_host DCN
# path.  The gather is a COLLECTIVE — all processes must reach it together
# (checkpoint boundaries are the natural sync point) — so nothing here
# runs implicitly from a scrape handler; the Prometheus AGGREGATE mode
# serves the most recently gathered states instead (see export.py).

_last_host_states: Optional[List[Tuple[int, dict]]] = None


def host_id() -> int:
    """This process's index in the fleet (0 when single-process).

    The PHYSICAL (launcher-assigned) id, deliberately not the logical
    rank: a fleet re-form re-assigns logical ranks contiguously over
    the survivors, and a metric series whose ``host`` label silently
    remapped mid-run would splice two different machines' histories
    together."""
    try:
        from ..parallel import dist
        if dist.is_initialized():
            return dist.phys_rank()
    except Exception:   # noqa: BLE001 — jax state probing must not
        pass            # break local-only metrics
    return 0


def gather_host_states(reg: Optional[MetricsRegistry] = None
                       ) -> List[Tuple[int, dict]]:
    """Gather ``(host_index, export_state())`` from every process.
    Collective when the process group is initialized; local-only
    fallback otherwise.  The result is memoized so the Prometheus
    AGGREGATE endpoint can serve the fleet view between gathers."""
    global _last_host_states
    reg = reg if reg is not None else registry()
    local = reg.export_state()
    states = [(host_id(), local)]
    try:
        from ..parallel import dist
        if dist.is_initialized():
            blobs = dist.allgather_bytes(
                json.dumps(local).encode("utf-8"))
            states = [(i, json.loads(b.decode("utf-8")))
                      for i, b in enumerate(blobs)]
            # memoize ONLY a successful fleet gather: a transient
            # failure must not evict the last good remote view the
            # AGGREGATE endpoint is serving (last_host_states always
            # reads the LOCAL host live regardless)
            _last_host_states = states
    except Exception as e:   # noqa: BLE001 — a failed gather degrades to
        # the local view instead of taking down the caller (observability
        # must never kill the job it observes)
        import warnings
        warnings.warn(f"multi-host metric gather failed; serving the "
                      f"local view only ({e})", RuntimeWarning,
                      stacklevel=2)
    return states


def ingest_host_states(states: List[Tuple[int, dict]]) -> None:
    """Install externally-gathered per-host states as the remote view
    ``last_host_states`` (and the ``MXTPU_METRICS_AGGREGATE`` endpoint)
    serve between collective gathers.

    The timer-thread fleet gather
    (:class:`~mxnet_tpu.tuning.FleetGatherController`) feeds this from
    the barrier-free KV transport: hosts publish and collect at their
    own cadence, so a peer's state may be one of its ticks stale —
    exactly the "remote as-of last gather" contract the serving path
    already documents, just timer-fresh instead of checkpoint-fresh."""
    global _last_host_states
    _last_host_states = sorted(
        ((int(h), dict(st)) for h, st in states), key=lambda hs: hs[0])


def last_host_states(reg: Optional[MetricsRegistry] = None
                     ) -> List[Tuple[int, dict]]:
    """Per-host states for the serving path: THIS host's state is read
    live from the registry; remote hosts are as-of the most recent
    gather (scrapes must not run collectives — see gather_host_states).
    Before any gather (or single-process) this is just the local host."""
    reg = reg if reg is not None else registry()
    me = host_id()
    states = [(me, reg.export_state())]
    if _last_host_states is not None:
        states += [(h, st) for h, st in _last_host_states if h != me]
        states.sort(key=lambda hs: hs[0])
    return states


def state_bounds(state: dict) -> Tuple[float, ...]:
    """Rebuild a histogram state's bucket upper bounds from its
    ``base``/``growth`` (the overflow bucket carries no bound)."""
    n = len(state["counts"]) - 1
    base, growth = state["base"], state["growth"]
    return tuple(base * growth ** i for i in range(n))


def state_cumulative_buckets(state: dict) -> List[Tuple[float, int]]:
    """(upper_bound, cumulative_count) pairs from a raw histogram state
    — the state-dict twin of :meth:`Histogram.cumulative_buckets`, with
    the same elision of empty buckets and final (inf, count) pair."""
    bounds = state_bounds(state)
    out: List[Tuple[float, int]] = []
    acc = 0
    for i, c in enumerate(state["counts"][:-1]):
        acc += c
        if c:
            out.append((bounds[i], acc))
    out.append((float("inf"), state["count"]))
    return out


def group_host_entries(states: List[Tuple[int, dict]]):
    """Iterate the union of metric names across per-host states as
    ``(name, kind, [(host, entry), ...])``, keeping only entries whose
    kind matches the first host reporting that name (a disagreeing host
    is dropped from that series rather than corrupting it).  Shared by
    the merge and the host-labeled Prometheus text format so the two
    views can't drift."""
    names = sorted({n for _, st in states for n in st})
    for name in names:
        entries = [(h, st[name]) for h, st in states if name in st]
        kind = entries[0][1].get("kind")
        yield name, kind, [(h, e) for h, e in entries
                           if e.get("kind") == kind]


def merge_host_states(states: List[Tuple[int, dict]]) -> dict:
    """Merge per-host raw states into one host-labeled fleet snapshot:

    - counter → ``{"kind", "total", "host": {"<i>": n}}``
    - gauge → ``{"kind", "host": {"<i>": v}}`` (no cross-host sum — a
      queue depth summed over hosts is meaningless; PromQL aggregates)
    - histogram → merged aggregate (buckets added elementwise across
      hosts with identical bucketing) plus a per-host aggregate map

    A host whose metric kind or bucketing disagrees with the first
    host's is reported under its host label but left out of the merged
    totals rather than silently corrupting them."""
    merged: Dict[str, dict] = {}
    for name, kind, entries in group_host_entries(states):
        if kind == "counter":
            merged[name] = {
                "kind": "counter",
                "total": sum(e["n"] for _, e in entries),
                "host": {str(h): e["n"] for h, e in entries}}
        elif kind == "gauge":
            merged[name] = {
                "kind": "gauge",
                "host": {str(h): e["v"] for h, e in entries}}
        elif kind == "histogram":
            ref = entries[0][1]
            bounds = state_bounds(ref)
            counts = [0] * len(ref["counts"])
            count, total = 0, 0.0
            vmin, vmax = None, None
            per_host = {}
            for h, e in entries:
                per_host[str(h)] = _aggregate_from(
                    state_bounds(e), e["counts"], e["count"], e["total"],
                    e["min"], e["max"])
                if (e["base"], e["growth"], len(e["counts"])) != \
                        (ref["base"], ref["growth"], len(ref["counts"])):
                    continue     # incompatible bucketing: labeled only
                for i, c in enumerate(e["counts"]):
                    counts[i] += c
                count += e["count"]
                total += e["total"]
                if e["min"] is not None and \
                        (vmin is None or e["min"] < vmin):
                    vmin = e["min"]
                if e["max"] is not None and \
                        (vmax is None or e["max"] > vmax):
                    vmax = e["max"]
            agg = _aggregate_from(bounds, counts, count, total, vmin,
                                  vmax)
            agg["kind"] = "histogram"
            agg["host"] = per_host
            merged[name] = agg
    return merged
