"""Process-global metrics registry: Counter / Gauge / Histogram.

Design constraints, in order:

1. **The disabled path must stay cheap.**  Nothing here formats, logs, or
   allocates per event: a counter bump is a plain int add and a histogram
   observation is a bisect over a small tuple plus two adds — formatting
   happens only when something actually scrapes (``snapshot()`` /
   the Prometheus endpoint).  Hot paths that previously bumped a bare
   ``self._n += 1`` may keep exactly that cost by bumping ``counter.n``
   directly (the documented inlined idiom — same GIL-granularity fidelity
   the plain attributes they replace had); ``inc()`` is the exact,
   lock-protected path for everything that is not a per-op hot loop.
2. **One surface.**  Every metric in the process is reachable through
   ``registry().snapshot()`` under a namespaced dotted name
   (``engine.ops_dispatched``, ``resilience.steps_skipped``, ...), so an
   exporter or a test needs exactly one call.
3. **Pull-based.**  Producers only ever mutate ints; aggregation
   (percentiles, means, text formats) is computed at read time by the
   consumer.

Histogram buckets are FIXED log-scale: ``bounds[i] = base * growth**i``.
The default (``base=1.0``, ``growth=10**0.1``, 10 buckets per decade over
12 decades) resolves p50/p90/p99 of microsecond-scale latencies to within
about ±12% — plenty for flush/step timing — while keeping ``observe()``
allocation-free and O(log n_buckets).
"""
from __future__ import annotations

import re
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple, Union

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry"]

# namespaced dotted names: `engine.ops_dispatched`, `loader.batches`, ...
_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*(\.[a-z0-9_]+)+$")


class Counter:
    """Monotonic event count.  ``inc()`` is the lock-exact path; hot
    loops may bump ``.n`` directly (see module docstring)."""

    __slots__ = ("name", "n", "_lock")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.n = 0
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        with self._lock:
            self.n += amount

    @property
    def value(self) -> int:
        return self.n

    def reset(self) -> None:
        with self._lock:
            self.n = 0

    def read(self) -> int:
        return self.n

    def __repr__(self) -> str:
        return f"Counter({self.name}={self.n})"


class Gauge:
    """Last-write-wins instantaneous value (queue depth, loss scale)."""

    __slots__ = ("name", "_v", "_lock")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        self._v = float(value)

    @property
    def value(self) -> float:
        return self._v

    def reset(self) -> None:
        self._v = 0.0

    def read(self) -> float:
        return self._v

    def __repr__(self) -> str:
        return f"Gauge({self.name}={self._v})"


class Histogram:
    """Fixed log-scale-bucket histogram (see module docstring).

    ``counts[i]`` counts observations with ``v <= bounds[i]`` (and above
    ``bounds[i-1]``); ``counts[-1]`` is the overflow bucket.  All updates
    happen under one lock — a handful of int/float adds, no formatting.
    """

    __slots__ = ("name", "bounds", "counts", "count", "total", "vmin",
                 "vmax", "_lock")
    kind = "histogram"

    def __init__(self, name: str, base: float = 1.0,
                 growth: float = 10.0 ** 0.1, buckets: int = 120):
        if base <= 0 or growth <= 1.0 or buckets < 1:
            raise MXNetError(
                f"Histogram {name!r}: need base > 0, growth > 1, "
                f"buckets >= 1 (got {base}, {growth}, {buckets})")
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(
            base * growth ** i for i in range(buckets))
        self.counts: List[int] = [0] * (buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += value
            if self.vmin is None or value < self.vmin:
                self.vmin = value
            if self.vmax is None or value > self.vmax:
                self.vmax = value

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) from the buckets:
        the containing bucket's upper bound, clamped to the observed
        min/max so edge buckets don't overstate.  Resolution = one bucket
        (±(growth-1)/2 relative)."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = max(1, int(round(q / 100.0 * self.count)))
            acc = 0
            for i, c in enumerate(self.counts):
                acc += c
                if acc >= rank:
                    if i >= len(self.bounds):    # overflow bucket
                        return float(self.vmax)
                    hi = self.bounds[i]
                    lo = self.vmin if self.vmin is not None else hi
                    return float(min(max(hi, lo), self.vmax))
            return float(self.vmax)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * len(self.counts)
            self.count = 0
            self.total = 0.0
            self.vmin = None
            self.vmax = None

    def read(self) -> dict:
        """Aggregate view (the snapshot() value for histograms)."""
        with self._lock:
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        return {
            "count": count,
            "sum": round(total, 3),
            "mean": round(total / count, 3) if count else 0.0,
            "min": round(vmin, 3) if vmin is not None else 0.0,
            "max": round(vmax, 3) if vmax is not None else 0.0,
            "p50": round(self.percentile(50), 3),
            "p90": round(self.percentile(90), 3),
            "p99": round(self.percentile(99), 3),
        }

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs for Prometheus-style
        export; the final pair is (inf, total_count).  Empty buckets with
        no observations at or above them are elided to keep scrapes
        small."""
        out: List[Tuple[float, int]] = []
        with self._lock:
            acc = 0
            for i, c in enumerate(self.counts[:-1]):
                acc += c
                if c:
                    out.append((self.bounds[i], acc))
            out.append((float("inf"), self.count))
        return out

    def __repr__(self) -> str:
        return f"Histogram({self.name}, n={self.count})"


_Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Thread-safe name → metric map.  ``counter``/``gauge``/``histogram``
    get-or-create (idempotent — every call site can ask for its metric
    without coordination); asking for an existing name with a different
    type is always a bug and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, name: str, cls, **kwargs) -> _Metric:
        m = self._metrics.get(name)       # lock-free fast path (GIL dict)
        if m is not None:
            if type(m) is not cls:
                raise MXNetError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {cls.__name__}")
            return m
        if not _NAME_RE.match(name):
            raise MXNetError(
                f"bad metric name {name!r}: use namespaced lowercase "
                f"dotted names like 'engine.ops_dispatched'")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise MXNetError(
                    f"metric {name!r} is already registered as a "
                    f"{type(m).__name__}, not a {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str, **kwargs) -> Histogram:
        return self._get_or_create(name, Histogram, **kwargs)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self) -> dict:
        """Every metric in ONE dict: counters → int, gauges → float,
        histograms → their aggregate sub-dict.  The single pull surface
        the exporters, tests, and the back-compat views read."""
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.read() for name, m in items}

    def reset(self, prefix: str = "") -> None:
        """Zero every metric under ``prefix`` ('' = all) — test harness /
        benchmark epoch boundaries."""
        with self._lock:
            targets = [m for name, m in self._metrics.items()
                       if name.startswith(prefix)]
        for m in targets:
            m.reset()


_registry_lock = threading.Lock()
_registry_inst: Optional[MetricsRegistry] = None


def registry() -> MetricsRegistry:
    """THE process-global registry (analog of ``Engine.get()``)."""
    global _registry_inst
    inst = _registry_inst          # lock-free fast path: set-once
    if inst is not None:
        return inst
    with _registry_lock:
        if _registry_inst is None:
            _registry_inst = MetricsRegistry()
        return _registry_inst
