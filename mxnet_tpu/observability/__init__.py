"""Unified observability: ONE pull-based metrics surface for the stack.

PR 1 (resilience) and PR 2 (bulked dispatch) each grew an ad-hoc counter
dict (``ResilientTrainer.counters``, ``engine().stats()``); this package
merges them — and every future metric — into a single process-global
registry (ROADMAP follow-up for both PRs):

- :mod:`.registry` — thread-safe ``Counter`` / ``Gauge`` / ``Histogram``
  primitives under namespaced names (``engine.ops_dispatched``,
  ``resilience.steps_skipped``, ``loader.batches``) with one
  ``registry().snapshot()`` returning every metric in one dict.
- :mod:`.trace` — lightweight ``span(name)`` context managers recording
  wall-time into histograms (and echoing to engine profiler listeners
  when installed).
- :mod:`.export` — a Prometheus-text-format HTTP endpoint (opt-in via
  ``MXTPU_METRICS_PORT``; ``MXTPU_METRICS_AGGREGATE`` serves the
  host-labeled fleet view) and a JSONL periodic writer for headless
  runs (``MXTPU_METRICS_JSONL``).
- :mod:`.flight` — a crash flight recorder: a bounded ring of per-step
  records dumped (with a full snapshot) to JSON on unhandled exception
  / preemption / retry exhaustion (``MXTPU_FLIGHT_STEPS`` /
  ``MXTPU_FLIGHT_PATH``).
- :mod:`.sampler` — live introspection half 1: a continuous
  stack-sampling profiler (``MXTPU_PROF_SAMPLE_HZ``) folding all-thread
  stacks into collapsed/flamegraph counts in rotating windows, plus
  on-demand ``thread_stacks()``/``profile()`` for the ``/debug/*``
  endpoints (served by the HttpFrontend and the metrics exporter,
  gated on ``MXTPU_DEBUG_ENDPOINTS``).
- :mod:`.watchdog` — live introspection half 2: heartbeat touchpoints
  in the trainer/serving progress loops, a monitor that flags a
  touchpoint silent past ``MXTPU_WATCHDOG_FACTOR`` × its recent p99
  interval, and a one-shot hang-postmortem bundle (stacks + flight
  rings + span ring + profile window); plus the ``MXTPU_STACKS_SIGNAL``
  (SIGQUIT) manual stack-dump probe.

The fleet view: ``registry().snapshot(all_hosts=True)`` gathers every
host's metrics over the DCN ``allgather_host`` path and merges them
with ``host=<process_index>`` labels (local-only fallback when the
process group is not initialized).

The legacy surfaces stay as thin back-compat views: ``engine().stats()``
and ``ResilientTrainer.counters`` read the same registry metrics.

Import discipline: this ``__init__`` eagerly exposes only the
dependency-free :mod:`.registry` (the engine imports it at module load);
:mod:`.trace` and :mod:`.export` load lazily because they import the
engine back — eager imports here would cycle.
"""
from __future__ import annotations

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       registry)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "trace", "export", "span", "flight", "tracing", "sampler",
           "watchdog"]


def __getattr__(name):
    # importlib, not `from . import X`: the latter re-enters this
    # __getattr__ while the attribute is still unbound and recurses
    import importlib
    if name in ("trace", "span"):
        mod = importlib.import_module(".trace", __name__)
        return mod if name == "trace" else mod.span
    if name in ("export", "flight", "tracing", "sampler", "watchdog"):
        return importlib.import_module("." + name, __name__)
    raise AttributeError(
        f"module 'mxnet_tpu.observability' has no attribute {name!r}")
