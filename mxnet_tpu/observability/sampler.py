"""Continuous stack-sampling profiler: what every thread is doing, NOW.

Every other observability layer here records *after the fact* — the
metrics spine aggregates, traces record completed spans, the flight
recorder dumps at death.  None of them can answer the production
question "this process looks wedged / hot: what is it actually
executing RIGHT NOW?".  The standard answer (Go's ``/debug/pprof``, JVM
thread dumps, py-spy) is a low-overhead sampling profiler: walk
``sys._current_frames()`` at N Hz, fold each thread's frames into a
collapsed stack string, and count occurrences — the flamegraph input
format, ~free for the sampled threads (the walk happens on the sampler
thread; sampled threads pay nothing).

Three consumers:

- the **daemon sampler** (``MXTPU_PROF_SAMPLE_HZ`` > 0): samples
  continuously into rotating :class:`ProfileWindow` buckets
  (``MXTPU_PROF_WINDOW_SECS`` per window, ``MXTPU_PROF_WINDOWS`` kept)
  — always-on production profiling, served by ``/debug/profile`` and
  shipped in watchdog postmortems;
- **on-demand windows** (:func:`profile`): sample synchronously for S
  seconds on the caller's thread — the ``/debug/profile?seconds=S``
  handler, no daemon required;
- **point-in-time dumps** (:func:`thread_stacks`): one full walk of
  every thread, flight-style JSON — ``/debug/stacks``, the
  ``MXTPU_STACKS_SIGNAL`` handler, and watchdog postmortems.

Trace integration: while any consumer is active the tracing layer
mirrors span activations into a cross-thread map
(:func:`..tracing.thread_spans`), so every sample and stack dump is
tagged with the owning thread's active ``trace_id`` — "which
request/step owns this hot stack" falls out for free.

Cost discipline: OFF is the default and the instrumented start sites
(:func:`maybe_start_from_env`) pay one memoized raw-environ probe (the
tracing/engine idiom).  ON, the sampled threads pay only GIL
interference from the sampler's frame walks — the <3% overhead guard
in the test suite pins that on a dispatched-segment loop.  The fold
key is function identity (``file:line-of-def`` stays out; live line
numbers change every sample and would shatter the fold), bounded at
``MAX_DEPTH`` frames.
"""
from __future__ import annotations

import os
import sys
import threading
from collections import deque
from time import perf_counter, sleep, time as _wall
from typing import Deque, Dict, List, Optional, Tuple

from ..base import get_env
from .registry import registry

__all__ = ["ProfileWindow", "StackSampler", "sampler", "profile",
           "thread_stacks", "collapsed_from_windows",
           "chrome_events_from_window", "maybe_start_from_env",
           "SAMPLE_HZ_ENV", "WINDOW_SECS_ENV", "WINDOWS_ENV"]

SAMPLE_HZ_ENV = "MXTPU_PROF_SAMPLE_HZ"
WINDOW_SECS_ENV = "MXTPU_PROF_WINDOW_SECS"
WINDOWS_ENV = "MXTPU_PROF_WINDOWS"

#: frames kept per sampled stack (outermost frames beyond this drop)
MAX_DEPTH = 64

# memoized raw-environ probe for the off path (the tracing idiom: one
# dict hit per maybe_start_from_env call while the knob is unchanged)
_ENV_DATA = getattr(os.environ, "_data", None) if os.name == "posix" \
    else None
if not isinstance(_ENV_DATA, dict):
    _ENV_DATA = None
_HZ_KEY_B = SAMPLE_HZ_ENV.encode()


def _raw_env(key_bytes: bytes, key_str: str):
    """Raw environ entry for a DECLARED knob (compared against a memo;
    parsing goes through get_env only when the raw entry changed)."""
    if _ENV_DATA is not None:
        return _ENV_DATA.get(key_bytes)
    return os.environ.get(key_str)


def _frame_key(code) -> str:
    """Fold key for one frame: function identity, not the live line —
    line numbers move every sample and would shatter the fold."""
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


def _fold(frame, prefix: str) -> str:
    """Collapse a live frame chain into ``prefix;outer;...;leaf``."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < MAX_DEPTH:
        parts.append(_frame_key(f.f_code))
        f = f.f_back
    parts.append(prefix)
    parts.reverse()
    return ";".join(parts)


def _span_tags() -> Dict[int, Tuple[str, str]]:
    """ident → (trace_id, span name) for threads with an active span
    (empty unless thread-span tracking is enabled)."""
    from . import tracing as _tracing
    tags: Dict[int, Tuple[str, str]] = {}
    for ident, sp in _tracing.thread_spans().items():
        tid = getattr(sp, "trace_id", None)
        if tid:
            tags[ident] = (tid, getattr(sp, "name", "") or "")
    return tags


def thread_stacks() -> List[dict]:
    """Every thread's current stack, flight-style JSON: one record per
    thread with name/daemon/ident, outermost-first frames (with LIVE
    line numbers — this is a point-in-time dump, not a fold), and the
    active trace span when tracking is on."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    tags = _span_tags()
    me = threading.get_ident()
    out: List[dict] = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        stack: List[dict] = []
        f = frame
        while f is not None and len(stack) < MAX_DEPTH:
            code = f.f_code
            stack.append({"file": code.co_filename,
                          "func": code.co_name,
                          "line": f.f_lineno})
            f = f.f_back
        stack.reverse()
        rec = {"ident": ident,
               "name": t.name if t is not None else f"thread-{ident}",
               "daemon": bool(t.daemon) if t is not None else None,
               "current": ident == me,
               "frames": stack}
        tag = tags.get(ident)
        if tag is not None:
            rec["trace_id"], rec["span"] = tag
        out.append(rec)
    out.sort(key=lambda r: r["name"])
    return out


class ProfileWindow:
    """One bounded bucket of folded samples: ``counts`` maps
    ``(collapsed_stack, trace_id)`` → occurrences.  The trace_id key
    component keeps per-trace attribution without a second structure;
    :meth:`collapsed` aggregates it away for the flamegraph view."""

    __slots__ = ("t0", "t1", "hz", "samples", "counts", "_t0_pc")

    def __init__(self, hz: float):
        self.t0 = _wall()
        self.t1: Optional[float] = None
        self.hz = float(hz)
        self.samples = 0
        self.counts: Dict[Tuple[str, str], int] = {}
        self._t0_pc = perf_counter()

    @property
    def age_s(self) -> float:
        return perf_counter() - self._t0_pc

    def add(self, stack: str, trace_id: str = "") -> None:
        key = (stack, trace_id)
        self.counts[key] = self.counts.get(key, 0) + 1

    def close(self) -> None:
        if self.t1 is None:
            self.t1 = _wall()

    def collapsed(self) -> str:
        """The window as collapsed-stack text (``stack count`` lines,
        flamegraph.pl / speedscope input), trace tags aggregated away."""
        agg: Dict[str, int] = {}
        for (stack, _tid), n in self.counts.items():
            agg[stack] = agg.get(stack, 0) + n
        return "\n".join(f"{s} {n}" for s, n in
                         sorted(agg.items(), key=lambda kv: -kv[1]))

    def by_trace(self) -> Dict[str, int]:
        """trace_id → sample count (untagged samples under ``""``)."""
        agg: Dict[str, int] = {}
        for (_stack, tid), n in self.counts.items():
            agg[tid] = agg.get(tid, 0) + n
        return agg

    def to_dict(self) -> dict:
        return {"t0": round(self.t0, 3),
                "t1": round(self.t1, 3) if self.t1 is not None else None,
                "hz": self.hz,
                "samples": self.samples,
                "stacks": [{"stack": s, "trace_id": tid, "count": n}
                           for (s, tid), n in
                           sorted(self.counts.items(),
                                  key=lambda kv: -kv[1])]}


def collapsed_from_windows(windows: List[ProfileWindow]) -> str:
    """Merged collapsed-stack text across windows (the
    ``/debug/profile`` all-windows view)."""
    agg: Dict[str, int] = {}
    for w in windows:
        for (stack, _tid), n in w.counts.items():
            agg[stack] = agg.get(stack, 0) + n
    return "\n".join(f"{s} {n}" for s, n in
                     sorted(agg.items(), key=lambda kv: -kv[1]))


def chrome_events_from_window(win: ProfileWindow) -> List[dict]:
    """The window as chrome-trace ``X`` events: per thread lane, each
    folded stack becomes one block whose duration is its sample-count
    share of the window (``count / hz``) — a poor man's flamechart that
    opens directly in Perfetto.  Event args carry the full collapsed
    stack and the trace tag."""
    period_us = 1e6 / max(win.hz, 1e-6)
    lanes: Dict[str, int] = {}
    cursors: Dict[str, float] = {}
    events: List[dict] = []
    base = win.t0 * 1e6
    for (stack, tid), n in sorted(win.counts.items(),
                                  key=lambda kv: -kv[1]):
        thread = stack.split(";", 1)[0]
        lane = lanes.setdefault(thread, len(lanes))
        ts = cursors.get(thread, 0.0)
        dur = n * period_us
        cursors[thread] = ts + dur
        leaf = stack.rsplit(";", 1)[-1]
        args = {"stack": stack, "count": n}
        if tid:
            args["trace_id"] = tid
        events.append({"name": leaf, "ph": "X", "cat": "sample",
                       "pid": 0, "tid": lane, "ts": base + ts,
                       "dur": dur, "args": args})
    events.extend({"name": "thread_name", "ph": "M", "pid": 0,
                   "tid": lane, "args": {"name": thread}}
                  for thread, lane in lanes.items())
    return events


def _collect_into(win: ProfileWindow, skip_ident: int) -> int:
    """One sampling pass: walk every thread's frames (except
    ``skip_ident`` — the sampler itself), fold, count.  Returns the
    number of stacks folded."""
    tags = _span_tags()
    names = {t.ident: t.name for t in threading.enumerate()}
    folded = 0
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        name = names.get(ident) or f"thread-{ident}"
        tag = tags.get(ident)
        win.add(_fold(frame, name), tag[0] if tag is not None else "")
        folded += 1
    win.samples += 1
    return folded


class StackSampler:
    """The daemon sampler: a background thread folding all-thread
    stacks into the current :class:`ProfileWindow` at :attr:`hz`,
    rotating windows into a bounded ring.  ``start()``/``stop()`` are
    idempotent; the rate is live (``set_rate`` applies next tick)."""

    def __init__(self, hz: Optional[float] = None,
                 window_secs: Optional[float] = None,
                 windows: Optional[int] = None):
        self.hz = float(get_env(SAMPLE_HZ_ENV) if hz is None else hz)
        self.window_secs = float(get_env(WINDOW_SECS_ENV)
                                 if window_secs is None else window_secs)
        cap = int(get_env(WINDOWS_ENV) if windows is None else windows)
        self._windows: Deque[ProfileWindow] = deque(maxlen=max(1, cap))
        self._cur: Optional[ProfileWindow] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = registry()
        self._c_samples = reg.counter(
            "profiler.samples",
            help="sampling passes taken by the stack sampler")
        self._c_rotations = reg.counter(
            "profiler.windows_rotated",
            help="profile windows rotated into the bounded ring")

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def set_rate(self, hz: float) -> None:
        with self._lock:
            self.hz = float(hz)

    def start(self) -> bool:
        """Start the daemon (no-op if already running).  Enables
        thread-span tracking for the daemon's lifetime so samples carry
        trace tags."""
        from . import tracing as _tracing
        with self._lock:
            if self.running or self.hz <= 0:
                return False
            self._stop.clear()
            self._cur = ProfileWindow(self.hz)
            _tracing.enable_thread_span_tracking()
            t = threading.Thread(target=self._run, name="mxtpu-sampler",
                                 daemon=True)
            self._thread = t
        t.start()
        return True

    def stop(self, timeout: float = 2.0) -> None:
        from . import tracing as _tracing
        with self._lock:
            t, self._thread = self._thread, None
            if t is None:
                return
            self._stop.set()
        t.join(timeout)
        _tracing.disable_thread_span_tracking()
        with self._lock:
            cur, self._cur = self._cur, None
            if cur is not None and cur.samples:
                cur.close()
                self._windows.append(cur)

    def _run(self) -> None:
        me = threading.get_ident()
        next_t = perf_counter()
        while True:
            period = 1.0 / max(self.hz, 1e-3)
            next_t += period
            if self._stop.wait(max(0.0, next_t - perf_counter())):
                return
            with self._lock:
                win = self._cur
                if win is None:
                    continue
                _collect_into(win, me)
                self._c_samples.n += 1
                if win.age_s >= self.window_secs:
                    win.close()
                    self._windows.append(win)
                    self._cur = ProfileWindow(self.hz)
                    self._c_rotations.n += 1

    # -- consumption ---------------------------------------------------------
    def windows(self, include_current: bool = True
                ) -> List[ProfileWindow]:
        """Rotated windows oldest-first, plus the in-progress one."""
        with self._lock:
            out = list(self._windows)
            if include_current and self._cur is not None \
                    and self._cur.samples:
                out.append(self._cur)
        return out

    def last_window(self) -> Optional[ProfileWindow]:
        """The most recent window with samples (the postmortem's
        'what was hot just now' attachment)."""
        wins = self.windows()
        return wins[-1] if wins else None

    def collapsed(self) -> str:
        return collapsed_from_windows(self.windows())

    def clear(self) -> None:
        with self._lock:
            self._windows.clear()
            if self._cur is not None:
                self._cur = ProfileWindow(self.hz)


def profile(seconds: float = 1.0, hz: float = 100.0) -> ProfileWindow:
    """Sample synchronously for ``seconds`` on the CALLING thread (the
    ``/debug/profile?seconds=S`` handler) — independent of the daemon,
    skips the caller's own stack, returns the closed window."""
    win = ProfileWindow(hz)
    from . import tracing as _tracing
    _tracing.enable_thread_span_tracking()
    try:
        me = threading.get_ident()
        period = 1.0 / max(hz, 1e-3)
        end = perf_counter() + max(0.0, seconds)
        while True:
            _collect_into(win, me)
            if perf_counter() + period > end:
                break
            sleep(period)
    finally:
        _tracing.disable_thread_span_tracking()
    win.close()
    return win


# -- process singleton + env opt-in ------------------------------------------

_sampler_lock = threading.Lock()
_sampler_inst: Optional[StackSampler] = None


def sampler() -> StackSampler:
    """THE process-global sampler (the registry()/tracer() idiom)."""
    global _sampler_inst
    inst = _sampler_inst
    if inst is not None:
        return inst
    with _sampler_lock:
        if _sampler_inst is None:
            _sampler_inst = StackSampler()
        return _sampler_inst


# raw-env memo for maybe_start_from_env: module globals are only
# WRITTEN under _probe_lock; the fast-path read is GIL-plain
_probe_lock = threading.Lock()
_raw_hz_memo: object = object()
_hz_on = False


def maybe_start_from_env() -> bool:
    """Start (or stop) the daemon sampler to match
    ``MXTPU_PROF_SAMPLE_HZ``.  Callable from init sites at any
    frequency: while the raw environ entry is unchanged this is ONE
    dict hit (the tracing ``enabled`` idiom)."""
    global _raw_hz_memo, _hz_on
    raw = _raw_env(_HZ_KEY_B, SAMPLE_HZ_ENV)
    if raw == _raw_hz_memo:
        return _hz_on
    with _probe_lock:
        if raw == _raw_hz_memo:
            return _hz_on
        hz = float(get_env(SAMPLE_HZ_ENV) or 0.0)
        inst = sampler()
        if hz > 0:
            inst.set_rate(hz)
            inst.start()
        else:
            inst.stop()
        _raw_hz_memo = raw
        _hz_on = hz > 0
        return _hz_on
