"""Causal tracing: end-to-end request/step traces over the metrics spine.

:mod:`.trace` measures *how long* things take (spans feed histograms);
this module records *what caused what*.  A **trace** is a tree of spans
sharing one ``trace_id`` — a serving request and the batch it rode, a
training step and the loader wait that starved it, a fleet re-form and
every survivor's round — stitched across threads and HOSTS, so a p99
outlier resolves to the one concrete execution that produced it instead
of an anonymous histogram bucket.

Context model (W3C trace-context shaped):

- every span carries ``trace_id`` (32 hex) / ``span_id`` (16 hex) /
  ``parent_id``; the ACTIVE span propagates via a :mod:`contextvars`
  ContextVar, so nesting works across ``with`` scopes and executor
  context copies without any plumbing;
- cross-thread and cross-host edges carry the W3C ``traceparent``
  string (``00-<trace_id>-<span_id>-01``): :func:`traceparent` exports
  the active context, :func:`parse_traceparent` + :func:`activate`
  adopt a remote one — the serving request object, the membership
  re-form view keys, and the preemption vote payloads all ship it
  through the coordination-service KV tier;
- **deterministic ids**: lockstep fleet events (the supervised training
  step) derive their trace_id from fleet-uniform state
  (:func:`deterministic_trace_id` over ``(fence, step)``), so every
  host's step-N spans share one trace with ZERO cross-host traffic —
  the causal key is the lockstep itself.

Sampling and cost discipline:

- everything is knob-gated (``MXTPU_TRACE``, default off) and the OFF
  path is engineered to be free on hot roots: :meth:`Tracer.enabled` is
  memoized against the raw environ entry (the ``Engine.bulk_enabled``
  idiom — one dict hit per probe), instrumented call sites guard on an
  already-``None`` per-object context before touching the tracer, and
  span begin/finish never formats, logs, or allocates numpy;
- **head sampling** (``MXTPU_TRACE_SAMPLE`` = N): a new ROOT trace is
  started for 1 in N sampling decisions; children of a sampled trace
  are always recorded (the trace stays whole).  Deterministic roots
  sample on their own fleet-uniform counter (``sampled_index``) so
  every host keeps or drops the same fleet step;
- completed spans land in a bounded ring (``MXTPU_TRACE_RING``) and,
  when ``MXTPU_TRACE_JSONL`` is set, in a size-rotated JSONL file
  (buffered — one write per ~64 spans, flushed at exit), the unit a
  cross-host postmortem merges.

Export: :meth:`Tracer.chrome_events` renders the ring as chrome-trace
events with **flow arrows** (``ph: s/f``) from parent to child and from
link sources (a batch span links every member request) — cross-host
traces merge on ``pid = host`` lanes; the :mod:`profiler` merges these
into its unified timeline, and :func:`chrome_trace_from_spans` builds a
standalone timeline from merged multi-host JSONL/ring dumps.

Exemplars: while tracing is enabled, every
:meth:`~mxnet_tpu.observability.registry.Histogram.observe` records the
active ``trace_id`` into the observed bucket (last-K, OpenMetrics
exemplar syntax on the Prometheus endpoint) — the p99 bucket of
``serving.request_us`` or ``resilience.step_wall_us`` then POINTS AT
real traces in this ring.
"""
from __future__ import annotations

import contextvars
import hashlib
import json
import os
import random
import threading
from collections import deque
from time import perf_counter, time as _wall
from typing import Dict, List, Optional, Tuple

from ..base import get_env
from .registry import host_id, registry, set_exemplar_trace_hook

__all__ = ["Span", "RemoteContext", "Tracer", "tracer", "current",
           "traceparent", "parse_traceparent", "activate", "now",
           "deterministic_trace_id", "gen_trace_id", "record_child",
           "chrome_trace_from_spans", "chrome_events_from_spans",
           "thread_spans", "enable_thread_span_tracking",
           "disable_thread_span_tracking",
           "TRACE_ENV", "TRACE_SAMPLE_ENV", "TRACE_RING_ENV",
           "TRACE_JSONL_ENV"]

TRACE_ENV = "MXTPU_TRACE"
TRACE_SAMPLE_ENV = "MXTPU_TRACE_SAMPLE"
TRACE_RING_ENV = "MXTPU_TRACE_RING"
TRACE_JSONL_ENV = "MXTPU_TRACE_JSONL"

#: exemplar depth per histogram bucket (the "last-K")
EXEMPLAR_K = 4

# os.environ's decoded-bytes dict (posix): the enabled probe runs on
# serving dispatch roots, where os.environ.get's key encode is real
# money — same memoization engine.py uses for the bulk knobs
_ENV_DATA = getattr(os.environ, "_data", None) if os.name == "posix" \
    else None
if not isinstance(_ENV_DATA, dict):
    _ENV_DATA = None

_TRACE_KEY_B = TRACE_ENV.encode()
_TRACE_SAMPLE_KEY_B = TRACE_SAMPLE_ENV.encode()


def _raw_env(key_bytes: bytes, key_str: str):
    """Raw environ entry for a DECLARED knob (the engine._raw_env
    idiom): the value is only ever compared against a memo — parsing
    goes through get_env when the raw entry actually changed."""
    if _ENV_DATA is not None:
        return _ENV_DATA.get(key_bytes)
    return os.environ.get(key_str)

# the ACTIVE span for the current logical context.  contextvars, not a
# thread-local stack: executor-copied contexts and explicit activate()
# scopes compose, and a plain ContextVar.get() is the whole cost of the
# not-tracing probe.
_active: contextvars.ContextVar = contextvars.ContextVar(
    "mxtpu_trace_span", default=None)

# Cross-thread view of the active spans, for the stack sampler and the
# watchdog postmortem: a ContextVar is unreadable from another thread,
# so while introspection is enabled (refcounted — the sampler daemon,
# the watchdog, an on-demand /debug/profile window) every activation
# site mirrors the span into this ident-keyed dict.  OFF is the normal
# state and costs one module-global bool read per span activation; the
# dict itself needs no lock — each thread writes only its own ident
# (GIL-atomic dict ops) and readers only snapshot via dict copy.
_track_spans = False
_track_refs = 0
_track_lock = threading.Lock()
_thread_spans: Dict[int, object] = {}


def enable_thread_span_tracking() -> None:
    """Start mirroring span activations into the cross-thread map
    (refcounted: pairs with :func:`disable_thread_span_tracking`)."""
    global _track_spans, _track_refs
    with _track_lock:
        _track_refs += 1
        _track_spans = True


def disable_thread_span_tracking() -> None:
    """Drop one tracking ref; the map stops updating (and is cleared)
    when the last consumer detaches."""
    global _track_spans, _track_refs
    off = False
    with _track_lock:
        _track_refs = max(0, _track_refs - 1)
        if _track_refs == 0:
            _track_spans = False
            off = True
    if off:
        _thread_spans.clear()


def thread_spans() -> Dict[int, object]:
    """Snapshot of thread ident → active Span/RemoteContext.  Empty
    unless tracking is enabled — callers treat a missing ident as "no
    active span"."""
    return dict(_thread_spans)


def _set_active(obj):
    """Install ``obj`` as the active context AND mirror it into the
    cross-thread map when tracking is on.  Returns the reset token."""
    token = _active.set(obj)
    if _track_spans:
        _thread_spans[threading.get_ident()] = obj
    return token


def _reset_active(token) -> None:
    """Undo a :func:`_set_active` (ValueError = crossed a context
    boundary: clearing beats leaking the span into unrelated work)."""
    try:
        _active.reset(token)
    except ValueError:
        _active.set(None)
    if _track_spans:
        cur = _active.get()
        ident = threading.get_ident()
        if cur is None:
            _thread_spans.pop(ident, None)
        else:
            _thread_spans[ident] = cur


_rng = random.Random()
_rng.seed(int.from_bytes(os.urandom(8), "big"))
_rng_lock = threading.Lock()


def _gen_id(bits: int) -> str:
    with _rng_lock:
        return format(_rng.getrandbits(bits), f"0{bits // 4}x")


def gen_trace_id() -> str:
    """A fresh random 32-hex trace id — for rare always-traced events
    (fleet re-forms) that bypass head sampling by passing an explicit
    id to :meth:`Tracer.begin`."""
    return _gen_id(128)


def deterministic_trace_id(*parts) -> str:
    """A 32-hex trace id derived purely from ``parts`` — the stitch key
    for fleet-lockstep events: every host computing
    ``deterministic_trace_id(fence, step)`` lands in the SAME trace with
    no cross-host handshake (the lockstep is the causality)."""
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return h.hexdigest()[:32]


class RemoteContext:
    """A parent context received from another host/thread (a parsed
    ``traceparent``): just the two ids, usable anywhere a local
    :class:`Span` is accepted as ``parent``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"RemoteContext({self.trace_id}, {self.span_id})"


class Span:
    """One recorded unit of work.  Usable three ways:

    - ``with tracer().begin("name") as sp:`` — activates for the body,
      records on exit;
    - explicit lifecycle: ``sp = begin(..., activate=False)`` ...
      ``sp.finish()`` — the serving request shape (begin on submit,
      finish on completion, possibly on another thread);
    - retroactive: ``begin(..., t0=..., activate=False)`` then
      ``finish(t_end=...)`` — attributing already-measured work (the
      loader wait that preceded a step) into the trace after the fact.

    ``link(ctx)`` records a non-parent causal edge (a batch span links
    every member request) — rendered as a chrome-trace flow arrow.
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0_pc",
                 "t0_wall", "duration_us", "args", "links", "_tracer",
                 "_token", "_done")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], t0_pc: Optional[float],
                 args: Optional[dict]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _gen_id(64)
        self.parent_id = parent_id
        pc = perf_counter()
        self.t0_pc = pc if t0_pc is None else float(t0_pc)
        # wall anchor derived from the SAME instant so pc and wall views
        # of one span can never disagree (cross-host merges use wall)
        self.t0_wall = _wall() - (pc - self.t0_pc)
        self.duration_us = 0.0
        self.args = args
        self.links: Optional[List[Tuple[str, str]]] = None
        self._tracer = tracer
        self._token = None
        self._done = False

    def link(self, ctx) -> None:
        """Record a causal (non-parent) edge from ``ctx`` to this span."""
        if ctx is None:
            return
        if self.links is None:
            self.links = []
        self.links.append((ctx.trace_id, ctx.span_id))

    def annotate(self, **kv) -> None:
        """Merge metadata into the span's args (postmortem context —
        never touches any histogram)."""
        if self.args is None:
            self.args = {}
        self.args.update(kv)

    def adopt(self, ctx) -> None:
        """Re-parent this (still-open) span under a remote context — the
        membership re-form uses it once the round's canonical
        traceparent is known (the lowest-rank view's), so every
        survivor's round lands in ONE trace no matter who opened it."""
        if ctx is None or self._done:
            return
        self.trace_id = ctx.trace_id
        self.parent_id = ctx.span_id

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    # -- context-manager / lifecycle ----------------------------------------
    def __enter__(self) -> "Span":
        # idempotent: begin(activate=True) already installed the
        # context — a second set here would orphan the first token and
        # leak the span past its own `with` block
        if self._token is None and not self._done:
            self._token = _set_active(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.annotate(error=exc_type.__name__)
        self.finish()

    def finish(self, t_end: Optional[float] = None) -> None:
        """Close and record the span (idempotent).  ``t_end`` is a
        ``tracing.now()`` timestamp for retroactive spans."""
        if self._done:
            return
        self._done = True
        end = perf_counter() if t_end is None else float(t_end)
        self.duration_us = max(0.0, (end - self.t0_pc) * 1e6)
        if self._token is not None:
            _reset_active(self._token)
            self._token = None
        self._tracer._record(self)


class Tracer:
    """Process tracer: sampling decisions + the bounded completed-span
    ring + the JSONL stream.  One process-global instance
    (:func:`tracer`); tests may build private ones."""

    def __init__(self, ring: Optional[int] = None,
                 jsonl: Optional[str] = None):
        # config memo fields are GIL-plain (never under the lock): the
        # enabled/sample probes run on hot roots and must stay dict-hit
        # cheap; ring/jsonl state below is lock-protected
        self._raw_on: object = object()
        self._on = False
        self._raw_sample: object = object()
        self._sample = 1
        self._root_seq = 0
        self._ring_cap = ring
        self._jsonl_path = jsonl
        self._jsonl_max = 16 * 1024 * 1024
        self._configured = False
        # one-time construction of the process tracer, reached from
        # serving dispatch roots only through the set-once tracer()
        # singleton — the engine/registry singleton-init precedent
        # mxlint: disable=hot-path-purity — one-time singleton init
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, ring or 2048))
        self._buf: List[str] = []
        self._lanes: Dict[int, int] = {}
        self._lane_names: Dict[int, str] = {}
        reg = registry()
        self._c_spans = reg.counter(
            "tracing.spans_recorded",
            help="completed spans recorded into the trace ring")
        self._c_sampled = reg.counter(
            "tracing.roots_sampled",
            help="new root traces started (head sampling kept them)")
        self._c_unsampled = reg.counter(
            "tracing.roots_unsampled",
            help="root candidates dropped by 1-in-N head sampling")

    # -- knobs ---------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        """Live, memoized ``MXTPU_TRACE``: re-parsed only when the raw
        environ entry changes (this property is the whole cost of the
        tracing-off path on instrumented hot roots)."""
        raw = _raw_env(_TRACE_KEY_B, TRACE_ENV)
        if raw != self._raw_on:
            self._raw_on = raw
            self._on = bool(get_env(TRACE_ENV))
            if self._on and not self._configured:
                self._configure()
        return self._on

    @property
    def sample_n(self) -> int:
        """Live, memoized ``MXTPU_TRACE_SAMPLE`` (1 = every root)."""
        raw = _raw_env(_TRACE_SAMPLE_KEY_B, TRACE_SAMPLE_ENV)
        if raw != self._raw_sample:
            self._raw_sample = raw
            self._sample = max(1, int(get_env(TRACE_SAMPLE_ENV)))
        return self._sample

    def _configure(self) -> None:
        """Resolve ring depth + JSONL path from the env (runs on the
        first off→on transition; constructor arguments pin them for
        test instances)."""
        self._configured = True
        with self._lock:
            if self._ring_cap is None:
                cap = max(1, int(get_env(TRACE_RING_ENV)))
                self._ring = deque(self._ring, maxlen=cap)
            if self._jsonl_path is None:
                path = str(get_env(TRACE_JSONL_ENV)).strip()
                self._jsonl_path = path or ""
            jsonl = self._jsonl_path
        if jsonl:
            import atexit
            atexit.register(self.flush_jsonl)

    def sampled_index(self, i: int) -> bool:
        """Deterministic head-sampling for fleet-lockstep roots: keep
        index ``i`` iff ``i % sample_n == 0`` — every host computes the
        same verdict for the same step, so sampled step traces are
        always whole across the fleet."""
        if not self.enabled:
            return False
        return int(i) % self.sample_n == 0

    # -- span creation -------------------------------------------------------
    def begin(self, name: str, *, parent=None, trace_id: Optional[str]
              = None, t0: Optional[float] = None, args: Optional[dict]
              = None, activate: bool = True) -> Optional[Span]:
        """Start a span, or return None (record nothing) when tracing is
        off or head sampling dropped a new root.

        - ``parent`` given (a Span or RemoteContext): a child — always
          recorded (sampling happened at the root).
        - no parent, active context present: child of it.
        - no parent, no context, ``trace_id`` given: a deterministic
          root — the CALLER made the sampling decision
          (:meth:`sampled_index`).
        - no parent, no context, no trace_id: a fresh root, subject to
          1-in-N head sampling.

        ``activate=False`` skips the contextvar install (explicit
        lifecycle: serving requests, retroactive children).
        """
        if not self.enabled:
            return None
        if parent is None:
            parent = _active.get()
        if parent is not None:
            sp = Span(self, name, parent.trace_id, parent.span_id, t0,
                      args)
        elif trace_id is not None:
            self._c_sampled.inc()
            sp = Span(self, name, trace_id, None, t0, args)
        else:
            # root sequence under the lock: concurrent submit threads
            # racing a bare += would drift the 1-in-N ratio (and inc(),
            # not a plain .n bump — many threads reach this)
            with self._lock:
                self._root_seq += 1
                seq = self._root_seq
            n = self.sample_n
            if n > 1 and seq % n:
                self._c_unsampled.inc()
                return None
            self._c_sampled.inc()
            sp = Span(self, name, _gen_id(128), None, t0, args)
        if activate:
            sp._token = _set_active(sp)
        return sp

    def record_child(self, name: str, t_end_pc: float, dur_us: float,
                     args: Optional[dict]) -> None:
        """Retroactively record an already-measured unit as a child of
        the ACTIVE span (the :class:`~mxnet_tpu.observability.trace.span`
        exit hook: every histogram span inside a traced region lands in
        the trace for free).  No active context → no-op."""
        parent = _active.get()
        if parent is None:
            return
        sp = Span(self, name, parent.trace_id, parent.span_id,
                  t_end_pc - dur_us / 1e6, args)
        sp._done = True
        sp.duration_us = dur_us
        self._record(sp)

    # -- recording -----------------------------------------------------------
    def _lane_locked(self, ident: int) -> int:
        lane = self._lanes.get(ident)
        if lane is None:
            lane = len(self._lanes)
            self._lanes[ident] = lane
            self._lane_names[lane] = threading.current_thread().name
        return lane

    def _record(self, sp: Span) -> None:
        rec = {
            "name": sp.name,
            "trace_id": sp.trace_id,
            "span_id": sp.span_id,
            "parent_id": sp.parent_id,
            "host": host_id(),
            "t0_pc": sp.t0_pc,
            "t0_wall": round(sp.t0_wall, 6),
            "dur_us": round(sp.duration_us, 1),
        }
        if sp.args:
            rec["args"] = sp.args
        if sp.links:
            rec["links"] = sp.links
        line = None
        with self._lock:
            rec["lane"] = self._lane_locked(threading.get_ident())
            self._ring.append(rec)
            if self._jsonl_path:
                self._buf.append(json.dumps(rec))
                if len(self._buf) >= 64:
                    line = "\n".join(self._buf) + "\n"
                    self._buf = []
        self._c_spans.inc()
        if line is not None:
            self._write_jsonl(line)

    def _write_jsonl(self, chunk: str) -> None:
        path = self._jsonl_path
        try:
            if os.path.exists(path) and \
                    os.path.getsize(path) + len(chunk) > self._jsonl_max:
                os.replace(path, path + ".1")   # one rotation generation
            with open(path, "a") as f:
                f.write(chunk)
        except OSError:
            pass   # tracing must never take down the traced job

    def flush_jsonl(self) -> None:
        """Write any buffered JSONL lines now (atexit / test sync)."""
        with self._lock:
            if not (self._jsonl_path and self._buf):
                return
            chunk = "\n".join(self._buf) + "\n"
            self._buf = []
        self._write_jsonl(chunk)

    # -- consumption ---------------------------------------------------------
    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def find(self, trace_id: str) -> List[dict]:
        """Every ring span belonging to ``trace_id`` (exemplar
        resolution: histogram bucket → trace_id → the actual spans)."""
        with self._lock:
            return [s for s in self._ring if s["trace_id"] == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._buf = []

    def lane_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._lane_names)

    def chrome_events(self, base_pc: Optional[float] = None,
                      tid_offset: int = 0) -> List[dict]:
        """The ring as chrome-trace events (see
        :func:`chrome_trace_from_spans`).  ``base_pc`` renders on the
        perf_counter clock relative to that origin (the profiler's
        unified timeline); default is the wall clock (standalone and
        cross-host merges)."""
        return chrome_events_from_spans(self.spans(), base_pc=base_pc,
                                        tid_offset=tid_offset)

    def dump_chrome_trace(self, path: str) -> str:
        """Write the ring as a standalone chrome-trace JSON file."""
        return chrome_trace_from_spans(self.spans(), path)


def chrome_events_from_spans(spans: List[dict],
                             base_pc: Optional[float] = None,
                             tid_offset: int = 0) -> List[dict]:
    """Chrome-trace events for a span list (possibly merged from many
    hosts' rings/JSONL dumps): one ``X`` duration event per span on
    ``pid = host`` / ``tid = recording-thread lane``, plus **flow
    events** — an arrow from each parent span to each child and from
    every link source (e.g. member requests) to the linking span.
    Cross-host edges just work: flow events bind by id, not pid."""

    def ts(s):
        if base_pc is not None:
            return (s["t0_pc"] - base_pc) * 1e6
        return s["t0_wall"] * 1e6

    by_span = {s["span_id"]: s for s in spans}
    events: List[dict] = []
    for s in spans:
        t0 = ts(s)
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"]}
        if s.get("args"):
            args.update(s["args"])
        tid = tid_offset + s.get("lane", 0)
        events.append({"name": s["name"], "ph": "X", "cat": "trace",
                       "pid": s.get("host", 0), "tid": tid, "ts": t0,
                       "dur": max(s["dur_us"], 0.1), "args": args})
        edges = []
        parent = by_span.get(s.get("parent_id") or "")
        if parent is not None:
            edges.append((parent, "causes"))
        for _lt, ls in s.get("links") or ():
            # links may cross TRACES (a batch span links member
            # requests living in their own traces) — presence of the
            # source span is the only requirement for the arrow
            src = by_span.get(ls)
            if src is not None:
                edges.append((src, "links"))
        for idx, (src, kind) in enumerate(edges):
            # one flow id per EDGE: chrome/perfetto bind s->f pairs by
            # (cat, id), so a span with a parent edge plus N link edges
            # sharing one id would merge into a garbled chain
            fid = (int(s["span_id"][:11] or "0", 16) << 4) | (idx & 15)
            src_tid = tid_offset + src.get("lane", 0)
            events.append({"name": kind, "ph": "s", "cat": "trace",
                           "id": fid, "pid": src.get("host", 0),
                           "tid": src_tid, "ts": ts(src)})
            events.append({"name": kind, "ph": "f", "bp": "e",
                           "cat": "trace", "id": fid,
                           "pid": s.get("host", 0), "tid": tid,
                           "ts": max(t0, ts(src))})
    return events


def chrome_trace_from_spans(spans: List[dict], path: str) -> str:
    """Write merged span records as a standalone chrome-trace file
    (``pid = host`` with process_name metadata) — the cross-host
    postmortem: concatenate the hosts' JSONL dumps, load one list, call
    this, open in ``chrome://tracing`` / Perfetto."""
    meta = [{"name": "process_name", "ph": "M", "pid": h,
             "args": {"name": f"host {h}"}}
            for h in sorted({s.get("host", 0) for s in spans})]
    payload = {"traceEvents": meta + chrome_events_from_spans(spans),
               "displayTimeUnit": "ms"}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


# -- module-level context surface --------------------------------------------

def current() -> Optional[Span]:
    """The active span in this context, or None."""
    return _active.get()


def now() -> float:
    """The tracing clock (``perf_counter`` seconds) — for callers that
    need span-comparable timestamps without tripping the timing-pair
    lint outside the observability layer."""
    return perf_counter()


def traceparent() -> Optional[str]:
    """W3C traceparent of the active context (``00-<trace>-<span>-01``),
    or None — what crosses the KV tier to another host."""
    sp = _active.get()
    return sp.traceparent if sp is not None else None


def parse_traceparent(header) -> Optional[RemoteContext]:
    """Parse a traceparent string into a :class:`RemoteContext`;
    malformed/empty input returns None (remote payloads are
    best-effort)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.split("-")
    if len(parts) < 3:
        return None
    tid, sid = (parts[1], parts[2]) if parts[0] == "00" \
        else (parts[0], parts[1])
    if len(tid) != 32 or len(sid) != 16:
        return None
    try:
        int(tid, 16), int(sid, 16)
    except ValueError:
        return None
    return RemoteContext(tid, sid)


class activate:
    """``with activate(ctx):`` — install a (remote) parent context for
    the body, so spans begun inside join its trace.  ``ctx=None`` is a
    transparent no-op (pairs with :func:`parse_traceparent`)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None:
            self._token = _set_active(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _reset_active(self._token)


def record_child(name: str, t_end_pc: float, dur_us: float,
                 args: Optional[dict] = None) -> None:
    """Module-level fast path for :meth:`Tracer.record_child`: bail on
    the (overwhelmingly common) no-active-context case before touching
    the singleton — one ContextVar.get when tracing is idle."""
    if _active.get() is None:
        return
    tracer().record_child(name, t_end_pc, dur_us, args)


_tracer_lock = threading.Lock()
_tracer_inst: Optional[Tracer] = None


def _active_trace_id() -> Optional[str]:
    """The histogram exemplar hook: trace_id of the active span (or
    None) — one ContextVar.get per observe while tracing is enabled."""
    sp = _active.get()
    return sp.trace_id if sp is not None else None


def tracer() -> Tracer:
    """THE process-global tracer (the registry()/engine() idiom).  The
    first call installs the histogram exemplar hook, so exemplars
    record exactly when traces exist to point at."""
    global _tracer_inst
    inst = _tracer_inst
    if inst is not None:
        return inst
    with _tracer_lock:
        if _tracer_inst is None:
            _tracer_inst = Tracer()
            set_exemplar_trace_hook(_active_trace_id)
        return _tracer_inst
