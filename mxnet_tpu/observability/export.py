"""Exporters: Prometheus text endpoint + JSONL periodic writer.

Two consumption shapes for the one registry:

- **Pull** (:class:`MetricsServer`): a stdlib ``http.server`` endpoint
  serving ``/metrics`` in the Prometheus text exposition format (and
  ``/metrics.json`` for humans/scripts).  Opt-in: nothing listens unless
  the server is started explicitly or ``MXTPU_METRICS_PORT`` is set —
  the formatting cost exists only per scrape.
- **Push-to-disk** (:class:`JsonlWriter`): one JSON object per line,
  appended every ``interval`` seconds (or on explicit ``write_now()``),
  with size-based rotation — the headless-run story where nothing can
  scrape (batch jobs writing into a log pipeline).  Env:
  ``MXTPU_METRICS_JSONL=<path>`` (+ ``MXTPU_METRICS_INTERVAL`` seconds,
  default 60).

``maybe_start_from_env()`` wires both from the environment; the package
``__init__`` calls it once at import, so setting the env vars is the
whole deployment step.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..base import MXNetError, get_env, list_env
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       group_host_entries, last_host_states, registry,
                       state_cumulative_buckets)

__all__ = ["prometheus_text", "prometheus_text_aggregate",
           "aggregate_mode", "MetricsServer", "JsonlWriter",
           "maybe_start_from_env", "debug_route", "debug_enabled",
           "DEBUG_ENDPOINTS_ENV"]

METRICS_PORT_ENV = "MXTPU_METRICS_PORT"
DEBUG_ENDPOINTS_ENV = "MXTPU_DEBUG_ENDPOINTS"
METRICS_JSONL_ENV = "MXTPU_METRICS_JSONL"
METRICS_INTERVAL_ENV = "MXTPU_METRICS_INTERVAL"
#: serve the FLEET view (merged multi-host states, every series labeled
#: host="<process_index>") instead of the local registry.  Read live per
#: scrape; point Prometheus at host 0, whose gathered view covers the
#: whole fleet.
METRICS_AGGREGATE_ENV = "MXTPU_METRICS_AGGREGATE"

#: every exported sample is prefixed so dashboards can scope on it
PROM_PREFIX = "mxtpu_"

_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")

#: per-model serving metrics (``serving.model.<name>.<metric>`` — see
#: :data:`mxnet_tpu.serving.registry.MODEL_METRIC_PREFIX`) re-render as
#: ONE Prometheus family per <metric> with a real ``model="<name>"``
#: label: ``mxtpu_serving_model_<metric>{model="<name>"}``.  The family
#: name keeps the ``model`` component so it can never collide with the
#: servers' own unlabeled ``mxtpu_serving_*`` spine (a family may only
#: carry one TYPE header per exposition).
_MODEL_METRIC_RE = re.compile(r"^serving\.model\.([a-z0-9_]+)\.(.+)$")
_MODEL_HELP_PREFIX_RE = re.compile(r"^model [^:]*: ")


def _prom_name(name: str) -> str:
    return PROM_PREFIX + _SANITIZE_RE.sub("_", name)


def _fmt(v: float) -> str:
    """Prometheus sample values: integers render bare, floats with
    repr-precision, +Inf spelled the Prometheus way."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _help_line(pname: str, help_text: str) -> Optional[str]:
    if not help_text:
        return None
    escaped = help_text.replace("\\", "\\\\").replace("\n", "\\n")
    return f"# HELP {pname} {escaped}"


def prometheus_text(reg: Optional[MetricsRegistry] = None,
                    exemplars: bool = False) -> str:
    """The registry in Prometheus text exposition format (version 0.0.4):
    counters/gauges as single samples, histograms as cumulative
    ``_bucket{le=...}`` series plus ``_sum``/``_count``; ``# HELP``
    lines for metrics registered with a description.

    ``exemplars=True`` appends OpenMetrics exemplar suffixes to bucket
    lines — only legal in OpenMetrics-shaped output (the endpoint's
    explicit ``/metrics?exemplars=1`` opt-in, which it serves under the
    ``application/openmetrics-text`` content type with the ``# EOF``
    terminator); the classic 0.0.4 parser rejects a whole scrape
    containing them, so the default text format never carries any."""
    reg = reg if reg is not None else registry()
    lines = []
    # two passes: registry names sort with the model component BEFORE
    # the metric (serving.model.a.request_us, serving.model.a.requests,
    # serving.model.b.request_us, ...) but Prometheus requires all
    # samples of one family contiguous under a single TYPE header — so
    # per-model metrics are collected into families here and emitted
    # after the plain spine.
    families = {}                  # metric -> [(model, m)]
    for name in reg.names():
        m = reg.get(name)
        if m is None:                     # raced an (hypothetical) removal
            continue
        mm = _MODEL_METRIC_RE.match(name)
        if mm:
            families.setdefault(mm.group(2), []).append(
                (mm.group(1), m))
            continue
        pname = _prom_name(name)
        hl = _help_line(pname, m.help)
        if hl:
            lines.append(hl)
        if isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_fmt(m.n)}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(m.value)}")
        elif isinstance(m, Histogram):
            lines.append(f"# TYPE {pname} histogram")
            # OpenMetrics exemplars (negotiated scrapes only): a bucket
            # line carries the newest trace_id observed into it —
            # `# {trace_id="..."} <value> <ts>` — so the p99 bucket in
            # a dashboard resolves to a real trace in the span ring.
            # Empty when tracing is off.
            ex = m.exemplars() if exemplars else {}
            for bound, cum in m.cumulative_buckets():
                line = f'{pname}_bucket{{le="{_fmt(bound)}"}} {cum}'
                e = ex.get(bound)
                if e:
                    tid, val, ts = e[-1]
                    line += (f' # {{trace_id="{tid}"}} {_fmt(val)} '
                             f'{ts}')
                lines.append(line)
            lines.append(f"{pname}_sum {_fmt(m.total)}")
            lines.append(f"{pname}_count {m.count}")
    for metric in sorted(families):
        entries = families[metric]
        pname = _prom_name(f"serving.model.{metric}")
        # the per-entry help embeds the model name; the family header
        # is model-agnostic, so strip the "model <name>: " prefix
        help_text = next((_MODEL_HELP_PREFIX_RE.sub("", m.help)
                          for _, m in entries if m.help), "")
        hl = _help_line(pname, help_text)
        if hl:
            lines.append(hl)
        kind = entries[0][1]
        if isinstance(kind, Counter):
            lines.append(f"# TYPE {pname} counter")
            for model, m in entries:
                lines.append(f'{pname}{{model="{model}"}} {_fmt(m.n)}')
        elif isinstance(kind, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            for model, m in entries:
                lines.append(
                    f'{pname}{{model="{model}"}} {_fmt(m.value)}')
        elif isinstance(kind, Histogram):
            lines.append(f"# TYPE {pname} histogram")
            for model, m in entries:
                ex = m.exemplars() if exemplars else {}
                for bound, cum in m.cumulative_buckets():
                    line = (f'{pname}_bucket{{model="{model}",'
                            f'le="{_fmt(bound)}"}} {cum}')
                    e = ex.get(bound)
                    if e:
                        tid, val, ts = e[-1]
                        line += (f' # {{trace_id="{tid}"}} '
                                 f'{_fmt(val)} {ts}')
                    lines.append(line)
                lines.append(
                    f'{pname}_sum{{model="{model}"}} {_fmt(m.total)}')
                lines.append(
                    f'{pname}_count{{model="{model}"}} {m.count}')
    return "\n".join(lines) + "\n"


def aggregate_mode() -> bool:
    """Live read of the ``MXTPU_METRICS_AGGREGATE`` opt-in."""
    return bool(get_env(METRICS_AGGREGATE_ENV))


def prometheus_text_aggregate(
        reg: Optional[MetricsRegistry] = None) -> str:
    """The FLEET view in Prometheus text format: every series from the
    most recently gathered per-host states (``snapshot(all_hosts=True)``
    refreshes them — a collective, so it runs at fleet sync points like
    checkpoint boundaries, never from the scrape handler), each labeled
    ``host="<process_index>"``.  Cross-host aggregation (sums, merged
    quantiles) is PromQL's job — ``sum by (le)`` etc.  Before the first
    gather (or single-process) this serves the local host's series under
    its own host label."""
    lines = []
    for name, kind, entries in group_host_entries(last_host_states(reg)):
        pname = _prom_name(name)
        help_text = next((e["help"] for _, e in entries
                          if e.get("help")), "")
        hl = _help_line(pname, help_text)
        if hl:
            lines.append(hl)
        lines.append(f"# TYPE {pname} {kind}")
        for h, e in entries:
            if kind == "counter":
                lines.append(f'{pname}{{host="{h}"}} {_fmt(e["n"])}')
            elif kind == "gauge":
                lines.append(f'{pname}{{host="{h}"}} {_fmt(e["v"])}')
            elif kind == "histogram":
                for bound, cum in state_cumulative_buckets(e):
                    lines.append(
                        f'{pname}_bucket{{host="{h}",'
                        f'le="{_fmt(bound)}"}} {cum}')
                lines.append(
                    f'{pname}_sum{{host="{h}"}} {_fmt(e["total"])}')
                lines.append(
                    f'{pname}_count{{host="{h}"}} {e["count"]}')
    return "\n".join(lines) + "\n"


def debug_enabled() -> bool:
    """Live read of the ``MXTPU_DEBUG_ENDPOINTS`` opt-in."""
    return bool(get_env(DEBUG_ENDPOINTS_ENV))


#: /debug/profile sampling bounds: a handler thread blocks for the
#: whole window, so the knob-free query param is clamped hard
PROFILE_MAX_SECONDS = 30.0
PROFILE_MIN_SECONDS = 0.05
PROFILE_DEFAULT_HZ = 100.0

_DEBUG_INDEX = """\
live introspection endpoints (MXTPU_DEBUG_ENDPOINTS=1):
  GET /debug/stacks               all-thread stacks, trace-tagged JSON
  GET /debug/profile?seconds=S    on-demand sample window (S<=30;
      &hz=H&format=collapsed|chrome|json; &windows=1 serves the
      daemon sampler's rotated windows instead of sampling now)
  GET /debug/flight               live flight-recorder rings
  GET /debug/trace/<trace_id>     span-ring lookup for one trace
  GET /debug/vars                 every registered knob's live value
"""


def _query_params(query: str) -> dict:
    params = {}
    for part in query.split("&"):
        if "=" in part:
            k, _, v = part.partition("=")
            params[k] = v
    return params


def _json_body(obj) -> Tuple[str, bytes]:
    return ("application/json",
            json.dumps(obj, sort_keys=True, indent=1).encode())


def debug_route(path: str, query: str = ""
                ) -> Optional[Tuple[int, str, bytes]]:
    """The shared ``/debug/*`` dispatcher — one implementation serving
    both the serving :class:`~mxnet_tpu.serving.frontend.HttpFrontend`
    and this module's stdlib metrics endpoint (so trainers without a
    frontend get the same surface).  Returns ``(status, content_type,
    body)`` for debug paths, None for everything else (the caller falls
    through to its own routing).  Knob-gated: with
    ``MXTPU_DEBUG_ENDPOINTS`` unset every debug path 404s with an
    explanation — the surface is auth-free and must be an explicit
    opt-in."""
    if path != "/debug" and not path.startswith("/debug/"):
        return None
    if not debug_enabled():
        return (404, "text/plain; charset=utf-8",
                f"debug endpoints disabled (set {DEBUG_ENDPOINTS_ENV}=1"
                f" to enable)\n".encode())
    try:
        return _debug_route(path, _query_params(query))
    except Exception as e:   # noqa: BLE001 — introspection of a
        # possibly-wedged process: report the failure, never 500-loop
        # the whole handler away
        return (500, "text/plain; charset=utf-8",
                f"debug handler error: {type(e).__name__}: {e}\n"
                .encode())


def _debug_route(path: str, params: dict
                 ) -> Tuple[int, str, bytes]:
    from . import flight as _flight
    from . import sampler as _sampler
    from . import tracing as _tracing
    if path in ("/debug", "/debug/"):
        return (200, "text/plain; charset=utf-8",
                _DEBUG_INDEX.encode())
    if path == "/debug/stacks":
        ctype, body = _json_body({"ts": round(time.time(), 3),
                                  "pid": os.getpid(),
                                  "threads": _sampler.thread_stacks()})
        return (200, ctype, body)
    if path == "/debug/profile":
        fmt = params.get("format", "collapsed")
        if params.get("windows"):
            wins = _sampler.sampler().windows()
            if fmt == "json":
                ctype, body = _json_body(
                    {"windows": [w.to_dict() for w in wins]})
                return (200, ctype, body)
            text = _sampler.collapsed_from_windows(wins)
            return (200, "text/plain; charset=utf-8",
                    (text + "\n").encode())
        try:
            seconds = float(params.get("seconds", 1.0))
        except ValueError:
            seconds = 1.0
        seconds = min(max(seconds, PROFILE_MIN_SECONDS),
                      PROFILE_MAX_SECONDS)
        try:
            hz = float(params.get("hz", PROFILE_DEFAULT_HZ))
        except ValueError:
            hz = PROFILE_DEFAULT_HZ
        hz = min(max(hz, 1.0), 1000.0)
        win = _sampler.profile(seconds=seconds, hz=hz)
        if fmt == "chrome":
            ctype, body = _json_body(
                {"traceEvents":
                 _sampler.chrome_events_from_window(win),
                 "displayTimeUnit": "ms"})
            return (200, ctype, body)
        if fmt == "json":
            ctype, body = _json_body(win.to_dict())
            return (200, ctype, body)
        return (200, "text/plain; charset=utf-8",
                (win.collapsed() + "\n").encode())
    if path == "/debug/flight":
        ctype, body = _json_body(_flight.recorder().live())
        return (200, ctype, body)
    if path.startswith("/debug/trace/"):
        trace_id = path[len("/debug/trace/"):].strip("/")
        spans = _tracing.tracer().find(trace_id) if trace_id else []
        status = 200 if spans else 404
        ctype, body = _json_body({"trace_id": trace_id,
                                  "n_spans": len(spans),
                                  "spans": spans})
        return (status, ctype, body)
    if path == "/debug/vars":
        ctype, body = _json_body(list_env())
        return (200, ctype, body)
    return (404, "text/plain; charset=utf-8",
            b"unknown debug endpoint; GET /debug for the index\n")


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxtpu-metrics"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path, _, query = self.path.partition("?")
        status = 200
        dbg = debug_route(path, query)
        if dbg is not None:
            status, ctype, body = dbg
        elif path == "/metrics":
            # exemplar suffixes are legal only in OpenMetrics-shaped
            # output — a 0.0.4 scraper receiving them rejects the
            # ENTIRE scrape — so they are an explicit opt-in
            # (`/metrics?exemplars=1`), never the default exposition
            exemplars = "exemplars=1" in query.split("&")
            if aggregate_mode():
                text = prometheus_text_aggregate()
                exemplars = False   # the fleet view carries none
            else:
                text = prometheus_text(exemplars=exemplars)
            if exemplars:
                # exemplar suffixes are OpenMetrics syntax: label the
                # body so a parser that routes on Content-Type picks
                # the right grammar (EOF terminator required)
                text += "# EOF\n"
                ctype = ("application/openmetrics-text; "
                         "version=1.0.0; charset=utf-8")
            else:
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            body = text.encode()
        elif path == "/metrics.json":
            body = json.dumps(registry().snapshot(), sort_keys=True,
                              indent=1).encode()
            ctype = "application/json"
        else:
            self.send_error(404, "try /metrics, /metrics.json, /debug")
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):   # no stderr chatter per scrape
        pass


class MetricsServer:
    """Serve the registry over HTTP on a daemon thread.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``server.port``.  ``stop()`` shuts the listener down; the server also
    dies with the process (daemon thread) — scrape targets need no
    shutdown ceremony.
    """

    def __init__(self, port: int, addr: str = "0.0.0.0",
                 start: bool = True):
        self._httpd = ThreadingHTTPServer((addr, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name="mxtpu-metrics-server")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._thread = None


class JsonlWriter:
    """Append registry snapshots as JSON lines, with size-based rotation.

    Each line is ``{"ts": <unix seconds>, "metrics": {...snapshot...}}``.
    When the file would exceed ``max_bytes`` the current file rotates to
    ``<path>.1`` (one generation — the consumer is a log shipper, not an
    archive).  ``start()`` spawns a daemon thread writing every
    ``interval`` seconds; ``write_now()`` is the synchronous path (tests,
    end-of-run flushes).
    """

    def __init__(self, path: str, interval: float = 60.0,
                 max_bytes: int = 16 * 1024 * 1024):
        if not path:
            raise MXNetError("JsonlWriter needs a path")
        self.path = path
        self.interval = float(interval)
        self.max_bytes = int(max_bytes)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def write_now(self) -> None:
        line = json.dumps({"ts": round(time.time(), 3),
                           "metrics": registry().snapshot()},
                          sort_keys=True) + "\n"
        with self._lock:
            try:
                if os.path.exists(self.path) and \
                        os.path.getsize(self.path) + len(line) > \
                        self.max_bytes:
                    os.replace(self.path, self.path + ".1")
            except OSError:
                pass                      # rotation is best-effort
            with open(self.path, "a") as f:
                f.write(line)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.write_now()
                except OSError:
                    # disk-full/unlinked-dir must not kill the writer —
                    # the next tick retries; training never depends on it
                    pass

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mxtpu-metrics-jsonl")
        self._thread.start()

    def stop(self, final_write: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_write:
            try:
                self.write_now()
            except OSError:
                pass


_env_server: Optional[MetricsServer] = None
_env_writer: Optional[JsonlWriter] = None
_env_lock = threading.Lock()


def maybe_start_from_env() -> None:
    """Start the HTTP endpoint and/or the JSONL writer if the opt-in env
    vars are set.  Idempotent; failures (port in use, unwritable path)
    warn instead of raising — observability must never take down the
    training job it observes."""
    global _env_server, _env_writer
    with _env_lock:
        port = get_env(METRICS_PORT_ENV).strip()
        jsonl = get_env(METRICS_JSONL_ENV).strip()
        if port or jsonl:
            # materialize the engine singleton so its metric families
            # exist from the first scrape/write, not from the first op
            from ..engine import engine
            engine()
        if port and _env_server is None:
            try:
                _env_server = MetricsServer(int(port))
            except (OSError, ValueError) as e:
                import warnings
                warnings.warn(
                    f"{METRICS_PORT_ENV}={port!r}: metrics endpoint not "
                    f"started ({e})", RuntimeWarning, stacklevel=2)
        if jsonl and _env_writer is None:
            try:
                interval = float(get_env(METRICS_INTERVAL_ENV))
                _env_writer = JsonlWriter(jsonl, interval=interval)
                _env_writer.start()
            except (OSError, ValueError) as e:
                import warnings
                warnings.warn(
                    f"{METRICS_JSONL_ENV}={jsonl!r}: JSONL metrics "
                    f"writer not started ({e})", RuntimeWarning,
                    stacklevel=2)
