"""Crash flight recorder: the last N step records + a full snapshot,
dumped to JSON when a run dies.

When a training job crashes, the metrics die with it: the Prometheus
endpoint goes away, the JSONL writer's last tick may be a minute stale,
and the per-step trajectory (was the loss already NaN? was the loader
starving? had the loss scale collapsed?) is gone.  The flight recorder
keeps a bounded ring of per-step records — step wall-time, loss, loss
scale, engine flush p99, skip/rollback counts, loader prefetch depth —
and writes the ring plus a complete ``registry().snapshot()`` to one
JSON file at death, turning postmortems from "rerun and hope" into
"read the dump".  Dump triggers:

- **unhandled exception** — ``install()`` chains ``sys.excepthook``;
- **preemption** (SIGTERM/SIGINT) and **retry exhaustion** —
  :class:`~mxnet_tpu.parallel.resilience.ResilientTrainer` feeds the
  ring every supervised step and dumps from its existing
  checkpoint-and-flush and step-failure paths;
- **explicitly** — ``recorder().dump("why")`` from any shutdown path.

Serving (PR-4 follow-up): the recorder additionally keeps a
**per-request ring** — ``record_request()`` appends one record per
served request (``request_id``, enqueue/assemble/dispatch/done
timestamps, shape bucket, batch size), fed by
:class:`~mxnet_tpu.serving.ModelServer` at completion time.  A crash
dump carries both rings side by side (``steps`` + ``requests``), so a
dying server explains its last ~256 requests the same way a dying
trainer explains its last steps.

Self-tuning (PR-8): a third ring holds the last-N **controller
decisions** — ``record_tuning()`` appends one record per
:mod:`mxnet_tpu.tuning` controller decision (controller, from → to,
applied/held/dry-run, the reason string).  A crash dump carries it as
``tuning`` next to ``steps``/``requests``, so a bad controller decision
— the knob flap that preceded the OOM — is visible in the post-mortem
ring, not just in a Prometheus history that died with the scrape
endpoint.

Elastic fleet (PR-9): a fourth ring holds **membership events** —
``record_membership()`` appends one record per lease-expiry suspicion,
fencing discovery, and committed re-form (with the detect → quiesce →
reform → resume timeline), fed by
:mod:`mxnet_tpu.parallel.membership` and the ``ResilientTrainer``
re-form arc.  A crash dump carries it as ``membership`` next to
``steps``/``requests``/``tuning``, so a post-mortem shows *when* the
fleet shrank and what the survivors did about it.

Cost discipline: ``record()`` is a dict build and a deque append — no
formatting, no I/O, no device sync.  Device-backed values (the step
loss) are stored as live references and materialized only at dump time,
best-effort (a crashed runtime that refuses ``device_get`` degrades that
field to ``None``, never blocks the dump).

Env knobs: ``MXTPU_FLIGHT_STEPS`` — ring capacity (default 256; 0
disables recording and dumping entirely); ``MXTPU_FLIGHT_PATH`` — dump
file (default ``<tmpdir>/mxtpu_flight_<pid>.json``; multi-host runs
should point each host at a distinct path or rely on the default's pid
suffix — the dump also carries its ``host`` index).
"""
from __future__ import annotations

import collections
import json
import os
import sys
import tempfile
import threading
import time
from typing import Deque, List, Optional

from ..base import get_env
from .registry import host_id, registry

__all__ = ["FlightRecorder", "recorder", "write_json_atomic",
           "FLIGHT_STEPS_ENV", "FLIGHT_PATH_ENV"]

FLIGHT_STEPS_ENV = "MXTPU_FLIGHT_STEPS"
FLIGHT_PATH_ENV = "MXTPU_FLIGHT_PATH"


def _env_capacity() -> int:
    # the registered default (256) covers unset AND unparsable values
    return max(0, int(get_env(FLIGHT_STEPS_ENV)))


def _materialize(v):
    """Best-effort JSON-friendly conversion at dump time.  Device values
    (NDArray / jax scalars) sync HERE, not at record time; a runtime too
    broken to read them yields None instead of blocking the dump."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, (list, tuple)):
        # membership records carry member lists and (phase, ts)
        # timelines — recurse instead of degrading them to None
        return [_materialize(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _materialize(x) for k, x in v.items()}
    try:
        if hasattr(v, "asnumpy"):
            # crash-dump materialization: the process is dying and
            # the ring must land on disk (see docstring)
            # mxlint: disable=hidden-host-sync — crash-dump path
            return float(v.asnumpy())
        return float(v)
    except Exception:   # noqa: BLE001 — a crashed backend must not
        return None     # take the dump down with it


def write_json_atomic(payload: dict, path: str) -> Optional[str]:
    """Atomic JSON write (tmp-then-rename), never raises: the shared
    dump primitive for crash dumps, watchdog postmortems, and signal
    stack dumps — all of which run on processes in trouble.  Returns
    the path, or None when the write failed."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path
    except (OSError, TypeError, ValueError):
        return None


class FlightRecorder:
    """Bounded ring of per-step records with crash-time JSON dump.

    ``capacity=None`` / ``path=None`` defer to the env knobs (capacity
    is resolved at construction, the path at each dump — so a test can
    redirect dumps without rebuilding the recorder).
    """

    def __init__(self, capacity: Optional[int] = None,
                 path: Optional[str] = None):
        self.capacity = _env_capacity() if capacity is None \
            else max(0, int(capacity))
        self.path = path
        self._ring: Deque[dict] = collections.deque(
            maxlen=max(1, self.capacity))
        self._req_ring: Deque[dict] = collections.deque(
            maxlen=max(1, self.capacity))
        self._tune_ring: Deque[dict] = collections.deque(
            maxlen=max(1, self.capacity))
        self._member_ring: Deque[dict] = collections.deque(
            maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._installed = False
        self._prev_hook = None

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, **fields) -> None:
        """Append one step record.  Cheap: no I/O, no sync — device
        values may be passed as-is and are materialized at dump time."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(fields)

    def record_request(self, **fields) -> None:
        """Append one served-request record to the request ring (same
        cost discipline as :meth:`record` — a dict build and a deque
        append, no I/O, no sync)."""
        if not self.enabled:
            return
        with self._lock:
            self._req_ring.append(fields)

    def record_tuning(self, **fields) -> None:
        """Append one controller-decision record to the tuning ring
        (same cost discipline: dict build + deque append)."""
        if not self.enabled:
            return
        with self._lock:
            self._tune_ring.append(fields)

    def record_membership(self, **fields) -> None:
        """Append one fleet-membership event (lease suspicion, fencing,
        committed re-form with its timeline) to the membership ring
        (same cost discipline: dict build + deque append)."""
        if not self.enabled:
            return
        with self._lock:
            self._member_ring.append(fields)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def requests(self) -> List[dict]:
        with self._lock:
            return list(self._req_ring)

    def tunings(self) -> List[dict]:
        with self._lock:
            return list(self._tune_ring)

    def memberships(self) -> List[dict]:
        with self._lock:
            return list(self._member_ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._req_ring.clear()
            self._tune_ring.clear()
            self._member_ring.clear()

    def _resolve_path(self, path: Optional[str]) -> str:
        if path:
            return path
        if self.path:
            return self.path
        env = get_env(FLIGHT_PATH_ENV).strip()
        if env:
            return env
        return os.path.join(tempfile.gettempdir(),
                            f"mxtpu_flight_{os.getpid()}.json")

    def sibling_path(self, suffix: str) -> str:
        """A dump-adjacent path for companion bundles (watchdog
        postmortems, signal stack dumps): the resolved flight path with
        ``suffix`` spliced in before the extension."""
        path = self._resolve_path(None)
        root, ext = os.path.splitext(path)
        return f"{root}.{suffix}{ext or '.json'}"

    def _snapshot_rings(self) -> tuple:
        """Ring contents as plain lists, under the lock and NOTHING
        else: materialization can sync device values (``.asnumpy()``)
        and must never run while writers are blocked on the lock."""
        with self._lock:
            return (list(self._ring), list(self._req_ring),
                    list(self._tune_ring), list(self._member_ring))

    def live(self) -> dict:
        """Materialized view of all four rings for live introspection
        (``/debug/flight``, watchdog postmortems) — snapshot under the
        lock, encode outside it, same shape as the dump payload's ring
        sections."""
        raw_steps, raw_reqs, raw_tune, raw_member = self._snapshot_rings()
        steps = [{k: _materialize(v) for k, v in rec.items()}
                 for rec in raw_steps]
        requests = [{k: _materialize(v) for k, v in rec.items()}
                    for rec in raw_reqs]
        tunings = [{k: _materialize(v) for k, v in rec.items()}
                   for rec in raw_tune]
        memberships = [{k: _materialize(v) for k, v in rec.items()}
                       for rec in raw_member]
        return {"n_steps": len(steps), "steps": steps,
                "n_requests": len(requests), "requests": requests,
                "n_tuning": len(tunings), "tuning": tunings,
                "n_membership": len(memberships),
                "membership": memberships}

    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the ring + a full registry snapshot to JSON (atomic
        tmp-then-rename); returns the path, or None when disabled or the
        write itself failed — a dump runs on dying processes and must
        never raise."""
        if not self.enabled:
            return None
        path = self._resolve_path(path)
        # snapshot-then-encode: the lock protects only the list() copies;
        # _materialize may sync device values and JSON encoding is O(ring)
        # — holding the ring lock across either would stall every
        # concurrent record() (serving dispatch, trainer steps)
        raw_steps, raw_reqs, raw_tune, raw_member = self._snapshot_rings()
        steps = [{k: _materialize(v) for k, v in rec.items()}
                 for rec in raw_steps]
        requests = [{k: _materialize(v) for k, v in rec.items()}
                    for rec in raw_reqs]
        tunings = [{k: _materialize(v) for k, v in rec.items()}
                   for rec in raw_tune]
        memberships = [{k: _materialize(v) for k, v in rec.items()}
                       for rec in raw_member]
        try:
            snapshot = registry().snapshot()
        except Exception:   # noqa: BLE001 — a half-torn registry still
            snapshot = {}   # leaves the step ring worth dumping
        # causal cross-reference: step/request records carry trace_id
        # fields — ship the tracer's completed-span ring alongside so a
        # crash dump resolves those ids without hunting for the JSONL
        # stream.  Best-effort; an empty ring costs one key.
        trace_spans: List[dict] = []
        try:
            from . import tracing as _tracing
            trace_spans = _tracing.tracer().spans()
        except Exception:   # noqa: BLE001 — tracing must never block
            pass            # the dump
        payload = {
            "reason": reason,
            "ts": round(time.time(), 3),
            "host": host_id(),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "n_steps": len(steps),
            "steps": steps,
            "n_requests": len(requests),
            "requests": requests,
            "n_tuning": len(tunings),
            "tuning": tunings,
            "n_membership": len(memberships),
            "membership": memberships,
            "n_trace_spans": len(trace_spans),
            "trace_spans": trace_spans,
            "snapshot": snapshot,
        }
        if write_json_atomic(payload, path) is None:
            return None
        try:
            registry().counter(
                "flight.dumps",
                help="flight-recorder dumps written").inc()
            print(f"mxnet_tpu flight recorder: wrote {len(steps)} step "
                  f"record(s) to {path} ({reason})", file=sys.stderr)
        except Exception:   # noqa: BLE001 — bookkeeping only
            pass
        return path

    # -- crash hook --------------------------------------------------------
    def install(self) -> None:
        """Chain ``sys.excepthook`` so any unhandled exception dumps the
        ring before the traceback prints.  Idempotent; the previous hook
        always runs."""
        if self._installed or not self.enabled:
            return
        self._installed = True
        self._prev_hook = prev = sys.excepthook

        def hook(etype, value, tb):
            try:
                self.dump(f"unhandled {etype.__name__}: {value}")
            except Exception:   # noqa: BLE001 — never mask the real crash
                pass
            prev(etype, value, tb)

        sys.excepthook = hook

    def uninstall(self) -> None:
        if self._installed and self._prev_hook is not None:
            sys.excepthook = self._prev_hook
            self._installed = False
            self._prev_hook = None


_recorder_lock = threading.Lock()
_recorder_inst: Optional[FlightRecorder] = None


def recorder() -> FlightRecorder:
    """THE process-global flight recorder (capacity from the env)."""
    global _recorder_inst
    inst = _recorder_inst
    if inst is not None:
        return inst
    with _recorder_lock:
        if _recorder_inst is None:
            _recorder_inst = FlightRecorder()
        return _recorder_inst
