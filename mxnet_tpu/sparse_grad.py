"""In-graph row-sparse embedding gradients (the TPU-native lowering of
the reference's row_sparse kernels — SURVEY.md §2.1 sparse stypes,
src/operator/optimizer_op.cc lazy updates).

The reference materializes an embedding gradient as a ``RowSparseNDArray``
(values + touched row ids) and the optimizer scatters into only those
rows.  Under one jitted XLA step there is no NDArray object to carry a
ragged row set, so the same economy is achieved with SHAPE-STABLE pieces:

- the batch's ids are deduplicated in-graph with ``jnp.unique(size=B)``
  into a fixed power-of-2 *id bucket* (``serving/buckets.py`` discipline:
  one compiled step per bucket, not per batch histogram);
- the forward gathers the live rows once, adds a zero *tap buffer*
  (``zbuf``) of shape ``(B, dim)``, and looks embeddings up from those
  rows through :func:`rows_lookup`, whose custom VJP is a literal
  ``jax.ops.segment_sum`` over the dedup inverse — so the gradient of
  the loss wrt ``zbuf`` IS the ``(values, unique_ids)`` row-sparse
  gradient, while the table itself sits behind ``stop_gradient`` and
  its dense cotangent is never built;
- unused bucket slots carry the out-of-range id ``input_dim``: gathers
  clip (reading a garbage row whose result is unused), and the
  optimizer's scatters DROP out-of-bounds ids — the same scratch
  convention as ``serving/kv_cache.py`` block 0, where unwritten slots
  point at reserved scratch and garbage is masked to an exact zero.

The trainer discovers which tables actually take the sparse path with a
trace-time ``jax.eval_shape`` probe (no ops emitted, re-run on every
retrace so changing batch shapes re-size the bucket), then differentiates
wrt ``(params, zbufs)``.  ``parallel/optim.py`` turns the resulting
``(values, unique_ids)`` pairs into gather→update→scatter lazy updates.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as _np

from .base import get_env

__all__ = ["id_bucket", "rows_lookup", "SparseGradTrace", "trace_ctx"]


def _next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def id_bucket(n_ids: int) -> int:
    """Bucket capacity for a batch of ``n_ids`` embedding lookups: the
    next power of 2 (one compiled step per bucket), floored by the
    ``MXTPU_SPARSE_ID_BUCKET`` knob.  The knob can only RAISE the
    bucket — capacity below the id count could silently drop rows."""
    auto = _next_pow2(max(1, int(n_ids)))
    knob = int(get_env("MXTPU_SPARSE_ID_BUCKET"))
    if knob > 0:
        return max(auto, _next_pow2(knob))
    return auto


def rows_lookup(rows, inv):
    """Gather ``rows[inv]`` whose backward is a literal
    ``jax.ops.segment_sum`` of the output cotangent over the dedup
    inverse — the in-graph row-sparse gradient kernel.  XLA lowers the
    segment-sum to a real scatter-add over ``rows.shape[0]`` segments
    (PERF.md recommender runbook step verifies the lowering on-chip)."""
    return _rows_lookup(rows, inv)


def _make_rows_lookup():
    import jax

    @jax.custom_vjp
    def lookup(rows, inv):
        return rows[inv]

    def fwd(rows, inv):
        return rows[inv], (inv, rows.shape[0])

    def bwd(res, g):
        import jax.numpy as jnp   # noqa: F401 — keeps jax resident
        inv, nrows = res
        vals = jax.ops.segment_sum(g, inv, num_segments=nrows)
        # int args take the symbolic-zero float0 cotangent
        return vals, _np.zeros(inv.shape, jax.dtypes.float0)

    lookup.defvjp(fwd, bwd)
    return lookup


_lookup_cache = None


def _rows_lookup(rows, inv):
    global _lookup_cache
    if _lookup_cache is None:
        _lookup_cache = _make_rows_lookup()
    return _lookup_cache(rows, inv)


class _TraceTLS(threading.local):
    def __init__(self):
        self.ctx: Optional["SparseGradTrace"] = None


_tls = _TraceTLS()


def trace_ctx() -> Optional["SparseGradTrace"]:
    """The active sparse-gradient trace context, or None (eager mode,
    inference, plain dense training)."""
    return _tls.ctx


class SparseGradTrace:
    """Per-trace context the sharded trainer opens around the forward.

    Two modes, same code path through ``Embedding.hybrid_forward``:

    - ``probe``: an abstract ``jax.eval_shape`` pass that only RECORDS
      each sparse table's batch id count (``id_counts``) so the trainer
      can size the tap buffers; the forward itself stays dense.
    - ``grad``: the differentiated pass — ``zbufs`` maps a sparse
      Parameter (by ``id()``) to its ``(bucket, dim)`` tap buffer, and
      the context collects the per-table ``unique_ids`` tracers
      (``uids``) that ride out through the loss aux.

    A sparse-marked table whose forward never reaches the context (e.g.
    a hybridized cached graph that bypasses the NDArray path) simply
    stays dense — probe and grad traces see identically nothing.
    """

    def __init__(self, mode: str, zbufs: Optional[Dict[int, object]] = None):
        if mode not in ("probe", "grad"):
            raise ValueError(f"mode must be 'probe' or 'grad', got {mode!r}")
        self.mode = mode
        self.zbufs = zbufs or {}
        self.id_counts: Dict[int, int] = {}
        self.buckets: Dict[int, int] = {}
        self.uids: Dict[int, object] = {}
        # tables looked up MORE THAN ONCE in a trace (shared weights):
        # two independent dedups would each claim the one tap buffer, so
        # the trainer keeps these dense
        self.multi: set = set()

    def __enter__(self):
        self._prev = _tls.ctx
        _tls.ctx = self
        return self

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False

    # -- the Embedding hook ------------------------------------------------
    def wants(self, param) -> bool:
        """True when ``param``'s gradient should take the sparse path in
        THIS trace: every sparse-marked table during the probe; during
        the grad pass only tables the probe sized a tap buffer for."""
        if self.mode == "probe":
            return True
        return id(param) in self.zbufs

    def embedding(self, param, x_val, w_val, input_dim: int):
        """The sparse embedding forward for one table.  ``x_val`` /
        ``w_val`` are raw (traced) arrays; returns the looked-up
        embeddings.  Probe mode records the id count and returns the
        dense gather (shapes only — this runs under eval_shape)."""
        import jax
        import jax.numpy as jnp
        ids = jnp.clip(x_val.astype(jnp.int32), 0, input_dim - 1)
        if self.mode == "probe":
            if id(param) in self.id_counts:
                self.multi.add(id(param))
            self.id_counts[id(param)] = int(_np.prod(ids.shape)) \
                if ids.ndim else 1
            self.buckets[id(param)] = id_bucket(
                self.id_counts[id(param)])
            return jnp.take(w_val, ids, axis=0, mode="clip")
        zbuf = self.zbufs[id(param)]
        bucket = zbuf.shape[0]
        # shape-stable dedup: unused slots get the out-of-range id
        # input_dim (scratch convention — gathers clip, scatters drop)
        uids, inv = jnp.unique(ids.ravel(), size=bucket,
                               fill_value=input_dim, return_inverse=True)
        table = jax.lax.stop_gradient(w_val)
        rows = jnp.take(table, uids, axis=0, mode="clip") + zbuf
        out = rows_lookup(rows, inv)
        self.uids[id(param)] = uids
        return out.reshape(ids.shape + (w_val.shape[1],))
