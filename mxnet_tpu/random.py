"""Global RNG state: ``mx.random.seed()`` and the key stream.

Reference role: src/operator/random/ + src/resource.cc parallel RNG states —
per-device counter-based generators seeded from a global seed (SURVEY.md
§2.2).  TPU-native design: a process-global threefry key, split per draw
(the jax.random discipline).  As SURVEY.md §7 notes, bit-exact streams vs the
reference are explicitly out of scope — the *API* and distributional behavior
are what's preserved.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["seed", "next_key", "get_state", "set_state"]

_lock = threading.Lock()
_key = None


def _jrandom():
    import jax.random as jr
    return jr


def seed(seed_state: Optional[int] = None, ctx="all") -> None:
    """Seed the global generator (reference: mx.random.seed; the ctx argument
    is accepted for API parity — with a functional key stream every device
    draws from the same root key)."""
    global _key
    if seed_state is None:
        seed_state = int(time.time() * 1e6) & 0x7FFFFFFF
    with _lock:
        _key = _jrandom().PRNGKey(int(seed_state))


_tls = threading.local()


def push_key(key) -> None:
    """Enter a scoped key stream (used by hybrid traces so RNG draws come
    from a traced input instead of the global python-side stream)."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(key)


def pop_key() -> None:
    _tls.stack.pop()


def next_key():
    """Split a fresh subkey off the innermost active stream."""
    global _key
    stack = getattr(_tls, "stack", None)
    if stack:
        stack[-1], sub = _jrandom().split(stack[-1])
        return sub
    with _lock:
        if _key is None:
            _key = _jrandom().PRNGKey(0)
        _key, sub = _jrandom().split(_key)
        return sub


def get_state():
    return _key


def set_state(key) -> None:
    """Restore the global key stream (checkpoint resume)."""
    global _key
    with _lock:
        _key = key


def __getattr__(name):
    # reference parity: python/mxnet/random.py re-exports the draw
    # frontends, so ``mx.random.uniform(...)`` works alongside
    # ``mx.nd.random.uniform``.  Lazy to avoid an import cycle (this
    # module is imported by ndarray.random for the key stream).
    _DRAWS = ("uniform", "normal", "randn", "randint", "exponential",
              "gamma", "poisson", "negative_binomial",
              "generalized_negative_binomial", "multinomial", "shuffle",
              "bernoulli")
    if name in _DRAWS:
        from .ndarray import random as _ndrandom
        return getattr(_ndrandom, name)
    raise AttributeError(f"module 'mxnet_tpu.random' has no attribute "
                         f"{name!r}")
