"""im2rec: pack an image folder (or a .lst manifest) into RecordIO.

Reference parity: tools/im2rec.py / tools/im2rec.cc (SURVEY.md §2.4) —
same .lst format (``index\tlabel[\tlabels...]\trelpath``), same .rec/.idx
output consumed by ImageRecordIter (including the native C++ core).

Usage:
    python -m mxnet_tpu.tools.im2rec PREFIX ROOT --list      # make .lst
    python -m mxnet_tpu.tools.im2rec PREFIX ROOT             # pack .rec
"""
from __future__ import annotations

import argparse
import os
import random
import sys

from ..recordio import IRHeader, MXIndexedRecordIO, pack_img

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(prefix: str, root: str, shuffle: bool = True,
              seed: int = 0) -> str:
    """Walk ``root``; one class per subdirectory (sorted), exactly the
    reference's folder convention."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    entries = []
    if classes:
        for label, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if os.path.splitext(fn)[1].lower() in _EXTS:
                    entries.append((float(label),
                                    os.path.join(cls, fn)))
    else:       # flat folder: label 0
        for fn in sorted(os.listdir(root)):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                entries.append((0.0, fn))
    if shuffle:
        random.Random(seed).shuffle(entries)
    lst = f"{prefix}.lst"
    with open(lst, "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write(f"{i}\t{label}\t{rel}\n")
    return lst


def read_list(lst_path: str):
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def pack(prefix: str, root: str, quality: int = 95,
         resize: int = 0) -> str:
    """Read ``prefix.lst``, write ``prefix.rec`` + ``prefix.idx``."""
    import numpy as np
    from PIL import Image

    rec = MXIndexedRecordIO(f"{prefix}.idx", f"{prefix}.rec", "w")
    n = 0
    for idx, labels, rel in read_list(f"{prefix}.lst"):
        img = Image.open(os.path.join(root, rel)).convert("RGB")
        if resize:
            w, h = img.size
            s = resize / min(w, h)
            img = img.resize((max(1, round(w * s)),
                              max(1, round(h * s))), Image.BILINEAR)
        label = labels[0] if len(labels) == 1 else \
            np.asarray(labels, np.float32)
        rec.write_idx(idx, pack_img(IRHeader(0, label, idx, 0),
                                    np.asarray(img), quality=quality))
        n += 1
    rec.close()
    print(f"packed {n} images -> {prefix}.rec")
    return f"{prefix}.rec"


def main(argv=None):
    ap = argparse.ArgumentParser(prog="im2rec")
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="generate PREFIX.lst instead of packing")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    args = ap.parse_args(argv)
    if args.list:
        make_list(args.prefix, args.root, shuffle=not args.no_shuffle)
    else:
        if not os.path.isfile(f"{args.prefix}.lst"):
            make_list(args.prefix, args.root,
                      shuffle=not args.no_shuffle)
        pack(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    sys.exit(main())
