"""launch: run N distributed worker processes on this machine.

Reference parity: tools/launch.py + the dmlc local tracker (SURVEY.md
§4.5) — forks the training command once per worker with the ``DMLC_*``
environment the kvstore's dist backend reads (parallel/dist.py), waits,
and propagates the first failure.  The reference also forked parameter
servers; servers do not exist here (sync SPMD — SURVEY.md §5.8), so -s
is accepted and ignored with a note.

Usage:
    python -m mxnet_tpu.tools.launch -n 4 python train.py --args...
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(n_workers: int, cmd, env_extra=None) -> int:
    import time
    port = _free_port()
    procs = []
    for rank in range(n_workers):
        env = dict(os.environ,
                   DMLC_ROLE="worker",
                   DMLC_PS_ROOT_URI="127.0.0.1",
                   DMLC_PS_ROOT_PORT=str(port),
                   DMLC_NUM_WORKER=str(n_workers),
                   DMLC_WORKER_ID=str(rank),
                   **(env_extra or {}))
        procs.append(subprocess.Popen(cmd, env=env))
    # poll ALL workers: one crashing while its peers block in a
    # collective must tear the group down, not hang the launcher
    rc = 0
    live = list(procs)
    while live:
        for p in list(live):
            r = p.poll()
            if r is not None:
                live.remove(p)
                rc = rc or r
        if rc:
            for p in live:
                p.kill()
            for p in live:
                p.wait()
            break
        time.sleep(0.1)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(prog="launch")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; ignored "
                    "(no parameter servers in synchronous SPMD)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.num_servers:
        print("note: -s ignored — dist_sync is synchronous SPMD, "
              "no server processes", file=sys.stderr)
    if not args.command:
        ap.error("no command given")
    return launch(args.num_workers, args.command)


if __name__ == "__main__":
    sys.exit(main())
