"""mxlint — the repo's own static-analysis subsystem.

A TWO-PASS, repo-wide analysis engine (grown from PR-5's per-file
walker): pass 1 (:mod:`.graph`) builds a project symbol table and a
conservative call graph — module functions, methods resolved through
``self``/class attrs, known-alias imports — from the same trees pass 2
walks for the lexical rules (still ONE ``ast.parse`` per file).  Rules
then run with interprocedural context via ``project_check``: findings
reached through the call graph carry a ``reason`` chain naming every
hop, and a stable ``id`` (rule + path + enclosing symbol, not line).
PR 20 adds a flow-sensitive tier on the same trees: :mod:`.cfg` lowers
each function to a basic-block CFG (branch/loop/``finally``/``with``
regions, conservative exception edges), :mod:`.protocols` declares the
repo's acquire→release disciplines, and the :mod:`.flow` rules search
for exit paths that break them — such findings additionally carry
``hops``, the ``file:line`` program-point path that exhibits the
defect.  Per-line ``# mxlint: disable=<rule>`` pragmas cover
intentional exceptions; ONE frozen JSON baseline (``baseline.json``)
holds grandfathered debt, file-level.

Rules (:mod:`.rules`) encode the codebase's actual contracts:

========================  ===================================================
``bare-except``           no bare ``except:`` under mxnet_tpu/
``unbounded-lru-method``  no ``lru_cache(maxsize=None)`` on methods
``counter-dict``          metrics go through ``observability.registry()``
``timing-pair``           wall-clock pairs go through ``trace.span``
``lock-discipline``       lock-guarded state is written under its lock;
                          plus (interprocedural) lock-order inversions
                          and re-acquisition of a held non-reentrant Lock
``collective-safety``     no collectives — even via helpers — reached
                          from host-divergent branches
``hot-path-purity``       nothing reachable from ``@hot_path("dispatch")``
                          allocates, reads env, creates locks, or logs
``hidden-host-sync``      no ``.asnumpy()``/``.item()``/cast syncs on or
                          near ``@hot_path`` roots
``env-knob``              ``MXNET_*``/``MXTPU_*`` reads go through the
                          declared knob table (``base.register_env``)
``resource-leak``         every acquire (KV block, span, tmp file,
                          ContextVar token) reaches a release or an
                          ownership transfer on EVERY path, exception
                          edges included
``thread-lifecycle``      every started thread is joined, stopped, or
                          atexit-registered by someone
``blocking-under-lock``   no indefinitely-blocking call (queue get/put
                          sans timeout, ``join()``, socket recv) is
                          reachable — even via callees — under a lock
========================  ===================================================

CLI::

    python -m mxnet_tpu.tools.mxlint [--json] [--changed] [--fix
        [--dry-run]] [paths...]

exits nonzero on any NEW finding (not pragma-suppressed, not in the
baseline).  ``--changed`` lints only git-touched files (quick local
runs); ``--fix`` applies the mechanical rewriters (:mod:`.fix` — raw
environ read → ``get_env``, same-block ``acquire()/release()`` pair →
``with lock:``), idempotent and validated by re-linting; with
``--dry-run`` it prints the diff and exits 1 if anything WOULD change
(the precommit hook mode — see ``tools/precommit.py``);
``--write-baseline`` refreezes the baseline (deliberate act — the lint
test guards the baseline against silent growth); ``--knobs-md`` prints
the generated env-knob reference table the README embeds.

Pytest entry point: ``tests/test_lint.py`` calls :func:`check_repo`,
which memoizes ONE full-repo two-pass run per process — the thin
per-rule assertions in other test modules (:func:`rule_findings`)
reuse it, so the whole suite pays a single analysis pass.
"""
from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, Finding, is_suppressed, pragma_map, \
    run_rules
from .graph import Project, build_project
from .rules import ALL_RULES, BASE_RELPATH, declared_knobs, make_rules

__all__ = ["Finding", "Project", "build_project", "lint_paths",
           "lint_source", "check_repo", "rule_findings", "load_baseline",
           "knob_table_markdown", "fix_paths", "main", "ALL_RULES",
           "REPO_ROOT", "DEFAULT_TARGET", "BASELINE_PATH"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(_PKG_DIR)))
DEFAULT_TARGET = os.path.join(REPO_ROOT, "mxnet_tpu")
BASELINE_PATH = os.path.join(_PKG_DIR, "baseline.json")

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


def _relpath(path: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), REPO_ROOT)
    return rel.replace(os.sep, "/")


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def _split_suppressed(findings: Sequence[Finding], source: str
                      ) -> Tuple[List[Finding], List[Finding]]:
    pragmas = pragma_map(source)
    lines = source.splitlines()
    new, suppressed = [], []
    for f in findings:
        (suppressed if is_suppressed(f, pragmas, lines) else new).append(f)
    return new, suppressed


def _lint_items(items: Sequence[Tuple[str, str, "ast.AST"]], rules
                ) -> Tuple[List[Finding], List[Finding]]:
    """The two-pass core over already-parsed files.

    Pass 1 (:func:`mxlint.graph.build_project`) builds the repo-wide
    symbol table + call graph; pass 2 walks each file once for the
    lexical rules, then runs every rule's ``project_check`` with the
    full interprocedural context.  Project findings are pragma-filtered
    against the source of the file they land in, exactly like lexical
    ones."""
    project = build_project([(rel, tree) for rel, _src, tree in items])
    sources = {rel: src for rel, src, _tree in items}
    by_file: Dict[str, List[Finding]] = {rel: [] for rel, _s, _t in items}
    for rel, source, tree in items:
        ctx = FileContext(rel, tree, source, project=project)
        file_rules = [r for r in rules if r.applies_to(rel)]
        by_file[rel].extend(run_rules(ctx, file_rules))
    for r in rules:
        for f in r.project_check(project):
            if f.path in by_file and r.applies_to(f.path):
                by_file[f.path].append(f)
    all_new: List[Finding] = []
    all_sup: List[Finding] = []
    for rel in sorted(by_file):
        new, sup = _split_suppressed(by_file[rel], sources[rel])
        all_new.extend(new)
        all_sup.extend(sup)
    return all_new, all_sup


def lint_source(source: str, relpath: str = "mxnet_tpu/<snippet>.py",
                rules=None) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source string → (new_findings, suppressed_findings).
    The fixture/test entry point; ``relpath`` participates in rule
    ``skip_paths`` policy, so pass something realistic.  The
    interprocedural rules see a one-file project (helpers defined in
    the same source resolve; anything else conservatively doesn't)."""
    rules = rules if rules is not None else make_rules(REPO_ROOT)
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return ([Finding("parse-error", relpath, e.lineno or 0,
                         f"syntax error: {e.msg}")], [])
    return _lint_items([(relpath, source, tree)], rules)


def lint_paths(paths: Optional[Sequence[str]] = None
               ) -> Tuple[List[Finding], List[Finding]]:
    """Lint files/directories → (findings, suppressed), pragma-filtered
    but NOT baseline-filtered (the caller splits new vs. grandfathered
    so ``--json`` can show both).  The call graph spans exactly the
    linted set: a full-tree run (the default, and the pytest gate) gets
    repo-wide reachability; a narrowed scope resolves what it can see."""
    paths = list(paths) if paths else [DEFAULT_TARGET]
    all_new: List[Finding] = []
    items: List[Tuple[str, str, "ast.AST"]] = []
    rules = make_rules(REPO_ROOT)
    for path in iter_py_files(paths):
        rel = _relpath(path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            all_new.append(Finding("parse-error", rel, 0,
                                   f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            all_new.append(Finding("parse-error", rel, e.lineno or 0,
                                   f"syntax error: {e.msg}"))
            continue
        items.append((rel, source, tree))
    new, sup = _lint_items(items, rules)
    all_new.extend(new)
    return all_new, sup


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Optional[str] = None) -> Set[Tuple[str, str]]:
    """Frozen grandfather entries as ``{(rule, relpath)}`` — file-level,
    so line drift in a grandfathered file never breaks the build while
    the SAME debt in a new file always does."""
    path = path or BASELINE_PATH
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return set()
    return {(e["rule"], e["path"]) for e in data.get("entries", ())}


def write_baseline(findings: Sequence[Finding],
                   path: Optional[str] = None) -> int:
    path = path or BASELINE_PATH
    entries = sorted({(f.rule, f.path) for f in findings})
    payload = {
        "comment": "mxlint grandfathered debt — file-level (rule, path) "
                   "entries.  FROZEN: tests/test_lint.py guards this "
                   "list; shrink it by fixing debt, never grow it for "
                   "new code (use the rule or a justified pragma).",
        "entries": [{"rule": r, "path": p} for r, p in entries],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return len(entries)


def split_baselined(findings: Sequence[Finding],
                    baseline: Set[Tuple[str, str]]
                    ) -> Tuple[List[Finding], List[Finding]]:
    new, old = [], []
    for f in findings:
        (old if (f.rule, f.path) in baseline else new).append(f)
    return new, old


# -- cached whole-repo run (the pytest entry point) -------------------------

_cached_run: Optional[Tuple[List[Finding], List[Finding]]] = None


def check_repo(refresh: bool = False
               ) -> Tuple[List[Finding], List[Finding]]:
    """ONE memoized lint of ``mxnet_tpu/`` per process → (new_findings,
    baselined_findings).  Every thin test assertion shares this run."""
    global _cached_run
    if _cached_run is None or refresh:
        findings, _sup = lint_paths([DEFAULT_TARGET])
        _cached_run = split_baselined(findings, load_baseline())
    return _cached_run


def rule_findings(rule: str) -> List[Finding]:
    """NEW findings of one rule from the cached repo run — the thin
    assertion the old per-test AST walkers collapse into:
    ``assert mxlint.rule_findings("bare-except") == []``."""
    new, _old = check_repo()
    return [f for f in new if f.rule == rule]


# -- env-knob reference (README generation) ---------------------------------

def knob_rows(repo_root: Optional[str] = None) -> List[dict]:
    """Statically extract every ``register_env(name, default, typ,
    help)`` row from the knob table in ``mxnet_tpu/base.py`` — no
    package import, so doc generation costs no jax startup."""
    root = repo_root or REPO_ROOT
    path = os.path.join(root, *BASE_RELPATH.split("/"))
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    rows = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "register_env" and node.args
                and isinstance(node.args[0], ast.Constant)):
            continue
        args = list(node.args) + [kw.value for kw in node.keywords]
        name = node.args[0].value
        try:
            default = ast.literal_eval(args[1]) if len(args) > 1 else None
        except ValueError:
            default = ast.unparse(args[1])
        typ = args[2].id if len(args) > 2 and \
            isinstance(args[2], ast.Name) else "str"
        help_text = ""
        for a in args[3:]:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                help_text = a.value
        for kw in node.keywords:
            if kw.arg == "help" and isinstance(kw.value, ast.Constant):
                help_text = kw.value.value
        rows.append({"name": name, "default": default, "type": typ,
                     "help": " ".join(help_text.split())})
    rows.sort(key=lambda r: r["name"])
    return rows


def knob_table_markdown(repo_root: Optional[str] = None) -> str:
    """The generated env-knob reference the README embeds between
    ``<!-- mxlint-knobs:begin -->`` / ``:end`` markers (test-enforced in
    sync)."""
    lines = ["| Variable | Type | Default | Description |",
             "|---|---|---|---|"]
    for r in knob_rows(repo_root):
        default = "_unset_" if r["default"] is None else \
            f"`{r['default']!r}`" if isinstance(r["default"], str) \
            else f"`{r['default']}`"
        lines.append(f"| `{r['name']}` | {r['type']} | {default} | "
                     f"{r['help']} |")
    return "\n".join(lines) + "\n"


# -- --fix ------------------------------------------------------------------

def fix_paths(paths: Optional[Sequence[str]] = None,
              dry_run: bool = False,
              out=sys.stdout) -> Tuple[int, int]:
    """Run the mechanical fixers over the target files → (files changed,
    fixes applied).  ``dry_run`` prints a unified diff instead of
    writing.  Idempotent: a second run changes nothing."""
    import difflib

    from .fix import fix_source
    declared = declared_knobs(REPO_ROOT)
    paths = list(paths) if paths else [DEFAULT_TARGET]
    n_files = n_fixes = 0
    for path in iter_py_files(paths):
        rel = _relpath(path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        fixed, fixes = fix_source(source, rel, declared)
        if not fixes or fixed == source:
            continue
        n_files += 1
        n_fixes += len(fixes)
        if dry_run:
            diff = difflib.unified_diff(
                source.splitlines(keepends=True),
                fixed.splitlines(keepends=True),
                fromfile=f"a/{rel}", tofile=f"b/{rel}")
            out.write("".join(diff))
        else:
            with open(path, "w", encoding="utf-8") as f:
                f.write(fixed)
        for fx in fixes:
            out.write(f"mxlint --fix{' (dry-run)' if dry_run else ''}: "
                      f"{rel}:{fx.line}: {fx.detail}\n")
    return n_files, n_fixes


# -- CLI --------------------------------------------------------------------

_FIXTURE_DIR = "tests/lint_fixtures/"


def _changed_files() -> List[str]:
    """git-touched .py files (diff vs HEAD + untracked) for --changed.
    The lint-fixture vectors are excluded: the ``*_bad`` ones trip their
    rules BY DESIGN, and ``tests/test_lint.py`` already locks their
    behavior down."""
    out: List[str] = []
    for cmd in (["git", "-C", REPO_ROOT, "diff", "--name-only", "HEAD"],
                ["git", "-C", REPO_ROOT, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30, check=True)
        except (OSError, subprocess.SubprocessError):
            return []
        out.extend(line.strip() for line in res.stdout.splitlines()
                   if line.strip().endswith(".py")
                   and not line.strip().startswith(_FIXTURE_DIR))
    seen, files = set(), []
    for rel in out:
        full = os.path.join(REPO_ROOT, rel)
        if rel not in seen and os.path.isfile(full):
            seen.add(rel)
            files.append(full)
    return files


_USAGE = """\
usage: python -m mxnet_tpu.tools.mxlint [options] [paths...]

Lint mxnet_tpu/ (default) or the given files/directories.

options:
  --json            machine-readable output (findings + baselined),
                    each finding with its stable id and reason chain
  --changed         lint only git-touched .py files (quick local runs)
  --fix             apply mechanical rewrites (environ read -> get_env,
                    same-block acquire/release pair -> with lock:),
                    then re-lint the fixed tree
  --dry-run         with --fix: print the diff, write nothing; exits 1
                    if anything would change (precommit-hook mode)
  --baseline PATH   use a different baseline file
  --write-baseline  refreeze the baseline from the current findings
  --knobs-md        print the generated env-knob reference table
  --list-rules      print rule names and one-line descriptions
  -h, --help        this message
"""


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = changed = write_bl = do_fix = dry_run = False
    baseline_path = None
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(_USAGE, end="")
            return 0
        if a == "--json":
            as_json = True
        elif a == "--changed":
            changed = True
        elif a == "--fix":
            do_fix = True
        elif a == "--dry-run":
            dry_run = True
        elif a == "--write-baseline":
            write_bl = True
        elif a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline needs a path", file=sys.stderr)
                return 2
            baseline_path = argv[i]
        elif a == "--knobs-md":
            print(knob_table_markdown(), end="")
            return 0
        elif a == "--list-rules":
            for r in make_rules(REPO_ROOT):
                print(f"{r.name:<22} {r.description}")
            return 0
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{_USAGE}", file=sys.stderr,
                  end="")
            return 2
        else:
            paths.append(a)
        i += 1

    if write_bl:
        # a baseline frozen from a partial scope would silently drop
        # the grandfather entries for everything outside it — always
        # refreeze from the full default target
        if paths or changed:
            print("mxlint: --write-baseline always freezes from the "
                  "full default target; ignoring the path/--changed "
                  "scope", file=sys.stderr)
        findings, _suppressed = lint_paths(None)
        n = write_baseline(findings, baseline_path)
        print(f"mxlint: froze {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} -> "
              f"{baseline_path or BASELINE_PATH}")
        return 0
    if dry_run and not do_fix:
        print("--dry-run only means something with --fix",
              file=sys.stderr)
        return 2
    if changed:
        paths = _changed_files()
        if not paths:
            if not as_json:
                print("mxlint: no changed .py files")
            return 0
    if do_fix:
        # with --json, stdout must stay ONE parseable document — route
        # the fixer chatter to stderr
        fix_out = sys.stderr if as_json else sys.stdout
        n_files, n_fixes = fix_paths(paths or None, dry_run=dry_run,
                                     out=fix_out)
        if dry_run:
            print(f"mxlint --fix --dry-run: {n_fixes} fix"
                  f"{'' if n_fixes == 1 else 'es'} pending in {n_files} "
                  f"file{'' if n_files == 1 else 's'}", file=fix_out)
            if n_fixes:
                return 1
            # fall through to the normal lint so the hook still gates
            # on findings the fixers can't touch
        else:
            print(f"mxlint --fix: applied {n_fixes} fix"
                  f"{'' if n_fixes == 1 else 'es'} in {n_files} "
                  f"file{'' if n_files == 1 else 's'}; re-linting",
                  file=fix_out)
    findings, suppressed = lint_paths(paths or None)
    baseline = load_baseline(baseline_path)
    new, old = split_baselined(findings, baseline)

    if as_json:
        print(json.dumps({
            "new": [f.as_dict() for f in new],
            "baselined": [f.as_dict() for f in old],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=1))
    else:
        for f in sorted(new, key=lambda f: (f.path, f.line, f.rule)):
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
            for r in f.reason:
                print(f"    reason: {r}")
            if f.hops:
                print("    path:   " + " -> ".join(f.hops))
        tail = []
        if old:
            tail.append(f"{len(old)} baselined")
        if suppressed:
            tail.append(f"{len(suppressed)} pragma-suppressed")
        extra = f" ({', '.join(tail)})" if tail else ""
        print(f"mxlint: {len(new)} new finding"
              f"{'' if len(new) == 1 else 's'}{extra}")
    return 1 if new else 0
