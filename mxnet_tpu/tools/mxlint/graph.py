"""mxlint pass 1: project symbol table + conservative call graph.

The interprocedural context the lexical rules were blind to.  One extra
walk per file (the trees are already parsed — still ONE ``ast.parse``
per file) extracts per-function **facts**:

- call sites, with the resolution hints the conservative resolver
  understands (bare names, ``self.meth``/``cls.meth``, ``alias.f`` via
  known imports) plus the lexical context at the site — the innermost
  host-divergent ``if`` token and the set of locks held;
- collective calls (``allgather_*``/``allreduce_host``/…), with their
  own host-branch context;
- host-sync events (``.asnumpy()``/``.item()``/value casts/np coercion);
- hot-path impurities (lock creation, env reads, logging, host-array
  allocation);
- lock acquisitions (``with``/``acquire()``), each with the locks
  already held — the raw material for lock-order analysis.

:class:`Project` then answers the interprocedural questions the rules
ask (``find_collective``, ``find_acquires``, ``reachable``), every
search **call-depth-bounded** (:data:`MAX_CALL_DEPTH`) and cycle-safe,
returning the call chain so findings can carry a ``reason`` the reader
can audit.

Resolution is deliberately conservative: a call the resolver cannot
attribute (``obj.method()`` on an arbitrary value, higher-order calls,
anything imported from outside the linted set) contributes no edge.
Missed edges mean missed findings, never false ones.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import protocols as _proto
from .core import FUNC_TYPES, _lock_token

__all__ = ["Project", "FuncFacts", "ModuleFacts", "build_project",
           "MAX_CALL_DEPTH", "COLLECTIVES", "HOST_TOKENS", "HOT_PATH_MARK"]

#: BFS bound for every interprocedural search: deep enough to see
#: through the wrapper layers this codebase actually has (dispatch →
#: segment → engine → registry is 4), small enough that a conservative
#: over-approximation cannot walk the whole repo from one call site.
MAX_CALL_DEPTH = 6

#: fleet collectives: every host must reach these or none may.  The
#: elastic-fleet membership/quiesce entry points are in the checked set
#: too: `reform`/`quiesce` are fleet-synchronized protocols (every
#: survivor runs them or the KV consensus round never completes) and
#: `step_barrier` IS a barrier — so none of them may be reachable from
#: a surviving-rank branch either.  The SPMD scale-out entry points
#: joined with ZeRO (PR 10): `reduce_scatter_host` reduces like the
#: other host collectives, and `reshard` rebuilds the sharded step
#: whose collectives span the new mesh — a rank that skips either
#: leaves the fleet's collective schedules desynced.
COLLECTIVES = frozenset((
    "allgather_bytes", "allgather_host", "allreduce_host",
    "allgather_rows", "reduce_scatter_host", "broadcast_host", "barrier",
    "reform", "quiesce", "step_barrier", "reshard"))

#: identifiers whose value DIVERGES across hosts — including the
#: re-form protocol's survivor/leader coordinates (`if me == leader:`
#: is exactly as host-divergent as `if rank == 0:`)
HOST_TOKENS = frozenset((
    "process_index", "process_id", "host_id", "rank", "worker_id",
    "local_rank", "host", "leader", "is_leader", "phys_rank",
    "new_rank", "survivor", "survivors"))

#: the decorator name marking hot-path roots (mxnet_tpu.base.hot_path)
HOT_PATH_MARK = "hot_path"

_LOCK_FACTORIES = ("Lock", "RLock")

# numpy-ish module aliases + the array-materializing calls on them
_NP_ALIASES = frozenset(("np", "_np", "numpy", "onp"))
_NP_ALLOC = frozenset(("array", "asarray", "zeros", "ones", "empty",
                       "full", "arange", "copy", "ascontiguousarray"))
_LOG_METHODS = frozenset(("debug", "info", "warning", "warn", "error",
                          "exception", "critical", "log"))
# value casts that force a device round-trip when fed an NDArray-valued
# expression; only method-call results count (float(x.sum())) — casting
# a plain name is overwhelmingly a host scalar already
_CAST_NAMES = frozenset(("float", "int", "bool"))
# ...but not results of dict/host accessors: bool(kwargs.get(...)) and
# friends never touch the device
_CAST_EXEMPT_METHODS = frozenset(("get", "pop", "setdefault", "decode",
                                  "encode", "strip", "split", "read"))


def _trailing_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _host_conditioned(test: ast.expr) -> Optional[str]:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in HOST_TOKENS:
            return n.id
        if isinstance(n, ast.Attribute) and n.attr in HOST_TOKENS:
            return n.attr
    return None


def _is_lock_factory(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and \
        _trailing_name(node.func) in _LOCK_FACTORIES and not node.args


def _hot_kind(decorators: Sequence[ast.expr]) -> Optional[str]:
    """``@hot_path("dispatch")`` / ``@base.hot_path("step")`` → kind."""
    for d in decorators:
        if isinstance(d, ast.Call) and \
                _trailing_name(d.func) == HOT_PATH_MARK and d.args and \
                isinstance(d.args[0], ast.Constant) and \
                isinstance(d.args[0].value, str):
            return d.args[0].value
    return None


class CallSite:
    """One call with the lexical context the interprocedural rules need."""

    __slots__ = ("desc", "line", "host_tok", "held")

    def __init__(self, desc: Tuple, line: int, host_tok: Optional[str],
                 held: Tuple):
        self.desc = desc          # ("name", f) | ("self", m) | ("attr", b, m)
        self.line = line
        self.host_tok = host_tok  # host-divergent branch token at the site
        self.held = held          # scoped lock tokens held at the site


class FuncFacts:
    """Everything pass 1 learned about one function/method.  Nested
    ``def``s and lambdas are inlined into their enclosing function —
    closures run (or not) on the enclosing frame's path, and the
    conservative direction is to attribute their effects upward."""

    __slots__ = ("key", "relpath", "qualname", "class_name", "line",
                 "hot_kind", "calls", "collectives", "syncs", "impure",
                 "acquires", "proto_releases", "blocking", "thread_ops",
                 "self_reads")

    def __init__(self, key: str, relpath: str, qualname: str,
                 class_name: Optional[str], line: int):
        self.key = key
        self.relpath = relpath
        self.qualname = qualname
        self.class_name = class_name
        self.line = line
        self.hot_kind: Optional[str] = None
        self.calls: List[CallSite] = []
        self.collectives: List[Tuple[str, int, Optional[str]]] = []
        self.syncs: List[Tuple[str, int, str]] = []    # (kind, line, what)
        self.impure: List[Tuple[str, int, str]] = []   # (kind, line, what)
        self.acquires: List[Tuple[Tuple, int, Tuple]] = []  # (tok, ln, held)
        # flow-tier facts (PR 20): protocol releases performed anywhere
        # in this function (protocol name -> first line), indefinitely-
        # blocking calls, and thread lifecycle ops (op, receiver, line)
        # with op in {"ctor-local", "ctor-self", "start", "retire"}
        self.proto_releases: Dict[str, int] = {}
        self.blocking: List[Tuple[str, int]] = []
        self.thread_ops: List[Tuple[str, str, int]] = []
        # every self/cls attribute READ in this function — the thread
        # rule's "does anyone else even look at this thread?" evidence
        # (joins through local aliases are invisible to verb matching:
        # `t, self._t = self._t, None; t.join()`)
        self.self_reads: Set[str] = set()

    def __repr__(self) -> str:
        return f"<FuncFacts {self.key}>"


class ClassFacts:
    __slots__ = ("name", "methods", "bases")

    def __init__(self, name: str, bases: Sequence[str]):
        self.name = name
        self.methods: Dict[str, str] = {}     # method name -> func key
        self.bases = tuple(bases)             # base-class NAMES (resolved
        # lazily through the same module's symbol table)


class ModuleFacts:
    __slots__ = ("relpath", "func_defs", "classes", "import_mods",
                 "import_syms", "lock_kinds")

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.func_defs: Dict[str, str] = {}          # name -> func key
        self.classes: Dict[str, ClassFacts] = {}
        self.import_mods: Dict[str, str] = {}        # alias -> module relpath
        self.import_syms: Dict[str, Tuple[str, str]] = {}  # alias -> (rp, sym)
        self.lock_kinds: Dict[Tuple, str] = {}       # token -> Lock | RLock


def _module_pkg_parts(relpath: str) -> List[str]:
    """Package-path parts for relative-import resolution."""
    parts = relpath.split("/")
    if parts[-1] == "__init__.py":
        return parts[:-1]
    return parts[:-1]


class _FactWalker:
    """One recursive, order-preserving walk of one module tree."""

    def __init__(self, relpath: str, project: "Project"):
        self.rp = relpath
        self.proj = project
        self.mf = ModuleFacts(relpath)
        project.modules[relpath] = self.mf
        self.cur_func: Optional[FuncFacts] = None
        self.cur_class: Optional[str] = None
        self.if_hosts: List[Optional[str]] = []
        self.held: List[Tuple] = []
        # module-level statements get their own pseudo-function so e.g.
        # a collective at import time still has somewhere to land; no
        # call ever resolves TO it, so it can't pollute reachability
        self.mod_func = FuncFacts(f"{relpath}::<module>", relpath,
                                  "<module>", None, 0)
        project.functions[self.mod_func.key] = self.mod_func

    # -- token scoping ------------------------------------------------------
    def _scoped_token(self, expr: ast.expr) -> Optional[Tuple]:
        tok = _lock_token(expr)
        if tok is None:
            return None
        scope, name = tok
        if scope in ("self", "cls"):
            if self.cur_class is None:
                return ("obj", scope, name)
            return ("cls", f"{self.rp}::{self.cur_class}", name)
        if isinstance(expr, ast.Name):
            # module identity ONLY for names assigned a Lock at module
            # top level (pre-scanned); a function-LOCAL lock variable
            # must not share identity with unrelated same-named locals
            # in other functions — that invents deadlock findings
            mod_tok = ("mod", self.rp, name)
            if mod_tok in self.mf.lock_kinds:
                return mod_tok
            return ("obj", "<local>", name)
        base = expr.value if isinstance(expr, ast.Attribute) else None
        base_name = base.id if isinstance(base, ast.Name) else "?"
        return ("obj", base_name, name)

    def _scoped_held(self) -> Tuple:
        """Locks held at this point that have a cross-function identity
        (class- or module-scoped; ``obj.attr`` locks on local values
        cannot be matched reliably across functions)."""
        return tuple(t for t in self.held if t[0] in ("cls", "mod"))

    def _host_tok(self) -> Optional[str]:
        for tok in reversed(self.if_hosts):
            if tok is not None:
                return tok
        return None

    # -- facts helpers ------------------------------------------------------
    def _func_key(self, name: str) -> str:
        if self.cur_class is not None:
            return f"{self.rp}::{self.cur_class}.{name}"
        return f"{self.rp}::{name}"

    def _record_lock_kind(self, token: Tuple, value: ast.expr) -> None:
        kind = _trailing_name(value.func)  # Lock | RLock
        self.mf.lock_kinds[token] = kind
        self.proj.lock_kinds[token] = kind

    # -- walk ---------------------------------------------------------------
    def walk(self, tree: ast.AST) -> None:
        # pre-scan module-level lock assignments so a `with _lock:` in a
        # function defined ABOVE the assignment still gets module scope
        for stmt in getattr(tree, "body", ()):
            if isinstance(stmt, ast.Assign) and \
                    _is_lock_factory(stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self._record_lock_kind(
                            ("mod", self.rp, tgt.id), stmt.value)
        for child in ast.iter_child_nodes(tree):
            self._go(child)

    def _go(self, node: ast.AST) -> None:  # noqa: C901 — one dispatch hub
        t = type(node)
        if t is ast.Attribute:
            if isinstance(node.value, ast.Name) and \
                    node.value.id in ("self", "cls") and \
                    isinstance(node.ctx, ast.Load):
                ff = self.cur_func if self.cur_func is not None else self.mod_func
                ff.self_reads.add(node.attr)
        if t in FUNC_TYPES:
            self._enter_func(node)
            return
        if t is ast.ClassDef:
            self._enter_class(node)
            return
        if t is ast.Import:
            self._do_import(node)
            return
        if t is ast.ImportFrom:
            self._do_import_from(node)
            return
        if t in (ast.With, ast.AsyncWith):
            toks = []
            for item in node.items:
                self._go(item.context_expr)
                if item.optional_vars is not None:
                    self._go(item.optional_vars)
                tok = self._scoped_token(item.context_expr)
                if tok is not None:
                    # push immediately so a later item in the same
                    # `with a, b:` sees `a` already held
                    self._note_acquire(tok, item.context_expr.lineno)
                    self.held.append(tok)
                    toks.append(tok)
            for stmt in node.body:
                self._go(stmt)
            if toks:
                del self.held[-len(toks):]
            return
        if t is ast.If or t is ast.IfExp:
            self._go(node.test)
            self.if_hosts.append(_host_conditioned(node.test))
            # an explicit acquire() inside ONE arm must not look held in
            # the other arm or after the If — the arms are mutually
            # exclusive, and inventing a hold there invents deadlock
            # findings (conservative = fewer held locks, never more)
            depth = len(self.held)
            if t is ast.If:
                for stmt in node.body:
                    self._go(stmt)
                del self.held[depth:]
                for stmt in node.orelse:
                    self._go(stmt)
                del self.held[depth:]
            else:
                self._go(node.body)
                del self.held[depth:]
                self._go(node.orelse)
                del self.held[depth:]
            self.if_hosts.pop()
            return
        if t is ast.Assign:
            self._do_assign(node)
            return
        if t is ast.Expr and isinstance(node.value, ast.Call):
            fn = node.value.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("acquire", "release"):
                tok = self._scoped_token(fn.value)
                if tok is not None:
                    self._go(node.value)   # the call itself (events/edges)
                    if fn.attr == "acquire":
                        self._note_acquire(tok, node.lineno)
                        self.held.append(tok)
                    else:
                        for i in range(len(self.held) - 1, -1, -1):
                            if self.held[i] == tok:
                                del self.held[i]
                                break
                    return
        if t is ast.Call:
            self._do_call(node)
            # fall through: walk arguments too
        for child in ast.iter_child_nodes(node):
            self._go(child)

    def _enter_func(self, node) -> None:
        for d in node.decorator_list:
            self._go(d)
        if self.cur_func is not None:
            # nested def/closure: inline its body into the parent
            held_depth = len(self.held)
            for stmt in node.body:
                self._go(stmt)
            del self.held[held_depth:]
            return
        if self.cur_class is not None:
            qual = f"{self.cur_class}.{node.name}"
        else:
            qual = node.name
        key = self._func_key(node.name)
        ff = FuncFacts(key, self.rp, qual, self.cur_class, node.lineno)
        ff.hot_kind = _hot_kind(node.decorator_list)
        self.proj.functions[key] = ff
        if self.cur_class is not None:
            self.mf.classes[self.cur_class].methods[node.name] = key
        else:
            self.mf.func_defs.setdefault(node.name, key)
        self.cur_func = ff
        held, ifs = self.held, self.if_hosts
        self.held, self.if_hosts = [], []
        for stmt in node.body:
            self._go(stmt)
        self.held, self.if_hosts = held, ifs
        self.cur_func = None

    def _enter_class(self, node: ast.ClassDef) -> None:
        if self.cur_func is not None:
            # class inside a function: its methods inline into the
            # enclosing function like any nested def
            for stmt in node.body:
                self._go(stmt)
            return
        bases = [b for b in (_trailing_name(x) for x in node.bases)
                 if b is not None]
        if self.cur_class is not None:
            # class nested in a class body: index its methods under a
            # dotted sentinel ("Outer.Inner" — can't collide with a
            # top-level class name) so `self.meth()` in OUTER methods
            # cannot resolve to the inner class's methods (a fabricated
            # edge), while calls WITHIN the inner class still resolve
            name = f"{self.cur_class}.{node.name}"
        else:
            name = node.name
        self.mf.classes[name] = ClassFacts(name, bases)
        outer, self.cur_class = self.cur_class, name
        for stmt in node.body:
            self._go(stmt)
        self.cur_class = outer

    # -- imports ------------------------------------------------------------
    def _resolve_module(self, dotted: str, level: int) -> Optional[str]:
        if level == 0:
            parts = dotted.split(".") if dotted else []
        else:
            base = _module_pkg_parts(self.rp)
            if level - 1 > len(base):
                return None
            base = base[:len(base) - (level - 1)]
            parts = base + (dotted.split(".") if dotted else [])
        if not parts:
            return None
        for cand in ("/".join(parts) + ".py",
                     "/".join(parts) + "/__init__.py"):
            if cand in self.proj.known_paths:
                return cand
        return None

    def _do_import(self, node: ast.Import) -> None:
        for alias in node.names:
            rp = self._resolve_module(alias.name, 0)
            if rp is None:
                continue
            if alias.asname is not None:
                self.mf.import_mods[alias.asname] = rp
            elif "." not in alias.name:
                # `import a.b.c` with no asname binds only `a`
                self.mf.import_mods[alias.name] = rp

    def _do_import_from(self, node: ast.ImportFrom) -> None:
        base_rp = self._resolve_module(node.module or "", node.level)
        if base_rp is None:
            return
        pkg_dir = base_rp[:-len("/__init__.py")] \
            if base_rp.endswith("/__init__.py") else None
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            # submodule of a package beats a symbol of the module
            if pkg_dir is not None:
                for cand in (f"{pkg_dir}/{alias.name}.py",
                             f"{pkg_dir}/{alias.name}/__init__.py"):
                    if cand in self.proj.known_paths:
                        self.mf.import_mods[local] = cand
                        break
                else:
                    self.mf.import_syms[local] = (base_rp, alias.name)
            else:
                self.mf.import_syms[local] = (base_rp, alias.name)

    # -- assignments (lock kinds, thread ctors) ------------------------------
    def _do_assign(self, node: ast.Assign) -> None:
        if _proto.is_thread_ctor(node.value):
            ff = self.cur_func if self.cur_func is not None else \
                self.mod_func
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in ("self", "cls"):
                    ff.thread_ops.append(("ctor-self", tgt.attr,
                                          node.lineno))
                elif isinstance(tgt, ast.Name):
                    ff.thread_ops.append(("ctor-local", tgt.id,
                                          node.lineno))
        if _is_lock_factory(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in ("self", "cls") and \
                        self.cur_class is not None:
                    self._record_lock_kind(
                        ("cls", f"{self.rp}::{self.cur_class}", tgt.attr),
                        node.value)
                elif isinstance(tgt, ast.Name):
                    if self.cur_class is not None and self.cur_func is None:
                        self._record_lock_kind(
                            ("cls", f"{self.rp}::{self.cur_class}",
                             tgt.id), node.value)
                    elif self.cur_func is None:
                        self._record_lock_kind(
                            ("mod", self.rp, tgt.id), node.value)
        for child in ast.iter_child_nodes(node):
            self._go(child)

    # -- events -------------------------------------------------------------
    def _note_acquire(self, tok: Tuple, line: int) -> None:
        ff = self.cur_func if self.cur_func is not None else self.mod_func
        ff.acquires.append((tok, line, self._scoped_held()))

    def _do_call(self, node: ast.Call) -> None:
        ff = self.cur_func if self.cur_func is not None else self.mod_func
        fn = node.func
        name = _trailing_name(fn)
        # collectives
        if name in COLLECTIVES:
            ff.collectives.append((name, node.lineno, self._host_tok()))
        # host syncs
        if name == "asnumpy" and isinstance(fn, ast.Attribute) and \
                not node.args:
            ff.syncs.append(("asnumpy", node.lineno, ".asnumpy()"))
        elif name == "item" and isinstance(fn, ast.Attribute) and \
                not node.args:
            ff.syncs.append(("item", node.lineno, ".item()"))
        elif isinstance(fn, ast.Name) and fn.id in _CAST_NAMES and \
                len(node.args) == 1 and \
                isinstance(node.args[0], ast.Call) and \
                isinstance(node.args[0].func, ast.Attribute) and \
                node.args[0].func.attr not in _CAST_EXEMPT_METHODS:
            ff.syncs.append(("cast", node.lineno,
                             f"{fn.id}(<.{node.args[0].func.attr}()>)"))
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in ("asarray", "array") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in _NP_ALIASES and node.args and \
                isinstance(node.args[0], (ast.Name, ast.Attribute)):
            ff.syncs.append(("np-coerce", node.lineno,
                             f"{fn.value.id}.{fn.attr}(...)"))
        # hot-path impurities
        if name in _LOCK_FACTORIES and not node.args:
            ff.impure.append(("lock-creation", node.lineno, f"{name}()"))
        elif name in ("get_env", "getenv", "_raw_env"):
            # _raw_env counts too: it IS an environ read (its own body is
            # policy-sanctioned, but a hot CALLER still pays the dict
            # lookup and must justify it)
            ff.impure.append(("env-read", node.lineno, f"{name}(...)"))
        elif name == "get" and isinstance(fn, ast.Attribute) and \
                _trailing_name(fn.value) == "environ":
            ff.impure.append(("env-read", node.lineno, "os.environ.get"))
        elif name in _LOG_METHODS and isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                "log" in fn.value.id.lower():
            ff.impure.append(("logging", node.lineno,
                              f"{fn.value.id}.{name}(...)"))
        elif name == "warn" and isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "warnings":
            ff.impure.append(("logging", node.lineno, "warnings.warn"))
        elif name == "print" and isinstance(fn, ast.Name):
            ff.impure.append(("logging", node.lineno, "print(...)"))
        elif isinstance(fn, ast.Attribute) and fn.attr in _NP_ALLOC and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in _NP_ALIASES:
            ff.impure.append(("allocation", node.lineno,
                              f"{fn.value.id}.{fn.attr}(...)"))
        # flow-tier facts: protocol releases (interprocedural "the
        # callee retired it" evidence), indefinitely-blocking calls
        # (reachable-under-lock search), thread lifecycle ops
        for proto_name in _proto.release_verbs(node):
            ff.proto_releases.setdefault(proto_name, node.lineno)
        blk = _proto.blocking_call(node)
        if blk is not None:
            ff.blocking.append((blk, node.lineno))
        if _proto.thread_start(node) and isinstance(fn, ast.Attribute):
            ff.thread_ops.append(
                ("start", _proto.call_desc(node)[0], node.lineno))
        else:
            retired = _proto.thread_retire(node)
            if retired is not None:
                ff.thread_ops.append(("retire", retired, node.lineno))
        # call edge
        desc = None
        if isinstance(fn, ast.Name):
            desc = ("name", fn.id)
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            if fn.value.id in ("self", "cls"):
                desc = ("self", fn.attr)
            else:
                desc = ("attr", fn.value.id, fn.attr)
        if desc is not None:
            ff.calls.append(CallSite(desc, node.lineno, self._host_tok(),
                                     self._scoped_held()))


class Project:
    """The repo-wide symbol table + call graph (pass-1 output)."""

    def __init__(self):
        self.modules: Dict[str, ModuleFacts] = {}
        self.functions: Dict[str, FuncFacts] = {}
        self.known_paths: Set[str] = set()
        self.lock_kinds: Dict[Tuple, str] = {}
        self._callee_cache: Dict[str, Tuple] = {}

    # -- resolution ---------------------------------------------------------
    def _class_method(self, rp: str, cls_name: str, meth: str,
                      depth: int = 0) -> Optional[str]:
        mod = self.modules.get(rp)
        if mod is None or depth > 3:
            return None
        cf = mod.classes.get(cls_name)
        if cf is None:
            # maybe the class name is an imported symbol
            tgt = mod.import_syms.get(cls_name)
            if tgt is not None:
                return self._class_method(tgt[0], tgt[1], meth, depth + 1)
            return None
        key = cf.methods.get(meth)
        if key is not None:
            return key
        for base in cf.bases:
            key = self._class_method(rp, base, meth, depth + 1)
            if key is not None:
                return key
        return None

    def _module_symbol(self, rp: str, name: str,
                       depth: int = 0) -> Optional[str]:
        """A callable symbol of module ``rp``: function key, or a class's
        ``__init__`` (constructor call).  ``depth`` bounds re-export
        chains — a two-module re-export CYCLE (a imports f from b, b
        from a) must dead-end, not recurse forever."""
        mod = self.modules.get(rp)
        if mod is None or depth > 3:
            return None
        key = mod.func_defs.get(name)
        if key is not None:
            return key
        cf = mod.classes.get(name)
        if cf is not None:
            return cf.methods.get("__init__")
        tgt = mod.import_syms.get(name)
        if tgt is not None and tgt[0] != rp:
            return self._module_symbol(tgt[0], tgt[1], depth + 1)
        return None

    def resolve(self, caller: FuncFacts, desc: Tuple) -> Optional[str]:
        """Conservative call-target resolution; None = no edge."""
        mod = self.modules.get(caller.relpath)
        if mod is None:
            return None
        kind = desc[0]
        if kind == "name":
            return self._module_symbol(caller.relpath, desc[1])
        if kind == "self":
            if caller.class_name is None:
                return None
            return self._class_method(caller.relpath, caller.class_name,
                                      desc[1])
        # ("attr", base, meth)
        base, meth = desc[1], desc[2]
        rp = mod.import_mods.get(base)
        if rp is not None:
            return self._module_symbol(rp, meth)
        if base in mod.classes:
            return self._class_method(caller.relpath, base, meth)
        tgt = mod.import_syms.get(base)
        if tgt is not None:
            # `from .engine import Engine; Engine.get()`
            return self._class_method(tgt[0], tgt[1], meth)
        return None

    def callees(self, key: str) -> Tuple:
        """Resolved ``(callee_key, CallSite)`` edges of one function."""
        cached = self._callee_cache.get(key)
        if cached is not None:
            return cached
        ff = self.functions.get(key)
        out: List[Tuple[str, CallSite]] = []
        if ff is not None:
            for cs in ff.calls:
                ck = self.resolve(ff, cs.desc)
                if ck is not None and ck in self.functions:
                    out.append((ck, cs))
        result = tuple(out)
        self._callee_cache[key] = result
        return result

    # -- bounded searches ---------------------------------------------------
    def find_collective(self, start: str, max_depth: int = MAX_CALL_DEPTH
                        ) -> Optional[Tuple[Tuple[str, ...], Tuple]]:
        """Shortest call chain from ``start`` to a function containing a
        collective call → (chain of keys incl. start, (name, line)), or
        None.  Cycle-safe, depth-bounded."""
        q = deque([(start, (start,))])
        seen = {start}
        while q:
            key, chain = q.popleft()
            ff = self.functions.get(key)
            if ff is not None and ff.collectives:
                name, line, _tok = ff.collectives[0]
                return chain, (name, line)
            if len(chain) > max_depth:
                continue
            for ck, _cs in self.callees(key):
                if ck not in seen:
                    seen.add(ck)
                    q.append((ck, chain + (ck,)))
        return None

    def find_acquires(self, start: str, max_depth: int = MAX_CALL_DEPTH
                      ) -> Dict[Tuple, Tuple[Tuple[str, ...], int]]:
        """Every class-/module-scoped lock token acquired in functions
        reachable from ``start`` (inclusive) within the depth bound →
        {token: (chain, line)} with the shortest chain per token."""
        out: Dict[Tuple, Tuple[Tuple[str, ...], int]] = {}
        q = deque([(start, (start,))])
        seen = {start}
        while q:
            key, chain = q.popleft()
            ff = self.functions.get(key)
            if ff is not None:
                for tok, line, _held in ff.acquires:
                    if tok[0] in ("cls", "mod") and tok not in out:
                        out[tok] = (chain, line)
            if len(chain) > max_depth:
                continue
            for ck, _cs in self.callees(key):
                if ck not in seen:
                    seen.add(ck)
                    q.append((ck, chain + (ck,)))
        return out

    def find_blocking(self, start: str, max_depth: int = MAX_CALL_DEPTH
                      ) -> Optional[Tuple[Tuple[str, ...],
                                          Tuple[str, int]]]:
        """Shortest call chain from ``start`` (inclusive) to a function
        containing an indefinitely-blocking call → (chain, (desc, line)),
        or None.  The blocking-under-lock rule walks this from every
        call site made while a lock is held."""
        q = deque([(start, (start,))])
        seen = {start}
        while q:
            key, chain = q.popleft()
            ff = self.functions.get(key)
            if ff is not None and ff.blocking:
                return chain, ff.blocking[0]
            if len(chain) > max_depth:
                continue
            for ck, _cs in self.callees(key):
                if ck not in seen:
                    seen.add(ck)
                    q.append((ck, chain + (ck,)))
        return None

    def find_release(self, start: str, proto_name: str,
                     max_depth: int = MAX_CALL_DEPTH
                     ) -> Optional[Tuple[Tuple[str, ...], int]]:
        """Shortest call chain from ``start`` (inclusive) to a function
        that performs a ``proto_name`` release → (chain, line), or None.
        Evidence-enrichment for ownership transfers: when a resource is
        handed to a resolvable callee, the leak rule cites the release
        the callee (transitively) performs."""
        q = deque([(start, (start,))])
        seen = {start}
        while q:
            key, chain = q.popleft()
            ff = self.functions.get(key)
            if ff is not None and proto_name in ff.proto_releases:
                return chain, ff.proto_releases[proto_name]
            if len(chain) > max_depth:
                continue
            for ck, _cs in self.callees(key):
                if ck not in seen:
                    seen.add(ck)
                    q.append((ck, chain + (ck,)))
        return None

    def reachable(self, roots: Iterable[str],
                  max_depth: int = MAX_CALL_DEPTH + 2
                  ) -> Dict[str, Tuple[str, ...]]:
        """{key: shortest chain from a root} for every function reachable
        from ``roots`` (roots included, chain = (root,))."""
        out: Dict[str, Tuple[str, ...]] = {}
        q = deque()
        for r in roots:
            if r in self.functions and r not in out:
                out[r] = (r,)
                q.append((r, (r,)))
        while q:
            key, chain = q.popleft()
            if len(chain) > max_depth:
                continue
            for ck, _cs in self.callees(key):
                if ck not in out:
                    out[ck] = chain + (ck,)
                    q.append((ck, chain + (ck,)))
        return out

    def hot_roots(self, kinds: Tuple[str, ...]) -> List[str]:
        return sorted(k for k, f in self.functions.items()
                      if f.hot_kind in kinds)

    # -- display ------------------------------------------------------------
    def pretty(self, key: str) -> str:
        ff = self.functions.get(key)
        if ff is None:
            return key
        return f"{ff.relpath}::{ff.qualname}"

    def chain_str(self, chain: Sequence[str]) -> str:
        return " -> ".join(self.pretty(k) for k in chain)


def build_project(items: Sequence[Tuple[str, ast.AST]]) -> Project:
    """Pass 1 over already-parsed trees: ``items`` is ``[(relpath,
    tree)]`` for every file in the lint scope."""
    proj = Project()
    proj.known_paths = {rp for rp, _tree in items}
    for rp, tree in items:
        _FactWalker(rp, proj).walk(tree)
    return proj
