"""mxlint core: one parse, one walk, many rules.

The framework contract (what makes this cheaper AND stronger than the
four copy-pasted AST walkers it replaces):

- **One ``ast.parse`` per file, one tree walk per file.**  Every rule
  subscribes to the node types it cares about (``interests``); the
  walker dispatches each node to each subscribed rule as it descends.
  Adding a rule costs a dict lookup per node, not another pass.
- **Shared lexical context.**  The walker maintains the stacks the
  interesting rules all need — enclosing classes, enclosing functions,
  held locks (``with self._lock:`` blocks), and enclosing ``if`` tests —
  so rules stay small and cannot disagree about scoping.
- **Per-line pragmas.**  ``# mxlint: disable=<rule>[,<rule>]`` on the
  finding's line (or on a standalone comment line directly above it)
  suppresses that rule there; ``disable=all`` suppresses everything.
  Pragmas are for *intentional* exceptions and should carry a
  justification comment; grandfathered debt goes in the baseline
  instead (see ``mxlint.baseline``).

Rules live in :mod:`.rules`; the runner, baseline handling, and CLI in
the package ``__init__``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "Rule", "FileContext", "run_rules", "pragma_map",
           "is_suppressed", "FUNC_TYPES"]

FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

_PRAGMA_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+)")


class Finding:
    """One rule violation at one source line.

    ``symbol`` is the enclosing function's qualified name (``Class.meth``
    or ``func``; ``None`` at module level) — it anchors the stable
    finding ``id`` (rule + path + symbol, deliberately NOT the line, so
    unrelated edits above a finding don't change its identity).
    ``reason`` is the interprocedural evidence chain: for a finding the
    analysis reached through the call graph, each entry is one hop
    (``"a.py::f -> b.py::g"`` style), ending at the fact that fired.
    ``hops`` is the flow-sensitive counterpart (PR 20): the *control-flow
    path* that exhibits the defect, as ``file:line`` program points from
    the acquire to the offending exit — what the CFG rules attach so a
    reader can replay the leaking path instead of taking the verdict on
    faith."""

    __slots__ = ("rule", "path", "line", "message", "symbol", "reason",
                 "hops")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 symbol: Optional[str] = None,
                 reason: Tuple[str, ...] = (),
                 hops: Tuple[str, ...] = ()):
        self.rule = rule
        self.path = path          # repo-relative, forward slashes
        self.line = line
        self.message = message
        self.symbol = symbol
        self.reason = tuple(reason)
        self.hops = tuple(hops)

    @property
    def id(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol or '<module>'}"

    def as_dict(self) -> dict:
        d = {"id": self.id, "rule": self.rule, "path": self.path,
             "line": self.line, "symbol": self.symbol,
             "message": self.message}
        if self.reason:
            d["reason"] = list(self.reason)
        if self.hops:
            d["hops"] = list(self.hops)
        return d

    def __repr__(self) -> str:
        base = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.reason:
            base += "\n    reason: " + " | ".join(self.reason)
        if self.hops:
            base += "\n    path: " + " -> ".join(self.hops)
        return base

    def __eq__(self, other) -> bool:
        return isinstance(other, Finding) and \
            (self.rule, self.path, self.line, self.message) == \
            (other.rule, other.path, other.line, other.message)

    def __hash__(self) -> int:
        return hash((self.rule, self.path, self.line, self.message))


class FileContext:
    """Per-file walk state shared by every rule.

    ``lock_stack`` holds one token per lock-ish context manager currently
    entered (``with self._lock:`` → ``("self", "_lock")``, ``with
    _env_lock:`` → ``("mod", "_env_lock")``); ``holds_lock()`` is the
    guard predicate the concurrency rules use.  ``if_stack`` holds the
    test expression of every enclosing ``if``/ternary branch (both arms
    — divergence is divergence).
    """

    def __init__(self, relpath: str, tree: ast.AST, source: str,
                 project=None):
        self.relpath = relpath
        self.tree = tree
        self.source = source
        self.project = project        # mxlint.graph.Project or None
        self.class_stack: List[ast.ClassDef] = []
        self.func_stack: List[ast.AST] = []
        self.lock_stack: List[Tuple[str, str]] = []
        self.if_stack: List[ast.expr] = []
        self.findings: List[Finding] = []

    # -- rule-facing helpers -------------------------------------------------
    def qualname(self) -> Optional[str]:
        """``Class.meth`` / ``func`` for the innermost enclosing def, or
        None at module level — the finding ``symbol`` anchor."""
        if not self.func_stack:
            return None
        name = self.func_stack[-1].name
        if self.class_stack:
            return f"{self.class_stack[-1].name}.{name}"
        return name

    def report(self, rule: "Rule", line: int, message: str,
               symbol: Optional[str] = None,
               reason: Tuple[str, ...] = (),
               hops: Tuple[str, ...] = ()) -> None:
        self.findings.append(Finding(
            rule.name, self.relpath, line, message,
            symbol=symbol if symbol is not None else self.qualname(),
            reason=reason, hops=hops))

    def current_class(self) -> Optional[ast.ClassDef]:
        return self.class_stack[-1] if self.class_stack else None

    def current_func(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    def holds_lock(self) -> bool:
        return bool(self.lock_stack)

    def at_body_level(self) -> bool:
        """True at module or class body level (not inside a function)."""
        return not self.func_stack


class Rule:
    """Base class for one lint rule.

    ``interests`` is the tuple of node types ``visit`` wants;
    ``skip_paths`` are repo-relative prefixes where the rule does not
    apply *by policy* (e.g. the metrics layer may own raw clocks) — as
    opposed to the baseline, which records grandfathered *debt*.
    """

    name = ""
    description = ""
    interests: Tuple = ()
    skip_paths: Tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        return not any(relpath.startswith(p) for p in self.skip_paths)

    def begin_file(self, ctx: FileContext) -> None:   # noqa: B027
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> None:  # noqa: B027
        pass

    def end_file(self, ctx: FileContext) -> None:     # noqa: B027
        pass

    def project_check(self, project) -> List[Finding]:
        """Interprocedural phase: called ONCE per lint run after every
        file has been walked, with the full :class:`mxlint.graph.Project`
        (symbol table + call graph).  Findings returned here go through
        the same pragma/baseline filtering as per-file findings."""
        return []


def _lock_token(expr: ast.expr) -> Optional[Tuple[str, str]]:
    """Lock token for a with-item context expression, or None.

    Anything named lock-ish counts: ``self._lock`` / ``cls._lock`` →
    scoped to the instance/class; a bare ``_some_lock`` name or a
    foreign attribute (``Engine._lock``) → ``("mod", name)``.
    """
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            return (base.id, expr.attr)
        return ("mod", expr.attr)
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return ("mod", expr.id)
    return None


def _acquire_release(stmt: ast.stmt) -> Optional[Tuple[str, Tuple[str, str]]]:
    """``lock.acquire()`` / ``lock.release()`` as a bare statement →
    ("acquire"|"release", lock token).  The explicit-pair form of a held
    region: the walker treats everything between the pair (including a
    ``try`` body whose ``finally`` releases) as lock-guarded, the same
    as a ``with`` block."""
    if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value, ast.Call):
        return None
    fn = stmt.value.func
    if not isinstance(fn, ast.Attribute) or \
            fn.attr not in ("acquire", "release"):
        return None
    tok = _lock_token(fn.value)
    if tok is None:
        return None
    return fn.attr, tok


def run_rules(ctx: FileContext, rules: Sequence[Rule]) -> List[Finding]:
    """Walk ``ctx.tree`` ONCE, dispatching nodes to every rule; returns
    the raw findings (pragma/baseline filtering is the runner's job)."""
    handlers: Dict[type, List[Rule]] = {}
    for r in rules:
        for t in r.interests:
            handlers.setdefault(t, []).append(r)
    for r in rules:
        r.begin_file(ctx)
    _visit(ctx, ctx.tree, handlers)
    for r in rules:
        r.end_file(ctx)
    return ctx.findings


def _visit(ctx: FileContext, node: ast.AST,
           handlers: Dict[type, List[Rule]]) -> None:
    for r in handlers.get(type(node), ()):
        r.visit(node, ctx)
    t = type(node)
    if t is ast.ClassDef:
        ctx.class_stack.append(node)
        depth = len(ctx.lock_stack)
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)
        del ctx.lock_stack[depth:]
        ctx.class_stack.pop()
    elif t in FUNC_TYPES:
        ctx.func_stack.append(node)
        # an unbalanced acquire() inside must not leak a held region
        # into the functions that follow
        depth = len(ctx.lock_stack)
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)
        del ctx.lock_stack[depth:]
        ctx.func_stack.pop()
    elif t is ast.Expr:
        # explicit lock.acquire()/lock.release() statements open/close a
        # held region exactly like a `with` block: statements between the
        # pair (sibling order — including a try body whose finally
        # releases) see the token on the lock stack
        ar = _acquire_release(node)
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)
        if ar is not None:
            kind, tok = ar
            if kind == "acquire":
                ctx.lock_stack.append(tok)
            elif tok in ctx.lock_stack:
                # remove the innermost matching hold
                for i in range(len(ctx.lock_stack) - 1, -1, -1):
                    if ctx.lock_stack[i] == tok:
                        del ctx.lock_stack[i]
                        break
    elif t in (ast.With, ast.AsyncWith):
        tokens = []
        for item in node.items:
            _visit(ctx, item.context_expr, handlers)
            if item.optional_vars is not None:
                _visit(ctx, item.optional_vars, handlers)
            tok = _lock_token(item.context_expr)
            if tok is not None:
                tokens.append(tok)
        ctx.lock_stack.extend(tokens)
        for stmt in node.body:
            _visit(ctx, stmt, handlers)
        if tokens:
            del ctx.lock_stack[-len(tokens):]
    elif t is ast.If:
        _visit(ctx, node.test, handlers)
        ctx.if_stack.append(node.test)
        # an acquire() inside one arm must not look held in the other
        # arm or after the If (the arms are mutually exclusive)
        depth = len(ctx.lock_stack)
        for stmt in node.body:
            _visit(ctx, stmt, handlers)
        del ctx.lock_stack[depth:]
        for stmt in node.orelse:
            _visit(ctx, stmt, handlers)
        del ctx.lock_stack[depth:]
        ctx.if_stack.pop()
    elif t is ast.IfExp:
        _visit(ctx, node.test, handlers)
        ctx.if_stack.append(node.test)
        _visit(ctx, node.body, handlers)
        _visit(ctx, node.orelse, handlers)
        ctx.if_stack.pop()
    else:
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)


# -- pragmas ----------------------------------------------------------------

def pragma_map(source: str) -> Dict[int, Set[str]]:
    """line number (1-based) → set of rule names disabled on that line."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _PRAGMA_RE.search(line)
        if m:
            names = {p.strip() for p in m.group(1).split(",") if p.strip()}
            if names:
                out[i] = names
    return out


def is_suppressed(finding: Finding, pragmas: Dict[int, Set[str]],
                  lines: Sequence[str]) -> bool:
    """Same-line pragma always counts; a pragma on the line directly
    above counts only when that line is a standalone comment (so a
    pragma for line N's statement can't leak onto line N+1's)."""
    names = pragmas.get(finding.line)
    if names and ("all" in names or finding.rule in names):
        return True
    prev = finding.line - 1
    names = pragmas.get(prev)
    if names and 1 <= prev <= len(lines) and \
            lines[prev - 1].lstrip().startswith("#") and \
            ("all" in names or finding.rule in names):
        return True
    return False
