"""mxlint core: one parse, one walk, many rules.

The framework contract (what makes this cheaper AND stronger than the
four copy-pasted AST walkers it replaces):

- **One ``ast.parse`` per file, one tree walk per file.**  Every rule
  subscribes to the node types it cares about (``interests``); the
  walker dispatches each node to each subscribed rule as it descends.
  Adding a rule costs a dict lookup per node, not another pass.
- **Shared lexical context.**  The walker maintains the stacks the
  interesting rules all need — enclosing classes, enclosing functions,
  held locks (``with self._lock:`` blocks), and enclosing ``if`` tests —
  so rules stay small and cannot disagree about scoping.
- **Per-line pragmas.**  ``# mxlint: disable=<rule>[,<rule>]`` on the
  finding's line (or on a standalone comment line directly above it)
  suppresses that rule there; ``disable=all`` suppresses everything.
  Pragmas are for *intentional* exceptions and should carry a
  justification comment; grandfathered debt goes in the baseline
  instead (see ``mxlint.baseline``).

Rules live in :mod:`.rules`; the runner, baseline handling, and CLI in
the package ``__init__``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "Rule", "FileContext", "run_rules", "pragma_map",
           "is_suppressed", "FUNC_TYPES"]

FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

_PRAGMA_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+)")


class Finding:
    """One rule violation at one source line."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path          # repo-relative, forward slashes
        self.line = line
        self.message = message

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __repr__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def __eq__(self, other) -> bool:
        return isinstance(other, Finding) and \
            (self.rule, self.path, self.line, self.message) == \
            (other.rule, other.path, other.line, other.message)

    def __hash__(self) -> int:
        return hash((self.rule, self.path, self.line, self.message))


class FileContext:
    """Per-file walk state shared by every rule.

    ``lock_stack`` holds one token per lock-ish context manager currently
    entered (``with self._lock:`` → ``("self", "_lock")``, ``with
    _env_lock:`` → ``("mod", "_env_lock")``); ``holds_lock()`` is the
    guard predicate the concurrency rules use.  ``if_stack`` holds the
    test expression of every enclosing ``if``/ternary branch (both arms
    — divergence is divergence).
    """

    def __init__(self, relpath: str, tree: ast.AST, source: str):
        self.relpath = relpath
        self.tree = tree
        self.source = source
        self.class_stack: List[ast.ClassDef] = []
        self.func_stack: List[ast.AST] = []
        self.lock_stack: List[Tuple[str, str]] = []
        self.if_stack: List[ast.expr] = []
        self.findings: List[Finding] = []

    # -- rule-facing helpers -------------------------------------------------
    def report(self, rule: "Rule", line: int, message: str) -> None:
        self.findings.append(Finding(rule.name, self.relpath, line, message))

    def current_class(self) -> Optional[ast.ClassDef]:
        return self.class_stack[-1] if self.class_stack else None

    def current_func(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    def holds_lock(self) -> bool:
        return bool(self.lock_stack)

    def at_body_level(self) -> bool:
        """True at module or class body level (not inside a function)."""
        return not self.func_stack


class Rule:
    """Base class for one lint rule.

    ``interests`` is the tuple of node types ``visit`` wants;
    ``skip_paths`` are repo-relative prefixes where the rule does not
    apply *by policy* (e.g. the metrics layer may own raw clocks) — as
    opposed to the baseline, which records grandfathered *debt*.
    """

    name = ""
    description = ""
    interests: Tuple = ()
    skip_paths: Tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        return not any(relpath.startswith(p) for p in self.skip_paths)

    def begin_file(self, ctx: FileContext) -> None:   # noqa: B027
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> None:  # noqa: B027
        pass

    def end_file(self, ctx: FileContext) -> None:     # noqa: B027
        pass


def _lock_token(expr: ast.expr) -> Optional[Tuple[str, str]]:
    """Lock token for a with-item context expression, or None.

    Anything named lock-ish counts: ``self._lock`` / ``cls._lock`` →
    scoped to the instance/class; a bare ``_some_lock`` name or a
    foreign attribute (``Engine._lock``) → ``("mod", name)``.
    """
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            return (base.id, expr.attr)
        return ("mod", expr.attr)
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return ("mod", expr.id)
    return None


def run_rules(ctx: FileContext, rules: Sequence[Rule]) -> List[Finding]:
    """Walk ``ctx.tree`` ONCE, dispatching nodes to every rule; returns
    the raw findings (pragma/baseline filtering is the runner's job)."""
    handlers: Dict[type, List[Rule]] = {}
    for r in rules:
        for t in r.interests:
            handlers.setdefault(t, []).append(r)
    for r in rules:
        r.begin_file(ctx)
    _visit(ctx, ctx.tree, handlers)
    for r in rules:
        r.end_file(ctx)
    return ctx.findings


def _visit(ctx: FileContext, node: ast.AST,
           handlers: Dict[type, List[Rule]]) -> None:
    for r in handlers.get(type(node), ()):
        r.visit(node, ctx)
    t = type(node)
    if t is ast.ClassDef:
        ctx.class_stack.append(node)
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)
        ctx.class_stack.pop()
    elif t in FUNC_TYPES:
        ctx.func_stack.append(node)
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)
        ctx.func_stack.pop()
    elif t in (ast.With, ast.AsyncWith):
        tokens = []
        for item in node.items:
            _visit(ctx, item.context_expr, handlers)
            if item.optional_vars is not None:
                _visit(ctx, item.optional_vars, handlers)
            tok = _lock_token(item.context_expr)
            if tok is not None:
                tokens.append(tok)
        ctx.lock_stack.extend(tokens)
        for stmt in node.body:
            _visit(ctx, stmt, handlers)
        if tokens:
            del ctx.lock_stack[-len(tokens):]
    elif t is ast.If:
        _visit(ctx, node.test, handlers)
        ctx.if_stack.append(node.test)
        for stmt in node.body:
            _visit(ctx, stmt, handlers)
        for stmt in node.orelse:
            _visit(ctx, stmt, handlers)
        ctx.if_stack.pop()
    elif t is ast.IfExp:
        _visit(ctx, node.test, handlers)
        ctx.if_stack.append(node.test)
        _visit(ctx, node.body, handlers)
        _visit(ctx, node.orelse, handlers)
        ctx.if_stack.pop()
    else:
        for child in ast.iter_child_nodes(node):
            _visit(ctx, child, handlers)


# -- pragmas ----------------------------------------------------------------

def pragma_map(source: str) -> Dict[int, Set[str]]:
    """line number (1-based) → set of rule names disabled on that line."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _PRAGMA_RE.search(line)
        if m:
            names = {p.strip() for p in m.group(1).split(",") if p.strip()}
            if names:
                out[i] = names
    return out


def is_suppressed(finding: Finding, pragmas: Dict[int, Set[str]],
                  lines: Sequence[str]) -> bool:
    """Same-line pragma always counts; a pragma on the line directly
    above counts only when that line is a standalone comment (so a
    pragma for line N's statement can't leak onto line N+1's)."""
    names = pragmas.get(finding.line)
    if names and ("all" in names or finding.rule in names):
        return True
    prev = finding.line - 1
    names = pragmas.get(prev)
    if names and 1 <= prev <= len(lines) and \
            lines[prev - 1].lstrip().startswith("#") and \
            ("all" in names or finding.rule in names):
        return True
    return False
