"""Declarative acquire→release protocol specs for the flow rules.

A :class:`Protocol` names one resource discipline this repo actually
uses and teaches the CFG layer to recognize its three verbs purely
syntactically (no imports resolved, same bar as the rest of mxlint):

- **acquire**: a call that mints the resource (``kv.reserve(...)``,
  ``tracer().begin(...)``, ``open(tmp_path, "w")``, ``var.set(...)``).
- **release**: a call that retires it (``kv.release(rid)``,
  ``span.finish()``, ``os.replace(tmp, final)``, ``var.reset(tok)``).
- **transfer**: structural, shared by all protocols — storing the bound
  name into ``self``/a subscript, returning/yielding it, or passing it
  to another call moves ownership out of the function, and the local
  path obligation ends (the interprocedural layer picks it up).

Matchers are receiver-hint based: ``reserve`` only counts on a receiver
whose name smells like a cache/pool (``kv``, ``_cache``, ``pool``…),
``begin`` only on a tracer, so a domain-specific verb on an unrelated
object stays silent.  Conservative in mxlint's usual direction — a
missed acquire is a missed finding, never a false one.

``ctx_managed=True`` marks protocols whose resource is its own context
manager (spans): an acquire used directly as a ``with`` item is safe by
construction and skipped.  The atomic-write protocol is deliberately
NOT ctx_managed — ``with open(tmp) as f`` closes the *handle*, but the
obligation is the rename/unlink of the *tmp file*, which outlives it.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

__all__ = ["Protocol", "PROTOCOLS", "match_acquire", "match_release",
           "release_verbs", "blocking_call", "thread_start",
           "thread_retire", "is_thread_ctor", "daemon_kwarg",
           "call_desc"]


def _rx(pat: str):
    return re.compile(pat, re.IGNORECASE)


class Protocol:
    __slots__ = ("name", "resource", "acquire_methods", "acquire_recv",
                 "release_methods", "release_recv", "ctx_managed",
                 "needs_binding", "hint")

    def __init__(self, name: str, resource: str, *,
                 acquire_methods: Tuple[str, ...],
                 acquire_recv: str,
                 release_methods: Tuple[str, ...],
                 release_recv: str = ".*",
                 ctx_managed: bool = False,
                 needs_binding: bool = False,
                 hint: str = ""):
        self.name = name
        self.resource = resource
        self.acquire_methods = acquire_methods
        self.acquire_recv = _rx(acquire_recv)
        self.release_methods = frozenset(release_methods)
        self.release_recv = _rx(release_recv)
        self.ctx_managed = ctx_managed
        # acquire only counts when its result is bound to a name —
        # kills ``gauge.set(v)`` / fire-and-forget lookalikes
        self.needs_binding = needs_binding
        self.hint = hint


#: receivers that look like locks — ``lock.release()`` belongs to
#: lock-discipline (PR 5), never to a resource protocol
_LOCKISH = _rx(r"lock|mutex|sem|cond|rlock")

PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol(
        "kv-block", "KV-cache block table",
        acquire_methods=("reserve",),
        acquire_recv=r"kv|cache|pool|block|_bkc",
        release_methods=("release", "free", "release_all"),
        hint="pair BlockKVCache.reserve() with release(req_id) on "
             "every exit, or hand the table to an owner",
    ),
    Protocol(
        "span", "tracing span",
        acquire_methods=("begin",),
        acquire_recv=r"tracer|tracing|trace",
        release_methods=("finish", "abandon"),
        ctx_managed=True,
        hint="finish() the span on every path (error paths included) "
             "or use it as a context manager",
    ),
    Protocol(
        "admission-slot", "admission-queue slot",
        acquire_methods=("take_slot", "acquire_slot"),
        acquire_recv=r"admission|_adm|queue|slots",
        release_methods=("settle", "release_slot", "settle_slot"),
        hint="settle the admission slot on every exit so shed "
             "accounting stays exact",
    ),
    Protocol(
        "atomic-write", "tmp file awaiting rename",
        acquire_methods=("open",),
        acquire_recv=r"^$",          # bare builtin open() only
        release_methods=("replace", "rename", "unlink", "remove"),
        release_recv=r"^os$|path",
        hint="a '.tmp' open() must reach os.replace()/unlink() on "
             "every path or a partial file survives the crash window",
    ),
    Protocol(
        "ctxvar-token", "contextvars reset token",
        acquire_methods=("set",),
        acquire_recv=r"var$|_active|ctx|current",
        release_methods=("reset",),
        needs_binding=True,
        hint="a ContextVar.set() token must reach .reset(token) or the "
             "stale value bleeds into the next task on this thread",
    ),
)

_TMPISH = _rx(r"\.tmp|\.part|tmp_|_tmp|temp")


def call_desc(call: ast.Call) -> Tuple[str, str]:
    """(receiver_text, method_name) for a call, '' when unnamed.
    ``a.b.c(x)`` -> ("a.b", "c"); ``f(x)`` -> ("", "f");
    ``tracer().begin(x)`` -> ("tracer()", "begin")."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return _expr_text(f.value), f.attr
    if isinstance(f, ast.Name):
        return "", f.id
    return "", ""


def _expr_text(e: ast.expr) -> str:
    if isinstance(e, ast.Name):
        return e.id
    if isinstance(e, ast.Attribute):
        base = _expr_text(e.value)
        return f"{base}.{e.attr}" if base else e.attr
    if isinstance(e, ast.Call):
        return _expr_text(e.func) + "()"
    return ""


def match_acquire(call: ast.Call) -> Optional[Protocol]:
    """The protocol this call acquires under, if any."""
    recv, meth = call_desc(call)
    for proto in PROTOCOLS:
        if meth not in proto.acquire_methods:
            continue
        if proto.name == "atomic-write":
            if recv:                         # only the builtin open()
                continue
            if not call.args or not _literalish_tmp(call.args[0]):
                continue
            return proto
        if recv and _LOCKISH.search(recv):
            continue
        if proto.acquire_recv.pattern == r"^$":
            if recv:
                continue
        elif not (recv and proto.acquire_recv.search(recv)):
            continue
        return proto
    return None


def _literalish_tmp(arg: ast.expr) -> bool:
    """Does the first open() argument look like a tmp path?  Matches
    string literals, f-strings, and names/attrs containing 'tmp'."""
    for node in ast.walk(arg):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _TMPISH.search(node.value):
                return True
        elif isinstance(node, (ast.Name, ast.Attribute)):
            txt = _expr_text(node)
            if txt and _TMPISH.search(txt):
                return True
    return False


def match_release(call: ast.Call, proto: Protocol) -> bool:
    """Is this call a release under ``proto``?  Receiver identity is
    NOT checked against the acquire receiver — mxlint tracks at most a
    couple of live resources per function, and a same-protocol release
    on any plausible receiver is accepted (missed-leak over false-leak)."""
    recv, meth = call_desc(call)
    if meth not in proto.release_methods:
        return False
    if recv and _LOCKISH.search(recv):
        return False
    if proto.release_recv.pattern != ".*":
        return bool(recv and proto.release_recv.search(recv))
    return True


def release_verbs(call: ast.Call) -> List[str]:
    """Protocol names this call releases under — pass-1 fact for the
    interprocedural transfer check ("the callee released it")."""
    out = []
    for proto in PROTOCOLS:
        if match_release(call, proto):
            out.append(proto.name)
    return out


# -- blocking-call matchers --------------------------------------------------

_QUEUEISH = _rx(r"queue|_q$|^q$|inbox|outbox|mailbox")
_SOCKISH = _rx(r"sock|conn|client|channel")


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def blocking_call(call: ast.Call) -> Optional[str]:
    """A human-readable description if this call can block indefinitely
    (the under-a-lock hazard set), else None.  Timeouts exonerate:
    ``q.get(timeout=...)``, ``t.join(0.5)``, ``cond.wait(0.1)`` pass."""
    recv, meth = call_desc(call)
    has_timeout = bool(call.args) or _kw(call, "timeout")
    if meth == "join" and not has_timeout:
        return "Thread.join() with no timeout"
    if meth in ("get", "put") and recv and _QUEUEISH.search(recv):
        if meth == "put" and (len(call.args) > 1 or _kw(call, "timeout")
                              or _kw(call, "block")):
            return None
        if meth == "get" and (call.args or _kw(call, "timeout")
                              or _kw(call, "block")):
            return None
        return f"queue.{meth}() with no timeout"
    if meth in ("recv", "recvfrom", "accept") and recv and \
            _SOCKISH.search(recv):
        return f"socket.{meth}()"
    if meth == "wait" and not has_timeout and recv and \
            not _LOCKISH.search(recv):
        # Event/Future-style wait; Condition.wait inside its own lock
        # is the *point* of a condition variable, so lockish is exempt
        return "wait() with no timeout"
    if meth == "result" and not has_timeout and recv:
        return "Future.result() with no timeout"
    return None


# -- thread lifecycle matchers -----------------------------------------------

def thread_start(call: ast.Call) -> bool:
    """``<x>.start()`` — the rule layer decides whether <x> is a
    Thread from the binding site."""
    _recv, meth = call_desc(call)
    return meth == "start" and not call.args and not call.keywords


_RETIRE_METHODS = frozenset(("join", "stop", "shutdown", "close",
                             "cancel", "terminate"))


def thread_retire(call: ast.Call) -> Optional[str]:
    """(receiver_text) when this call retires a thread-like object:
    ``t.join(...)``, ``t.stop()``, or an atexit registration mentioning
    it (``atexit.register(t.join)`` / ``threading._register_atexit``)."""
    recv, meth = call_desc(call)
    if meth in _RETIRE_METHODS and recv:
        return recv
    if meth in ("register", "_register_atexit") and call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Attribute):
            return _expr_text(a0.value)
        if isinstance(a0, ast.Name):
            return a0.id
    return None


def is_thread_ctor(value: ast.expr) -> bool:
    """Does this expression construct a thread?  ``Thread(...)``,
    ``threading.Thread(...)``, and repo wrappers whose class name ends
    in Thread/Worker (``_CommitThread(...)``)."""
    if not isinstance(value, ast.Call):
        return False
    _recv, name = call_desc(value)
    if not name:
        f = value.func
        name = f.attr if isinstance(f, ast.Attribute) else ""
    return name == "Thread" or name.endswith(("Thread", "Worker"))


def daemon_kwarg(value: ast.Call) -> Optional[bool]:
    """The ``daemon=`` literal on a thread ctor, if present."""
    for k in value.keywords:
        if k.arg == "daemon" and isinstance(k.value, ast.Constant):
            return bool(k.value.value)
    return None
